"""Throughput Test driver: S concurrent query streams.

TPU-native counterpart of the reference's `nds-throughput` wrapper
(reference: nds/nds-throughput:18-23 — `xargs -d ',' -P<S>` forking one
spark-submit Power Run per stream). Here the streams run as concurrent
threads over independent engine Sessions in ONE process, so the XLA compile
cache is shared across streams (the analogue of the reference's executors
sharing a warmed JVM) while each stream keeps its own catalog, reports, and
time log.

Ttt = max(stream end) - min(stream start), rounded UP to 0.1 s
(reference: nds/nds_bench.py:138-157, Spec 7.4.7.4).
"""

from __future__ import annotations

import csv
import math
import os
import threading
import time

from .power import gen_sql_from_stream, load_properties, run_query_stream


def round_up_to_nearest_10_percent(num: float) -> float:
    return math.ceil(num * 10) / 10


class _GateBroken(RuntimeError):
    """A stream's start-gate rendezvous failed (a sibling stream errored)."""


class _StartGate:
    """Aligned-start rendezvous for concurrent streams.

    All streams park in wait() and share one release timestamp (the barrier
    action runs in exactly one thread at trip time). Failure semantics:

    - a sibling erroring during setup calls abort() -> every parked wait()
      raises _GateBroken (the run fails with the root cause);
    - a PURE timeout (some stream is slow but nothing errored) degrades to
      ungated per-stream starts: each wait() returns its own clock instead
      of failing the whole run (the pre-gate behavior — a slow setup used
      to work, just unaligned, and must keep working).

    `timeout` defaults to the NDS_THROUGHPUT_GATE_TIMEOUT env knob
    (seconds, default 600)."""

    def __init__(self, n_streams: int, timeout: float = None):
        if timeout is None:
            timeout = float(
                os.environ.get("NDS_THROUGHPUT_GATE_TIMEOUT", "600")
            )
        self.timeout = timeout
        self._epoch = {}
        self._aborted = threading.Event()
        self._barrier = threading.Barrier(
            n_streams,
            action=lambda: self._epoch.__setitem__("t", time.time()),
        )

    def wait(self) -> float:
        try:
            self._barrier.wait(timeout=self.timeout)
        except threading.BrokenBarrierError:
            if self._aborted.is_set():
                raise _GateBroken(
                    "stream start gate broken: a sibling stream failed "
                    "during setup"
                ) from None
            # pure timeout: this (or a sibling's) wait outlived the budget
            # with no error anywhere — fall back to an ungated start. Say
            # so: Ttt loses its structural aligned-start guarantee here,
            # and the run output must make that auditable.
            import sys

            print(
                f"throughput start gate timed out after {self.timeout:.0f}s;"
                f" falling back to ungated per-stream starts",
                file=sys.stderr,
            )
            return time.time()
        return self._epoch["t"]

    def abort(self):
        self._aborted.set()
        self._barrier.abort()  # release siblings still parked at the gate


def _read_start_end(time_log_path: str):
    start = end = None
    with open(time_log_path) as f:
        for row in csv.reader(f):
            if len(row) >= 3 and row[1] == "Power Start Time":
                start = float(row[2])
            if len(row) >= 3 and row[1] == "Power End Time":
                end = float(row[2])
    if start is None or end is None:
        raise ValueError(f"{time_log_path}: missing Power Start/End Time rows")
    return start, end


def run_throughput(
    input_prefix,
    stream_paths: dict,
    time_log_base: str,
    input_format="parquet",
    use_decimal=True,
    property_file=None,
    json_summary_folder=None,
    output_path=None,
    output_format="parquet",
    mode="thread",
    sub_queries=None,
    gate_timeout=None,
    query_timeout=None,
):
    """Run the streams in `stream_paths` ({stream_num: stream_file})
    concurrently; write `<time_log_base>_<n>.csv` per stream; return Ttt
    seconds (rounded up to 0.1 s).

    mode="thread" (default): streams are threads over independent Sessions
    in this process — device dispatches release the GIL, so streams overlap
    on device/IO work while sharing one warmed in-process compile cache.
    mode="process": forks one Power Run process per stream (the reference's
    `xargs -P` shape, nds/nds-throughput:18-23); processes share compiled
    kernels through the persistent XLA cache instead."""
    if mode == "process":
        return _run_throughput_processes(
            input_prefix, stream_paths, time_log_base, input_format,
            use_decimal, property_file, json_summary_folder, output_path,
            output_format, sub_queries, query_timeout,
        )
    errors = {}
    # All streams rendezvous after table setup, before their Power clocks
    # start (see _StartGate): overlap of the [start, end] windows is then
    # structural, immune to the 1-core host scheduling one thread's first
    # query before another thread gets to read its own clock. A stream that
    # errors before reaching the gate aborts it for everyone rather than
    # deadlocking the rest; a pure timeout degrades to ungated starts.
    gate = _StartGate(len(stream_paths), timeout=gate_timeout)

    def one_stream(n, path):
        try:
            queries = gen_sql_from_stream(path)
            if sub_queries:
                from .power import get_query_subset

                queries = get_query_subset(queries, sub_queries)
            run_query_stream(
                input_prefix,
                property_file,
                queries,
                f"{time_log_base}_{n}.csv",
                input_format=input_format,
                use_decimal=use_decimal,
                # per-stream subfolder: the shared-folder emptiness check
                # would race between concurrent streams (summary filenames
                # carry the stream's app id, but the check itself doesn't)
                json_summary_folder=(
                    os.path.join(json_summary_folder, f"stream_{n}")
                    if json_summary_folder
                    else None
                ),
                output_path=(
                    f"{output_path}_{n}" if output_path else None
                ),
                output_format=output_format,
                start_gate=gate.wait,
                query_timeout=query_timeout,
            )
        except Exception as exc:
            errors[n] = exc
            gate.abort()

    threads = [
        threading.Thread(target=one_stream, args=(n, p), name=f"stream-{n}")
        for n, p in sorted(stream_paths.items())
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        # a pre-gate failure aborts the barrier, flooding every sibling
        # with gate-broken errors; report only the root cause(s) unless the
        # gate itself was the problem (pure timeout)
        real = {
            n: e for n, e in errors.items() if not isinstance(e, _GateBroken)
        }
        raise RuntimeError(f"throughput streams failed: {real or errors}")
    return _ttt_from_logs(stream_paths, time_log_base)


def _ttt_from_logs(streams, time_log_base) -> float:
    """Ttt = max(stream end) - min(stream start), rounded up to 0.1 s.

    `streams` is any iterable of stream numbers. Floored at 0.1 s: the time
    log's int-second timestamps truncate a sub-second run to 0, and Ttt
    feeds the composite metric's denominator (nds/nds_bench.py:334-357)
    where 0 would poison the whole score."""
    starts, ends = [], []
    for n in streams:
        s, e = _read_start_end(f"{time_log_base}_{n}.csv")
        starts.append(s)
        ends.append(e)
    return max(round_up_to_nearest_10_percent(max(ends) - min(starts)), 0.1)


def stream_wait_budget(query_timeout=None, n_queries: int = 103):
    """Per-child wall-clock budget (seconds) for process-mode streams, or
    None for unbounded. NDS_STREAM_TIMEOUT wins; else it derives from the
    per-query watchdog budget (engine-side NDS_QUERY_TIMEOUT) times a full
    stream's statement count plus setup slack — a child that blows through
    every per-query watchdog AND this outer budget is declared hung."""
    v = os.environ.get("NDS_STREAM_TIMEOUT")
    if v:
        return float(v) or None
    qt = query_timeout or os.environ.get("NDS_QUERY_TIMEOUT")
    if qt:
        return float(qt) * n_queries + 600
    return None


def _fold_child_streams(tracer, trace_dir, pre_existing, launches):
    """Fold the event files the child-stream processes wrote into the
    parent's own event log: one `child_stream` summary event per stream,
    plus a best-effort failure classification per stream (the parent only
    sees an exit code; the child's events say WHY it died). A child that
    rotated (engine.trace_rotate_bytes) leaves a SEGMENT CHAIN; discovery
    returns it in rotation order (obs.reader.segment_key) and the filter
    below preserves that order, so the summary and the classification
    read the child's whole stream in emission order.

    Attribution is by TRACE CONTEXT, not pid: each file's `trace_meta`
    line is verified against the stream's LAUNCH RECORD — the trace_id
    the parent minted and exported (NDS_TRACE_CONTEXT) when authoritative,
    else pid PLUS emission-time >= launch time. A recycled pid's leftover
    file from some long-dead process can no longer mis-blame this run's
    stream (the historical `-<pid>-` filename match trusted the pid
    alone). `launches` is {stream_num: {"pid", "ts_ms", "trace_id"}}.
    Returns {stream_num: failure_kind} for streams whose events record a
    failure."""
    from .obs import reader as obs_reader

    kinds = {}
    new = [
        f
        for f in obs_reader.discover_event_files(trace_dir)
        if f not in pre_existing
    ]
    metas = {f: obs_reader.trace_meta_of(f) for f in new}
    for n, rec in sorted(launches.items()):
        mine = [
            f for f in new
            if (
                obs_reader.meta_matches_launch(
                    metas[f], pid=rec.get("pid"),
                    launch_ts_ms=rec.get("ts_ms"),
                    trace_id=rec.get("trace_id"),
                )
                # a NEW file with an unreadable/missing meta line (child
                # killed before the eager meta landed, or its first line
                # torn): keep the OLD pid-filename evidence so an
                # instant death still yields its queries=0 marker — only
                # files whose meta READS and mismatches are rejected
                or (
                    metas[f] is None
                    and f"-{rec.get('pid')}-" in os.path.basename(f)
                )
            )
        ]
        if not mine:
            continue
        try:
            events = obs_reader.read_events(mine, strict=False)
        except OSError as exc:
            # observability must never take the benchmark down: an
            # unreadable child file still leaves a fold-in marker
            tracer.emit(
                "child_stream", stream=n,
                files=[os.path.basename(f) for f in mine],
                queries=0, completed=0, failed={}, failure_kinds=[],
                error=str(exc)[:200],
                child_trace_id=rec.get("trace_id"),
            )
            continue
        s = obs_reader.summarize_stream(events)
        tracer.emit(
            "child_stream",
            stream=n,
            files=[os.path.basename(f) for f in mine],
            queries=s["queries"],
            completed=s["completed"],
            failed=s["failed"],
            failure_kinds=s["failure_kinds"],
            child_trace_id=rec.get("trace_id"),
        )
        k = obs_reader.failure_kind_from_events(events)
        if k is not None:
            kinds[n] = k
    return kinds


def _run_throughput_processes(
    input_prefix, stream_paths, time_log_base, input_format, use_decimal,
    property_file, json_summary_folder, output_path, output_format,
    sub_queries=None, query_timeout=None,
):
    """One `nds_tpu.cli.power` subprocess per stream, all concurrent.

    With NDS_TRACE_DIR set each child writes its own event file; the
    parent discovers them afterwards, folds per-stream summaries into its
    own event log, and uses the child's events to classify a nonzero exit
    (the ROADMAP "classify subprocess phase failures from their logs" gap)."""
    import subprocess
    import sys

    from .obs import reader as obs_reader
    from .obs import trace as obs_trace

    # resolve the trace dir the way the children will (conf tier from the
    # property file, env fallback): a conf-only engine.trace_dir must not
    # silently disable the parent's fold-in/classification half
    conf = load_properties(property_file) if property_file else None
    trace_dir = obs_trace.resolve_trace_dir(conf)
    tracer = obs_trace.tracer_from_conf(conf)
    # parent context: the children's trace_ids parent to it, so a folded
    # log reads as one run even across the process boundary
    parent_ctx = (
        getattr(tracer, "context", None)
        or obs_trace.resolve_trace_context("throughput")
    )
    pre_existing = set(obs_reader.discover_event_files(trace_dir))
    procs = {}
    launches = {}  # stream -> {"pid", "ts_ms", "trace_id"} (fold-in key)
    failures = {}
    try:
        for n, path in sorted(stream_paths.items()):
            cmd = [
                sys.executable, "-m", "nds_tpu.cli.power",
                input_prefix, path, f"{time_log_base}_{n}.csv",
                "--input_format", input_format,
                "--output_format", output_format,
            ]
            if not use_decimal:
                cmd.append("--floats")
            if property_file:
                cmd += ["--property_file", property_file]
            if query_timeout:
                cmd += ["--query_timeout", str(query_timeout)]
            if json_summary_folder:
                cmd += [
                    "--json_summary_folder",
                    os.path.join(json_summary_folder, f"stream_{n}"),
                ]
            if output_path:
                cmd += ["--output_prefix", f"{output_path}_{n}"]
            if sub_queries:
                cmd += ["--sub_queries", ",".join(sub_queries)]
            # each child logs to its own file: a shared PIPE read
            # sequentially would block a chatty stream on pipe backpressure
            # mid-benchmark, stretching its time window and corrupting Ttt.
            # Append-style live log, not a parsed artifact — a torn final
            # line is expected crash evidence, so no atomic rename here
            # nds-lint: disable=atomic-write
            logf = open(f"{time_log_base}_{n}.out", "w")
            # per-child trace context: the child ADOPTS this exact
            # trace_id (tracer_from_conf reads NDS_TRACE_CONTEXT), so the
            # parent folds its event files by trace_id instead of pid
            ctx = parent_ctx.child(f"stream{n}")
            env = ctx.export(dict(os.environ))
            try:
                p = subprocess.Popen(
                    cmd, stdout=logf, stderr=subprocess.STDOUT, env=env,
                )
            except BaseException:
                logf.close()
                raise
            procs[n] = (p, logf)
            launches[n] = {
                "pid": p.pid,
                "ts_ms": int(time.time() * 1000),
                "trace_id": ctx.trace_id,
            }
        budget = stream_wait_budget(
            query_timeout, len(sub_queries) if sub_queries else 103
        )
        for n, (p, logf) in procs.items():
            try:
                p.wait(timeout=budget)
            except subprocess.TimeoutExpired:
                # the watchdog budget is exhausted: a hung child must not
                # stall the whole Throughput Test forever
                p.kill()
                p.wait()
                failures[n] = (
                    f"stream {n} exceeded the {budget:.0f}s watchdog "
                    f"budget (NDS_STREAM_TIMEOUT / NDS_QUERY_TIMEOUT) "
                    f"and was killed"
                )
                continue
            finally:
                logf.close()
            if p.returncode != 0:
                with open(f"{time_log_base}_{n}.out") as f:
                    failures[n] = f.read()[-2000:]
    finally:
        # a Popen failure (or any error above) must not leak children or
        # their log handles
        for n, (p, logf) in procs.items():
            if p.poll() is None:
                p.kill()
                p.wait()
            logf.close()
    if tracer is not None:
        try:
            child_kinds = _fold_child_streams(
                tracer, trace_dir, pre_existing, launches
            )
            for n, kind in child_kinds.items():
                if n in failures:
                    failures[n] = (
                        f"[classified {kind} from the stream's event log] "
                        f"{failures[n]}"
                    )
        finally:
            tracer.close()
    if failures:
        raise RuntimeError(f"throughput stream processes failed: {failures}")
    return _ttt_from_logs(stream_paths, time_log_base)
