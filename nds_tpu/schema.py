"""Schema registry for all TPC-DS source and maintenance tables.

TPU-native counterpart of the reference schema registry
(reference: nds/nds_schema.py — `get_schemas` :49-562, `get_maintenance_schemas`
:564-710, decimal/double switch :43-47). Schemas are declared as compact spec
strings in `_schema_data.py` and materialized here into typed `Schema` objects
with Arrow conversion for the IO layer.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import pyarrow as pa

from . import _schema_data
from .dtypes import DType, parse_dtype


@dataclass(frozen=True)
class Field:
    name: str
    dtype: DType
    nullable: bool = True


@dataclass(frozen=True)
class Schema:
    fields: tuple
    _index: dict = field(default_factory=dict, compare=False, repr=False)

    def __post_init__(self):
        object.__setattr__(self, "_index", {f.name: i for i, f in enumerate(self.fields)})

    @property
    def names(self):
        return [f.name for f in self.fields]

    def __len__(self):
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __contains__(self, name):
        return name in self._index

    def field(self, name: str) -> Field:
        return self.fields[self._index[name]]

    def to_arrow(self, use_decimal: bool = True) -> pa.Schema:
        return pa.schema(
            [pa.field(f.name, f.dtype.to_arrow(use_decimal), f.nullable) for f in self.fields]
        )


def _parse_table(spec: str) -> Schema:
    fields = []
    for line in spec.strip().splitlines():
        parts = line.split()
        if not parts:
            continue
        name, dtype = parts[0], parse_dtype(parts[1])
        nullable = "!" not in parts[2:]
        fields.append(Field(name, dtype, nullable))
    return Schema(tuple(fields))


def _float_mode(schema: Schema) -> Schema:
    """decimal -> float64, matching the reference's use_decimal=False mode."""
    return Schema(
        tuple(
            Field(f.name, DType("float64") if f.dtype.is_decimal else f.dtype, f.nullable)
            for f in schema.fields
        )
    )


_SOURCE = {name: _parse_table(spec) for name, spec in _schema_data.SOURCE_TABLES.items()}
_MAINT = {name: _parse_table(spec) for name, spec in _schema_data.MAINTENANCE_TABLES.items()}


def get_schemas(use_decimal: bool = True) -> dict:
    """All 24 source-table schemas. use_decimal=False maps decimal->float64."""
    if use_decimal:
        return dict(_SOURCE)
    return {name: _float_mode(s) for name, s in _SOURCE.items()}


def get_maintenance_schemas(use_decimal: bool = True) -> dict:
    """The 12 refresh/staging table schemas used by Data Maintenance."""
    if use_decimal:
        return dict(_MAINT)
    return {name: _float_mode(s) for name, s in _MAINT.items()}


# Fact tables partitioned on write, and their partition column
# (parity: nds/nds_transcode.py:45-53 TABLE_PARTITIONING).
TABLE_PARTITIONING = {
    "catalog_sales": "cs_sold_date_sk",
    "catalog_returns": "cr_returned_date_sk",
    "inventory": "inv_date_sk",
    "store_sales": "ss_sold_date_sk",
    "store_returns": "sr_returned_date_sk",
    "web_sales": "ws_sold_date_sk",
    "web_returns": "wr_returned_date_sk",
}


# Declared primary keys (TPC-DS spec table definitions; fact PKs are
# composite). The engine's catalog attaches these as Table.unique_key so
# probe-style joins can skip runtime uniqueness checks; the data generator
# enforces them (distinct items per ticket/order — tests/test_datagen.py).
TABLE_PRIMARY_KEYS = {
    "store_sales": ("ss_item_sk", "ss_ticket_number"),
    "store_returns": ("sr_item_sk", "sr_ticket_number"),
    "catalog_sales": ("cs_item_sk", "cs_order_number"),
    "catalog_returns": ("cr_item_sk", "cr_order_number"),
    "web_sales": ("ws_item_sk", "ws_order_number"),
    "web_returns": ("wr_item_sk", "wr_order_number"),
    "inventory": ("inv_date_sk", "inv_item_sk", "inv_warehouse_sk"),
    "store": ("s_store_sk",),
    "call_center": ("cc_call_center_sk",),
    "catalog_page": ("cp_catalog_page_sk",),
    "web_site": ("web_site_sk",),
    "web_page": ("wp_web_page_sk",),
    "warehouse": ("w_warehouse_sk",),
    "customer": ("c_customer_sk",),
    "customer_address": ("ca_address_sk",),
    "customer_demographics": ("cd_demo_sk",),
    "date_dim": ("d_date_sk",),
    "household_demographics": ("hd_demo_sk",),
    "income_band": ("ib_income_band_sk",),
    "item": ("i_item_sk",),
    "promotion": ("p_promo_sk",),
    "reason": ("r_reason_sk",),
    "ship_mode": ("sm_ship_mode_sk",),
    "time_dim": ("t_time_sk",),
}


if __name__ == "__main__":
    for tname, schema in {**get_schemas(), **get_maintenance_schemas()}.items():
        print(f"{tname}: {len(schema)} columns")
