"""Schema registry for all TPC-DS source and maintenance tables.

TPU-native counterpart of the reference schema registry
(reference: nds/nds_schema.py — `get_schemas` :49-562, `get_maintenance_schemas`
:564-710, decimal/double switch :43-47). Schemas are declared as compact spec
strings in `_schema_data.py` and materialized here into typed `Schema` objects
with Arrow conversion for the IO layer.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import pyarrow as pa

from . import _schema_data
from .dtypes import DType, parse_dtype


@dataclass(frozen=True)
class Field:
    name: str
    dtype: DType
    nullable: bool = True


@dataclass(frozen=True)
class Schema:
    fields: tuple
    _index: dict = field(default_factory=dict, compare=False, repr=False)

    def __post_init__(self):
        object.__setattr__(self, "_index", {f.name: i for i, f in enumerate(self.fields)})

    @property
    def names(self):
        return [f.name for f in self.fields]

    def __len__(self):
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __contains__(self, name):
        return name in self._index

    def field(self, name: str) -> Field:
        return self.fields[self._index[name]]

    def to_arrow(self, use_decimal: bool = True) -> pa.Schema:
        return pa.schema(
            [pa.field(f.name, f.dtype.to_arrow(use_decimal), f.nullable) for f in self.fields]
        )


def _parse_table(spec: str) -> Schema:
    fields = []
    for line in spec.strip().splitlines():
        parts = line.split()
        if not parts:
            continue
        name, dtype = parts[0], parse_dtype(parts[1])
        nullable = "!" not in parts[2:]
        fields.append(Field(name, dtype, nullable))
    return Schema(tuple(fields))


def _float_mode(schema: Schema) -> Schema:
    """decimal -> float64, matching the reference's use_decimal=False mode."""
    return Schema(
        tuple(
            Field(f.name, DType("float64") if f.dtype.is_decimal else f.dtype, f.nullable)
            for f in schema.fields
        )
    )


_SOURCE = {name: _parse_table(spec) for name, spec in _schema_data.SOURCE_TABLES.items()}
_MAINT = {name: _parse_table(spec) for name, spec in _schema_data.MAINTENANCE_TABLES.items()}


def get_schemas(use_decimal: bool = True) -> dict:
    """All 24 source-table schemas. use_decimal=False maps decimal->float64."""
    if use_decimal:
        return dict(_SOURCE)
    return {name: _float_mode(s) for name, s in _SOURCE.items()}


def get_maintenance_schemas(use_decimal: bool = True) -> dict:
    """The 12 refresh/staging table schemas used by Data Maintenance."""
    if use_decimal:
        return dict(_MAINT)
    return {name: _float_mode(s) for name, s in _MAINT.items()}


# Fact tables partitioned on write, and their partition column
# (parity: nds/nds_transcode.py:45-53 TABLE_PARTITIONING).
TABLE_PARTITIONING = {
    "catalog_sales": "cs_sold_date_sk",
    "catalog_returns": "cr_returned_date_sk",
    "inventory": "inv_date_sk",
    "store_sales": "ss_sold_date_sk",
    "store_returns": "sr_returned_date_sk",
    "web_sales": "ws_sold_date_sk",
    "web_returns": "wr_returned_date_sk",
}


if __name__ == "__main__":
    for tname, schema in {**get_schemas(), **get_maintenance_schemas()}.items():
        print(f"{tname}: {len(schema)} columns")
