"""CLI: `nds-tpu-submit lint` — run the engine lint over nds_tpu/.

Exits non-zero on any finding; see nds_tpu/analysis/lint.py for the rule
table and the `# nds-lint: disable=<rule>` pragma syntax. The static half
of the CI gate next to `profile --check` (runtime event validation) and
tools/plan_verify_corpus.py (plan-IR verification of all 99 templates).
"""

from __future__ import annotations

import sys

from ..analysis.lint import main

if __name__ == "__main__":
    sys.exit(main())
