"""Transcode / Load Test CLI (reference: nds/nds_transcode.py __main__ :218-290).

    python -m nds_tpu.cli.transcode <input_prefix> <output_prefix> <report_file>
        [--output_format parquet|csv] [--output_mode overwrite|...]
        [--tables t1,t2] [--floats] [--update] [--compression codec]
        [--workers N] [--resume]
"""

import argparse
import os

from ..check import check_version
from ..transcode import transcode


def main(argv=None):
    check_version()
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "input_prefix", help="text to prepend to every input file path"
    )
    parser.add_argument(
        "output_prefix", help="text to prepend to every output file path"
    )
    parser.add_argument(
        "report_file", help="location to store the performance report (local)"
    )
    parser.add_argument(
        "--output_mode",
        choices=["overwrite", "append", "ignore", "error", "errorifexists"],
        default="errorifexists",
        help="behavior when the output table directory already exists",
    )
    parser.add_argument(
        "--output_format",
        choices=["parquet", "csv", "orc", "json", "avro", "lakehouse"],
        default="parquet",
        help="output data format when converting CSV data sources",
    )
    parser.add_argument(
        "--tables",
        type=lambda s: s.split(","),
        help="comma separated table names, e.g. 'catalog_page,catalog_sales'",
    )
    parser.add_argument(
        "--floats",
        action="store_true",
        help="replace decimal with double when saving files",
    )
    parser.add_argument(
        "--update",
        action="store_true",
        help="transcode the maintenance/refresh data instead of source data",
    )
    parser.add_argument(
        "--compression",
        help="compression codec, e.g. snappy (default), zstd, gzip, none",
    )
    parser.add_argument(
        "--workers",
        type=int,
        default=int(os.environ.get("NDS_TRANSCODE_WORKERS", "1")),
        help="decode worker processes for lakehouse ingest "
             "(default NDS_TRANSCODE_WORKERS or 1; other formats ignore it)",
    )
    parser.add_argument(
        "--resume",
        action="store_true",
        help="lakehouse only: continue a killed ingest — replay chunks "
             "missing from the manifest's ingest ledger, skip the rest",
    )
    args = parser.parse_args(argv)
    transcode(args)


if __name__ == "__main__":
    main()
