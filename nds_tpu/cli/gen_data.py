"""Data-generation CLI.

Counterpart of the reference's generator driver (reference:
nds/nds_gen_data.py — generate_data_local :183-244, generate_data_hdfs
:130-180, merge/move helpers :85-127). Local mode fans out one ndsgen
process per chunk; cluster mode fans chunks across hosts over ssh onto a
shared filesystem — replacing the reference's Hadoop-MapReduce wrapper
(reference: nds/tpcds-gen/.../GenTable.java:188-209) with direct process
fan-out, which is the natural shape on TPU pod host VMs.

Output layout (identical to the reference's):
  data_dir/<table>/<table>_<child>_<parallel>.dat
"""

from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from nds_tpu import check
from nds_tpu.schema import get_schemas, get_maintenance_schemas

SOURCE_TABLE_NAMES = sorted(get_schemas().keys())
MAINTENANCE_TABLE_NAMES = sorted(get_maintenance_schemas().keys())


def _chunk_cmds(binary, args, children):
    cmds = []
    for i in children:
        cmd = [binary, "-scale", str(args.scale), "-dir", args.data_dir,
               "-parallel", str(args.parallel), "-child", str(i), "-seed", str(args.seed)]
        if args.update:
            cmd += ["-update", str(args.update)]
        if args.table:
            cmd += ["-table", args.table]
        cmds.append(cmd)
    return cmds


def _layout_tables(args, children):
    """Move chunk files into per-table subdirectories."""
    names = MAINTENANCE_TABLE_NAMES if args.update else SOURCE_TABLE_NAMES
    for table in names:
        table_dir = os.path.join(args.data_dir, table)
        os.makedirs(table_dir, exist_ok=True)
        for i in children:
            src = os.path.join(args.data_dir, f"{table}_{i}_{args.parallel}.dat")
            if os.path.exists(src):
                shutil.move(src, table_dir)


def _guard_output_dir(args):
    """Refuse to mix chunk sets: non-empty target needs --overwrite_output,
    and a full (non --range) rerun wipes stale content first."""
    os.makedirs(args.data_dir, exist_ok=True)
    if check.get_dir_size(args.data_dir) > 0:
        if not args.overwrite_output:
            raise Exception(
                f"There's already data in {args.data_dir}. Use '--overwrite_output' to overwrite.")
        if not args.range:
            for entry in os.listdir(args.data_dir):
                path = os.path.join(args.data_dir, entry)
                shutil.rmtree(path) if os.path.isdir(path) else os.remove(path)


def _wait_all(procs, what):
    """Wait for every child before raising, so a failed chunk can't leave
    siblings racing a subsequent --overwrite_output rerun."""
    failed = []
    for p in procs:
        p.wait()
        if p.returncode != 0:
            failed.append(p.returncode)
    if failed:
        raise Exception(f"{what} failed with return code(s) {failed}")


def _write_dbgen_version(args):
    """One-row version/audit table (reference: dsdgen emits dbgen_version,
    moved into place by nds_gen_data.py:50-51). Not emitted for refresh
    (--update) sets, matching the reference's source-table list."""
    if args.update:
        return
    import datetime

    now = datetime.datetime.now()
    d = os.path.join(args.data_dir, "dbgen_version")
    os.makedirs(d, exist_ok=True)
    cmdline = f"-scale {args.scale} -parallel {args.parallel}"
    row = (
        f"1.0.0|{now:%Y-%m-%d}|{now:%H:%M:%S}|{cmdline}|\n"
    )
    with open(os.path.join(d, "dbgen_version_1_1.dat"), "w") as f:
        f.write(row)


def generate_data_local(args, children):
    binary = check.check_build()
    _guard_output_dir(args)
    procs = [subprocess.Popen(cmd) for cmd in _chunk_cmds(binary, args, children)]
    _wait_all(procs, "ndsgen")
    _layout_tables(args, children)
    _write_dbgen_version(args)
    subprocess.run(["du", "-h", "-d1", args.data_dir])


def _spawn_on_host(host, cmd):
    """Launch one chunk command, locally or through ssh. Split out so tests
    can observe/replace the launch mechanism without a real cluster."""
    if host in ("localhost", "127.0.0.1"):
        return subprocess.Popen(cmd)
    return subprocess.Popen(["ssh", host] + cmd)


def generate_data_cluster(args, children):
    """Fan chunks across hosts over ssh; every host writes to the shared
    data_dir (NFS/GCS-fuse). Hosts file: one hostname per line.

    A chunk whose process exits non-zero (host down, ssh hiccup, OOM) is
    retried up to --retries times, each attempt rotated to the next host in
    the list so a single dead host can't wedge the run — the elastic-recovery
    counterpart of MapReduce task retries in the reference's Hadoop wrapper
    (reference: nds/tpcds-gen/.../GenTable.java:140-167, where MR re-executes
    failed map tasks)."""
    binary = check.check_build()
    with open(args.hosts) as f:
        hosts = [h.strip() for h in f if h.strip() and not h.strip().startswith("#")]
    if not hosts:
        raise Exception(f"no hosts in {args.hosts}")
    _guard_output_dir(args)
    # pending: chunk index (within this run's command list) -> attempt count
    cmds = _chunk_cmds(binary, args, children)
    attempts = {n: 0 for n in range(len(cmds))}
    pending = list(attempts)
    while pending:
        procs = {}
        for n in pending:
            host = hosts[(n + attempts[n]) % len(hosts)]
            attempts[n] += 1
            procs[n] = (host, _spawn_on_host(host, cmds[n]))
        failed = []
        for n, (host, p) in procs.items():
            p.wait()
            if p.returncode != 0:
                failed.append((n, host, p.returncode))
        pending = []
        for n, host, rc in failed:
            if attempts[n] <= args.retries:
                print(f"chunk {n + 1}/{len(cmds)} failed on {host} "
                      f"(rc={rc}); retry {attempts[n]}/{args.retries}",
                      file=sys.stderr)
                pending.append(n)
            else:
                raise Exception(
                    f"chunk {n + 1}/{len(cmds)} failed on {host} (rc={rc}) "
                    f"after {args.retries} retries")
    _layout_tables(args, children)
    _write_dbgen_version(args)


def generate_data(args):
    check.check_version()
    if args.table:
        valid = set(MAINTENANCE_TABLE_NAMES if args.update else SOURCE_TABLE_NAMES)
        if args.table not in valid:
            raise Exception(f"unknown table {args.table!r}; expected one of {sorted(valid)}")
    range_start, range_end = 1, args.parallel
    if args.range:
        range_start, range_end = check.valid_range(args.range, args.parallel)
    children = range(range_start, range_end + 1)
    if args.type == "local":
        generate_data_local(args, children)
    else:
        generate_data_cluster(args, children)


def main(argv=None):
    parser = argparse.ArgumentParser(description="Generate TPC-DS-shaped raw data (pipe-delimited)")
    parser.add_argument("type", choices=["local", "cluster"], nargs="?", default="local",
                        help="generate on this host or fan out across a host list")
    parser.add_argument("--scale", type=check.scale_of, required=True,
                        help="volume of data to generate in GB (fractional allowed for smoke tests)")
    parser.add_argument("--parallel", type=check.parallel_value_type, default=2,
                        help="generate data in <n> chunks")
    parser.add_argument("--data_dir", required=True, help="target directory for generated data")
    parser.add_argument("--range", help="generate only chunks 'start,end' of the parallel set")
    parser.add_argument("--update", type=int, help="generate refresh set <n> (maintenance/throughput)")
    parser.add_argument("--table", help="generate only this table")
    parser.add_argument("--seed", type=int, default=19620718, help="RNG seed")
    parser.add_argument("--overwrite_output", action="store_true",
                        help="overwrite existing data in data_dir")
    parser.add_argument("--hosts", default="hosts.txt", help="hosts file for cluster mode")
    parser.add_argument("--retries", type=int, default=2,
                        help="cluster mode: retry a failed chunk up to <n> times on rotated hosts")
    args = parser.parse_args(argv)
    generate_data(args)


if __name__ == "__main__":
    main()
