"""Whole-benchmark CLI (reference: nds/nds_bench.py __main__ :500-506).

    python -m nds_tpu.cli.bench <bench.yml> [--resume] [--fault_spec SPEC]
"""

import argparse
import os

from ..check import check_version
from ..full_bench import get_yaml_params, run_full_bench


def main(argv=None):
    check_version()
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "yaml_config", help="yaml config file for the benchmark"
    )
    parser.add_argument(
        "--resume",
        action="store_true",
        help="resume from the bench_state.json checkpoint: phases recorded "
        "as completed are skipped (no manual skip: editing)",
    )
    parser.add_argument(
        "--fault_spec",
        help="fault-injection spec, e.g. 'oom:query5;crash:power_test' "
        "(exported as NDS_FAULT_SPEC so phase subprocesses inherit it)",
    )
    args = parser.parse_args(argv)
    if args.fault_spec:
        # env, not conf: phases are subprocess boundaries and must inherit
        os.environ["NDS_FAULT_SPEC"] = args.fault_spec
    params = get_yaml_params(args.yaml_config)
    run_full_bench(params, resume=args.resume)


if __name__ == "__main__":
    main()
