"""Whole-benchmark CLI (reference: nds/nds_bench.py __main__ :500-506).

    python -m nds_tpu.cli.bench <bench.yml>
"""

import argparse

from ..check import check_version
from ..full_bench import get_yaml_params, run_full_bench


def main(argv=None):
    check_version()
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "yaml_config", help="yaml config file for the benchmark"
    )
    args = parser.parse_args(argv)
    params = get_yaml_params(args.yaml_config)
    run_full_bench(params)


if __name__ == "__main__":
    main()
