"""Snapshot rollback CLI (reference: nds/nds_rollback.py __main__ :54-60).

    python -m nds_tpu.cli.rollback <warehouse_path> <timestamp>

Restores the maintenance-mutated fact tables to their last snapshot at or
before <timestamp> ('YYYY-mm-dd HH:MM:SS[.f]').
"""

import argparse

from ..check import check_version
from ..maintenance import rollback


def main(argv=None):
    check_version()
    parser = argparse.ArgumentParser()
    parser.add_argument("warehouse_path", help="lakehouse warehouse root")
    parser.add_argument("timestamp", help="timestamp to roll back to")
    args = parser.parse_args(argv)
    rollback(args.warehouse_path, args.timestamp)


if __name__ == "__main__":
    main()
