"""Power Run CLI (reference: nds/nds_power.py __main__ :309-384).

    python -m nds_tpu.cli.power <input_prefix> <query_stream_file> <time_log>
        [--input_format parquet|csv] [--output_prefix DIR]
        [--output_format parquet|csv] [--property_file F] [--floats]
        [--json_summary_folder DIR] [--sub_queries q1,q2,...]
        [--extra_time_log F]
"""

import argparse

from ..check import check_version
from ..power import gen_sql_from_stream, run_query_stream


def main(argv=None):
    check_version()
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "input_prefix",
        help="text to prepend to every input file path (warehouse root)",
    )
    parser.add_argument(
        "query_stream_file",
        help="query stream file that contains NDS queries in specific order",
    )
    parser.add_argument(
        "time_log",
        help="path to execution time log (CSV), only local path supported",
        default="",
    )
    parser.add_argument(
        "--input_format",
        choices=["parquet", "csv", "orc", "lakehouse"],
        default="parquet",
        help="type of the input data source",
    )
    parser.add_argument(
        "--output_prefix",
        help="text to prepend to every output file; if absent, results are "
        "collected to host memory instead of written",
    )
    parser.add_argument(
        "--output_format", default="parquet", help="type of query output"
    )
    parser.add_argument(
        "--property_file", help="property file for engine configuration"
    )
    parser.add_argument(
        "--floats",
        action="store_true",
        help="use double instead of decimal for decimal-typed columns",
    )
    parser.add_argument(
        "--json_summary_folder",
        help="empty folder (created if missing) for per-query JSON summaries",
    )
    parser.add_argument(
        "--extra_time_log",
        help="extra path to save a copy of the time log",
    )
    parser.add_argument(
        "--mesh_devices",
        type=int,
        help="execute over an N-device jax mesh (fact tables row-sharded, "
        "dims replicated); default is single-device",
    )
    parser.add_argument(
        "--sub_queries",
        type=lambda s: [x.strip() for x in s.split(",")],
        help="comma separated list of queries to run, e.g. 'query1,query2'. "
        "Use _part1/_part2 suffixes for queries 14, 23, 24, 39.",
    )
    parser.add_argument(
        "--query_timeout",
        type=float,
        help="per-query watchdog budget in seconds: a query still running "
        "after this long is recorded as a classified 'timeout' failure and "
        "the stream moves on (conf engine.query_timeout; env "
        "NDS_QUERY_TIMEOUT)",
    )
    parser.add_argument(
        "--fault_spec",
        help="fault-injection spec (conf engine.fault_spec; env "
        "NDS_FAULT_SPEC), e.g. 'oom:query5:1;hang:query9:30'",
    )
    args = parser.parse_args(argv)
    if args.fault_spec:
        from .. import faults

        faults.install(args.fault_spec)
    query_dict = gen_sql_from_stream(args.query_stream_file)
    run_query_stream(
        input_prefix=args.input_prefix,
        property_file=args.property_file,
        query_dict=query_dict,
        time_log_output_path=args.time_log,
        extra_time_log_output_path=args.extra_time_log,
        sub_queries=args.sub_queries,
        input_format=args.input_format,
        use_decimal=not args.floats,
        output_path=args.output_prefix,
        output_format=args.output_format,
        json_summary_folder=args.json_summary_folder,
        mesh_devices=args.mesh_devices,
        query_timeout=args.query_timeout,
    )


if __name__ == "__main__":
    main()
