"""`nds-tpu-submit route`: the fleet router over N serve replicas.

    python -m nds_tpu.cli.route host:port [host:port ...]
        [--port 8081] [--mesh_replica host:port] [--property_file F]

One process, one HTTP listener (shared with /metrics, /statusz,
/healthz — obs/httpserv.py), zero engine state: the router holds replica
addresses, health, verdict cache and retry budgets, nothing else.

    POST /query         routed by budget verdict; 429 `reject` answered
                        at the edge, failover + Retry-After jitter on
                        replica death/shed. X-NDS-Tenant keys the
                        fleet-wide quota.
    GET  /fleet         live replica health + degraded capabilities
    POST /fleet/reload  rolling drain + reload across the replicas
    POST /drain         drain the router itself (healthz flips 503)

SIGTERM/SIGINT drains before exit. Knobs: the `engine.route_*` family
(README "Serving fleet" section).
"""

from __future__ import annotations

import argparse
import os
import signal
import threading

from ..check import check_version
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..power import load_properties
from ..serve.router import QueryRouter
from ..serve.service import resolve_serve_port


def build_router(args):
    """Router + listener from CLI args. Returns (router, server) — split
    from main() so tests and tools/serve_bench --fleet drive the real
    construction path without a subprocess."""
    conf = {"app.name": "NDS - Route"}
    if args.property_file:
        conf.update(load_properties(args.property_file))
    if args.port is not None:
        conf["engine.serve_port"] = args.port
    port = resolve_serve_port(conf)
    if port is None:
        raise SystemExit(
            "route: no port configured (pass --port, set engine.serve_port "
            "in the property file, or NDS_SERVE_PORT; 0 binds ephemeral)"
        )
    # ONE listener: the router rides the process-wide metrics endpoint,
    # same seam as a replica — /query, /fleet, /metrics, /statusz,
    # /healthz all answer from this port
    conf["engine.metrics_port"] = port
    tracer = obs_trace.tracer_from_conf(conf, app_id="nds-route")
    router = QueryRouter(
        args.replica, conf=conf, tracer=tracer,
        mesh_replica=args.mesh_replica,
    )
    server = obs_metrics.active_server()
    if server is None:
        raise SystemExit(
            f"route: could not bind port {port} (already in use?) — a "
            f"router without a listener is useless"
        )
    # /statusz's fleet section is the router's live view (replica
    # health, degraded capabilities, fleet tenant in-flight)
    obs_metrics.shared_sink().set_fleet_provider(router.fleet_snapshot)
    server.attach_app(router)
    return router, server


def main(argv=None):
    check_version()
    parser = argparse.ArgumentParser(
        description="fault-tolerant query router over N serve replicas"
    )
    parser.add_argument(
        "replica", nargs="+",
        help="replica address host:port (repeat for the fleet)",
    )
    parser.add_argument(
        "--port", type=int, default=None,
        help="HTTP port (0 = ephemeral; default: engine.serve_port / "
        "NDS_SERVE_PORT)",
    )
    parser.add_argument(
        "--mesh_replica",
        help="replica address to pin spill/blocked-verdict queries to "
        "(the mesh-backed host with the device capacity they need)",
    )
    parser.add_argument(
        "--property_file", help="property file for engine.route_* knobs"
    )
    args = parser.parse_args(argv)
    router, server = build_router(args)
    stop = threading.Event()

    def _on_signal(signum, frame):
        print(f"route: signal {signum}; draining", flush=True)
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    print(
        f"route: fronting {len(router.replicas)} replicas on "
        f"{server.host}:{server.port} "
        f"({router.max_attempts} attempts/request, "
        f"tenant cap {router.tenant_cap or 'off'}, pid {os.getpid()})",
        flush=True,
    )
    stop.wait()
    router.handle_drain()
    router.close()
    print("route: drained; bye", flush=True)


if __name__ == "__main__":
    main()
