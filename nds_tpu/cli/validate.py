"""Validation CLI (reference: nds/nds_validate.py __main__ :266-330).

    python -m nds_tpu.cli.validate <input1> <input2> <query_stream_file>
        [--input1_format parquet] [--input2_format parquet]
        [--ignore_ordering] [--epsilon E] [--max_errors N] [--floats]
        [--json_summary_folder DIR]
"""

import argparse

from ..check import check_version
from ..power import gen_sql_from_stream
from ..validate import iterate_queries, update_summary


def main(argv=None):
    check_version()
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "input1", help="path of the first input data (e.g. TPU run output)"
    )
    parser.add_argument(
        "input2", help="path of the second input data (e.g. CPU run output)"
    )
    parser.add_argument(
        "query_stream_file", help="query stream file used for the runs"
    )
    parser.add_argument("--input1_format", default="parquet")
    parser.add_argument("--input2_format", default="parquet")
    parser.add_argument(
        "--max_errors", type=int, default=10, help="Maximum number of differences to report."
    )
    parser.add_argument(
        "--epsilon",
        type=float,
        default=0.00001,
        help="Allow for differences in precision when comparing floating point values.",
    )
    parser.add_argument(
        "--ignore_ordering",
        action="store_true",
        help="Ignore ordering of output (sort the data collected before comparison)",
    )
    parser.add_argument(
        "--floats",
        action="store_true",
        help="the dataset was loaded as float instead of decimal",
    )
    parser.add_argument(
        "--json_summary_folder",
        help="path of a folder that contains json summary files to update "
        "with queryValidationStatus",
    )
    args = parser.parse_args(argv)
    query_names = list(gen_sql_from_stream(args.query_stream_file).keys())
    unmatch = iterate_queries(
        args.input1,
        args.input2,
        query_names,
        input1_format=args.input1_format,
        input2_format=args.input2_format,
        ignore_ordering=args.ignore_ordering,
        max_errors=args.max_errors,
        epsilon=args.epsilon,
        is_float=args.floats,
    )
    if args.json_summary_folder:
        update_summary(args.json_summary_folder, unmatch, query_names)
    print(f"{len(query_names) - len(unmatch)}/{len(query_names)} queries matched")
    return 1 if unmatch else 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
