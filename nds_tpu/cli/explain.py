"""CLI: `nds-tpu-submit explain` — print a statement's plan, and with
`--budget` the static budgeter's per-node estimate table and verdict
(analysis/budget.py): modeled rows/width/capacity/allocation/peak per plan
node, the plan-level peak vs the working-set budget, and the chosen
execution mode (direct | blocked(window_rows) | over | reject).

Schema-only by default: `--scale SF` synthesizes base-table cardinalities
from the TPC-DS scaling model, so no data (and no accelerator) is needed —
the same mode the corpus CI gate runs in. Point `--data_dir` at a real
warehouse to estimate against actual catalog row counts instead.

Examples:
    # one template's budget table at SF10, schema-only
    ./nds-tpu-submit explain --query 5 --scale 10 --budget

    # ad-hoc SQL against a real warehouse
    ./nds-tpu-submit explain --data_dir /data/wh --budget \\
        --sql "select count(*) from store_sales"

With a trace dir configured (NDS_TRACE_DIR / engine.trace_dir) each
analyzed statement also emits a `plan_budget` event, so explain runs leave
the same observability trail plan time does.
"""

from __future__ import annotations

import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _build_session(args):
    from ..engine.session import Session, _Entry
    from ..schema import get_schemas

    conf = {"engine.plan_budget": "off"}  # enforcement off: explain only
    if args.budget_bytes:
        conf["engine.plan_budget_bytes"] = int(args.budget_bytes)
    sess = Session(use_decimal=not args.float, conf=conf)
    if args.data_dir:
        sess.register_nds_tables(args.data_dir, fmt=args.format)
    else:
        for name, schema in get_schemas(not args.float).items():
            sess.catalog.entries[name] = _Entry(schema=schema)
    return sess


def _statements(args):
    from ..engine.sql.parser import parse_script

    if args.sql:
        yield "sql", args.sql
        return
    if args.file:
        with open(args.file, encoding="utf-8") as f:
            yield os.path.basename(args.file), f.read()
        return
    import numpy as np

    from ..datagen.query_streams import instantiate

    for q in (int(x) for x in args.query.split(",")):
        rng = np.random.default_rng(np.random.SeedSequence([args.rngseed, 0]))
        yield f"query{q}", instantiate(q, rng, args.scale)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="print a statement's plan (and its static budget table)"
    )
    src = ap.add_mutually_exclusive_group(required=True)
    src.add_argument("--sql", help="ad-hoc SQL text")
    src.add_argument("--file", help="path to a .sql file")
    src.add_argument(
        "--query", help="comma-separated TPC-DS template numbers"
    )
    ap.add_argument(
        "--budget", action="store_true",
        help="print the per-node estimate table + verdict",
    )
    ap.add_argument(
        "--mesh", type=int, default=None, metavar="N",
        help="model execution over an N-device mesh: sharded node bytes "
        "divide by N, replicated relations are charged per device, and "
        "the verdict is per-device (defaults to engine.mesh_devices "
        "when configured; schema-only — no backend is built)",
    )
    ap.add_argument(
        "--scale", type=float, default=1.0,
        help="scale factor for schema-only cardinalities (default 1.0)",
    )
    ap.add_argument(
        "--data_dir", default=None,
        help="real warehouse dir (estimates use actual catalog rows)",
    )
    ap.add_argument("--format", default="parquet")
    ap.add_argument("--float", action="store_true",
                    help="float (non-decimal) type mapping")
    ap.add_argument("--budget_bytes", type=int, default=None,
                    help="override the working-set budget")
    ap.add_argument("--rngseed", type=int, default=0)
    ap.add_argument(
        "--top", type=int, default=0,
        help="only print the last N (outermost) estimate rows",
    )
    args = ap.parse_args(argv)

    from ..analysis import budget as B
    from ..engine.sql import ast as A
    from ..engine.sql.parser import parse_script

    sess = _build_session(args)
    rejected = 0
    for label, text in _statements(args):
        for i, stmt in enumerate(parse_script(text)):
            if not isinstance(stmt, A.SelectStmt):
                print(f"== {label}#{i}: skipped ({type(stmt).__name__})")
                continue
            res = sess.run_stmt(stmt)
            print(f"== {label}#{i}")
            print(res.explain(), end="")
            if not args.budget:
                continue
            mesh_devs = args.mesh
            if mesh_devs is None:
                mesh_devs = B.session_mesh_devices(sess)
            pb = B.analyze_plan(
                res.plan,
                sess.catalog,
                scale_factor=None if args.data_dir else args.scale,
                budget_bytes=(
                    int(args.budget_bytes) if args.budget_bytes else None
                ),
                mesh_devices=mesh_devs,
            )
            print(pb.table(limit=args.top))
            B.emit_budget_event(sess.tracer, pb)
            if pb.verdict == "reject":
                rejected += 1
    return 2 if rejected else 0


if __name__ == "__main__":
    sys.exit(main())
