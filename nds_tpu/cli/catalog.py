"""`nds-tpu-submit catalog`: the fleet-catalog coordinator process.

    python -m nds_tpu.cli.catalog <warehouse_path> --port 7331
        [--property_file F] [--recover_only]

One coordinator per warehouse. Startup runs WAL recovery over every
lakehouse table under the warehouse root (published intents pruned,
unpublished intents rolled back — they were never acknowledged), then
serves the catalog routes on the ONE process-wide listener
(obs/httpserv.py, via `attach_app` — the same port carries /metrics,
/statusz with its `catalog` section, and /healthz for the fleet's load
checks):

    POST /catalog/commit   fence-checked, WAL-journaled, serialized
                           manifest publish (the single-writer commit log)
    POST /catalog/lease    reader-lease acquire/renew/release/held/sweep
    POST /catalog/fence    writer registration (epoch tokens), fence
                           read/bump
    GET  /catalog/state    tables this coordinator has touched

Clients point `engine.lake_catalog` / NDS_LAKE_CATALOG at
`http://host:port`. Kill -TERM exits cleanly; a crash at ANY point is
recovered by the next start's WAL pass (the chaos gate in ci/tier1-check
kills one mid-commit and asserts exactly that).
"""

from __future__ import annotations

import argparse
import os
import signal
import threading

from ..check import check_version
from ..lakehouse.catalog import CatalogCoordinator
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..power import load_properties


def build_coordinator(args):
    """Coordinator + listener from CLI args; returns (coordinator,
    server, recovery report). Split from main() so tests and
    tools/catalog_check.py drive the real construction path."""
    conf = {"app.name": "NDS - Catalog"}
    if args.property_file:
        conf.update(load_properties(args.property_file))
    if args.port is not None:
        conf["engine.serve_port"] = args.port
    port = conf.get("engine.serve_port")
    if port is None:
        port = os.environ.get("NDS_SERVE_PORT")
    if port is None:
        raise SystemExit(
            "catalog: no port configured (pass --port or NDS_SERVE_PORT; "
            "0 binds ephemeral)"
        )
    # ONE listener: the catalog rides the process-wide metrics endpoint,
    # so /catalog/*, /metrics, /statusz and /healthz share a port
    conf["engine.metrics_port"] = int(port)
    tracer = obs_trace.tracer_from_conf(conf)
    coordinator = CatalogCoordinator(tracer=tracer)
    recovered = coordinator.recover_warehouse(args.warehouse_path)
    server = obs_metrics.active_server()
    if server is None and not args.recover_only:
        raise SystemExit(
            f"catalog: could not bind port {port} (already in use?) — a "
            f"coordinator without a listener arbitrates nothing"
        )
    if server is not None:
        server.attach_app(coordinator)
    return coordinator, server, recovered


def main(argv=None):
    check_version()
    parser = argparse.ArgumentParser(
        description="fleet-catalog coordinator: single-writer commit log, "
        "cross-host leases, vacuum fencing for one lakehouse warehouse"
    )
    parser.add_argument(
        "warehouse_path", help="warehouse root holding lakehouse tables"
    )
    parser.add_argument(
        "--port", type=int, default=None,
        help="HTTP port (0 = ephemeral; default: engine.serve_port / "
        "NDS_SERVE_PORT)",
    )
    parser.add_argument(
        "--property_file", help="property file for engine configuration"
    )
    parser.add_argument(
        "--recover_only", action="store_true",
        help="run WAL recovery over the warehouse and exit (no listener)",
    )
    args = parser.parse_args(argv)
    coordinator, server, recovered = build_coordinator(args)
    for rep in recovered:
        if rep["pruned"] or rep["rolled_back"]:
            print(
                f"catalog: recovered {rep['table']}: {rep['pruned']} "
                f"pruned, {rep['rolled_back']} rolled back", flush=True,
            )
    if args.recover_only:
        print(f"catalog: recovery done over {len(recovered)} table(s)",
              flush=True)
        return
    stop = threading.Event()

    def _on_signal(signum, frame):
        print(f"catalog: signal {signum}; bye", flush=True)
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    print(
        f"catalog: coordinating {args.warehouse_path} on "
        f"{server.host}:{server.port} (pid {os.getpid()})", flush=True,
    )
    stop.wait()


if __name__ == "__main__":
    main()
