"""Data Maintenance CLI (reference: nds/nds_maintenance.py __main__ :273-316).

    python -m nds_tpu.cli.maintenance <warehouse_path> <refresh_data_path>
        <time_log> [--maintenance_queries LF_CS,DF_CS] [--property_file F]
        [--json_summary_folder DIR] [--floats] [--vacuum] [--optimize]

Maintenance-under-load mode (`full_bench`'s opt-in phase): pass
`--under_load_stream <query_N.sql>` and the DM functions run in a racing
thread against that query stream, measured as maintenance throughput x
query p99 degradation (`--under_load_report` gets the JSON).
"""

import argparse

from ..check import check_version
from ..maintenance import run_maintenance, run_maintenance_under_load


def main(argv=None):
    check_version()
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "warehouse_path", help="lakehouse warehouse root to apply refreshes to"
    )
    parser.add_argument(
        "refresh_data_path", help="path to the generated refresh (--update) data"
    )
    parser.add_argument(
        "time_log", help="path to execution time log (CSV)", default=""
    )
    parser.add_argument(
        "--maintenance_queries",
        type=lambda s: s.split(","),
        help="comma separated maintenance function names, e.g. 'LF_CS,DF_CS'",
    )
    parser.add_argument(
        "--property_file", help="property file for engine configuration"
    )
    parser.add_argument(
        "--json_summary_folder",
        help="empty folder (created if missing) for per-function JSON summaries",
    )
    parser.add_argument(
        "--floats",
        action="store_true",
        help="use double instead of decimal for decimal-typed columns",
    )
    parser.add_argument(
        "--vacuum",
        action="store_true",
        help="expire old snapshots + delete unreferenced data files after "
        "the refresh functions (reader-lease safe)",
    )
    parser.add_argument(
        "--optimize",
        action="store_true",
        help="compact small data files after the refresh functions "
        "(bin-pack toward engine.lake_compact_target_bytes, zone maps "
        "regenerated; snapshot-isolated from concurrent readers)",
    )
    parser.add_argument(
        "--under_load_stream",
        help="query stream file to run CONCURRENTLY with the refresh "
        "functions (maintenance-under-load mode)",
    )
    parser.add_argument(
        "--under_load_report",
        help="JSON report path for maintenance-under-load metrics",
    )
    parser.add_argument(
        "--under_load_queries",
        type=lambda s: s.split(","),
        help="comma separated stream-query subset for under-load mode",
    )
    args = parser.parse_args(argv)
    if args.under_load_stream:
        run_maintenance_under_load(
            warehouse_path=args.warehouse_path,
            refresh_data_path=args.refresh_data_path,
            stream_file=args.under_load_stream,
            time_log_output_path=args.time_log,
            report_path=args.under_load_report,
            property_file=args.property_file,
            spec_queries=args.maintenance_queries,
            sub_queries=args.under_load_queries,
            use_decimal=not args.floats,
        )
        return
    run_maintenance(
        warehouse_path=args.warehouse_path,
        refresh_data_path=args.refresh_data_path,
        time_log_output_path=args.time_log,
        json_summary_folder=args.json_summary_folder,
        property_file=args.property_file,
        spec_queries=args.maintenance_queries,
        use_decimal=not args.floats,
        vacuum_after=args.vacuum,
        optimize_after=args.optimize,
    )


if __name__ == "__main__":
    main()
