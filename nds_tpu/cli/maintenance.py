"""Data Maintenance CLI (reference: nds/nds_maintenance.py __main__ :273-316).

    python -m nds_tpu.cli.maintenance <warehouse_path> <refresh_data_path>
        <time_log> [--maintenance_queries LF_CS,DF_CS] [--property_file F]
        [--json_summary_folder DIR] [--floats]
"""

import argparse

from ..check import check_version
from ..maintenance import run_maintenance


def main(argv=None):
    check_version()
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "warehouse_path", help="lakehouse warehouse root to apply refreshes to"
    )
    parser.add_argument(
        "refresh_data_path", help="path to the generated refresh (--update) data"
    )
    parser.add_argument(
        "time_log", help="path to execution time log (CSV)", default=""
    )
    parser.add_argument(
        "--maintenance_queries",
        type=lambda s: s.split(","),
        help="comma separated maintenance function names, e.g. 'LF_CS,DF_CS'",
    )
    parser.add_argument(
        "--property_file", help="property file for engine configuration"
    )
    parser.add_argument(
        "--json_summary_folder",
        help="empty folder (created if missing) for per-function JSON summaries",
    )
    parser.add_argument(
        "--floats",
        action="store_true",
        help="use double instead of decimal for decimal-typed columns",
    )
    args = parser.parse_args(argv)
    run_maintenance(
        warehouse_path=args.warehouse_path,
        refresh_data_path=args.refresh_data_path,
        time_log_output_path=args.time_log,
        json_summary_folder=args.json_summary_folder,
        property_file=args.property_file,
        spec_queries=args.maintenance_queries,
        use_decimal=not args.floats,
    )


if __name__ == "__main__":
    main()
