"""Operator-level query profiler over the engine's structured event logs
(the local analogue of the reference's RAPIDS profiling tool over Spark
event logs).

    python -m nds_tpu.cli.profile <events.jsonl | trace_dir>...
        [--top N] [--per_query] [--json] [--check]
    python -m nds_tpu.cli.profile --critical-path <events | trace_dir>...
        [--min_attributed 0.9] [--json]
    python -m nds_tpu.cli.profile --check <failure-bundle-*.json>...
    python -m nds_tpu.cli.profile --compare OLD NEW
        [--ratio 1.25] [--min_ms 50] [--fail_on_regression]
        [--bench OLD_BENCH NEW_BENCH]
    python -m nds_tpu.cli.profile compact <trace_dir> [--all] [--dry_run]

Single-run mode aggregates one or more event logs (files or trace dirs —
a throughput run's per-stream files profile together naturally) into
per-query operator time/rows breakdowns, the top-N hottest operators
across the run, and cache-hit/retry tallies; a (partially) compacted
trace dir profiles transparently — raw segments and `compact-*.json`
summary artifacts merge with identical summary semantics.
`--critical-path` attributes each query's wall time to named causes
(execute / exchange-wait / spill-io / catalog-load / ladder-retry /
backoff-wait / hung-wait / plan-host — obs/critpath.py) and, on mesh
traces, names the straggler device and the skew share of the exchange
gap; `--min_attributed R` exits 1 when any query's attributed share
falls below R (the CI diagnosis gate). Paths that look like flight-
recorder failure bundles (`failure-bundle-*.json`) are validated
structurally (bundle keys + ring event schema) instead of being parsed
as event logs — `profile --check <bundle>` is how CI asserts a crash
left a USABLE black box. `--compare`
diffs two runs and flags per-query and per-operator regressions.
`compact` folds closed rotation segments (engine.trace_rotate_bytes)
into per-app summary artifacts and deletes the raw files, bounding a
long-running fleet's trace-dir disk (--all folds the open tails too —
post-run mode). Exit codes: 0 ok, 1 regressions found under
--fail_on_regression (or segments skipped by compact), 2 malformed
event log.
"""

import argparse
import json
import sys

from ..obs import critpath as CP
from ..obs import flight as FL
from ..obs import reader as R


def _fmt_bytes(n):
    if n is None:
        return "-"
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024


def _fmt_ms(v):
    return "-" if v is None else f"{v:,.1f}"


def _load_profile(paths, check: bool):
    """Validated profile aggregate over raw event files + compaction
    artifacts — one shared implementation (reader.load_profile); this
    wrapper only adds the CLI's schema reporting and exit codes. Schema
    validation applies to the raw events; artifacts were validated when
    their segments folded (compact refuses schema-dirty segments)."""

    def _validate(events):
        problems = R.validate_events(events)
        if problems:
            for p in problems[:20]:
                print(f"profile: schema: {p}", file=sys.stderr)
            if check:
                sys.exit(2)

    try:
        return R.load_profile(paths, strict=True, events_hook=_validate)
    except (R.MalformedEventError, OSError, ValueError, KeyError) as exc:
        print(f"profile: {exc}", file=sys.stderr)
        sys.exit(2)


def _render_profile(prof, top: int, per_query: bool):
    queries = prof["queries"]
    n_failed = sum(
        1 for v in queries.values() if v.get("status") == "Failed"
    )
    print(f"== {len(queries)} queries ({n_failed} failed)")
    for q in sorted(queries):
        rec = queries[q]
        mem = ""
        if rec.get("mem_hw_bytes") is not None:
            mem = (f"  mem_hw {_fmt_bytes(rec['mem_hw_bytes'])}"
                   f" ({rec.get('mem_source')})")
        status = rec.get("status") or "?"
        if rec.get("failure_kind"):
            status += f" ({rec['failure_kind']})"
        runs = f" x{rec['runs']}" if rec.get("runs", 1) > 1 else ""
        print(f"\n-- {q}{runs}: wall {_fmt_ms(rec.get('wall_ms'))} ms  "
              f"plan {_fmt_ms(rec.get('root_incl_ms'))} ms  {status}{mem}")
        if per_query and rec["ops"]:
            print(f"   {'operator':<18}{'count':>6}{'incl_ms':>12}"
                  f"{'excl_ms':>12}{'rows':>12}")
            for node, op in sorted(
                rec["ops"].items(), key=lambda kv: -kv[1]["excl_ms"]
            ):
                print(f"   {node:<18}{op['count']:>6}"
                      f"{op['incl_ms']:>12,.1f}{op['excl_ms']:>12,.1f}"
                      f"{op['rows']:>12,}")
    hot = sorted(
        prof["op_totals"].items(), key=lambda kv: -kv[1]["excl_ms"]
    )[:top]
    if hot:
        print(f"\n== top {len(hot)} operators by exclusive time (run-wide)")
        print(f"   {'operator':<18}{'count':>6}{'incl_ms':>12}"
              f"{'excl_ms':>12}{'rows':>12}")
        for node, op in hot:
            print(f"   {node:<18}{op['count']:>6}{op['incl_ms']:>12,.1f}"
                  f"{op['excl_ms']:>12,.1f}{op['rows']:>12,}")
    t = prof["tallies"]
    print(f"\n== tallies: plan-cache {t['plan_cache_hits']} hit / "
          f"{t['plan_cache_misses']} miss; catalog {t['catalog_loads']} "
          f"loads ({t['catalog_cache_hits']} cache-hit); "
          f"io retries {t['io_retries']}; ladder rungs {t['ladder_rungs']}; "
          f"watchdog fires {t['watchdog_fires']}; faults injected "
          f"{t['faults_injected']}; blocked-union windows "
          f"{t['blocked_union_windows']}")
    # mesh-execution evidence (exchange/mesh_fallback events); .get()
    # because compacted artifacts from pre-mesh runs lack the keys
    if t.get("exchange_ops") or t.get("mesh_fallbacks"):
        print(f"== exchange: {t.get('exchange_ops', 0)} collective "
              f"exchange(s) moved {_fmt_bytes(t.get('exchange_bytes', 0))} "
              f"over the interconnect; {t.get('exchange_retries', 0)} "
              f"overflow retries; worst skew "
              f"{t.get('exchange_max_skew', 0.0):.2f}x"
              + (f"; {t['mesh_fallbacks']} replication fallback(s)"
                 if t.get("mesh_fallbacks") else ""))
    # out-of-core evidence (spill events); .get() because compacted
    # artifacts from pre-spill runs lack the keys
    if t.get("spill_ops"):
        print(f"== spill: {t['spill_ops']} out-of-core op(s); "
              f"{_fmt_bytes(t.get('spill_bytes_in', 0))} into the host "
              f"pool / {_fmt_bytes(t.get('spill_bytes_out', 0))} read "
              f"back; {t.get('spill_evictions', 0)} segment(s) tiered "
              f"to disk")
    # transactional-lakehouse evidence (lake_commit/lake_vacuum events);
    # .get() because compacted artifacts from pre-lakehouse-txn runs lack
    # the keys
    if t.get("lake_commits") or t.get("lake_commit_conflicts"):
        print(f"== lakehouse: {t.get('lake_commits', 0)} commit(s) "
              f"({t.get('lake_commit_rebases', 0)} rebased, "
              f"{t.get('lake_commit_conflicts', 0)} conflict abort(s)); "
              f"{t.get('lake_vacuums', 0)} vacuum(s) removed "
              f"{t.get('lake_vacuum_files', 0)} file(s)")
    pb = prof.get("plan_budget") or {}
    if pb.get("verdicts"):
        verdicts = ", ".join(
            f"{v} x{n}" for v, n in sorted(pb["verdicts"].items())
        )
        wm = t.get("mem_watermarks", 0)
        print(f"== plan budget: {verdicts}; max modeled peak "
              f"{_fmt_bytes(pb['max_peak_bytes'])} vs budget "
              f"{_fmt_bytes(pb['max_budget_bytes'])}"
              + (f"; host watermarks {wm}" if wm else ""))
    rate = R.exec_cache_hit_rate(prof)
    if rate is not None or t["pipelines_fused"] or t["pipelines_eager"]:
        rate_s = "-" if rate is None else f"{rate:.1%}"
        print(f"== pipelines: {t['pipelines_fused']} fused / "
              f"{t['pipelines_eager']} eager; executable cache "
              f"{t['exec_cache_hits']} hit / {t['exec_cache_misses']} miss "
              f"(rate {rate_s})")
    # persistent AOT executable cache evidence (aot_cache events); .get()
    # because compacted artifacts from pre-AOT runs lack the keys
    aot_rate = R.aot_disk_hit_rate(prof)
    if aot_rate is not None or t.get("aot_stores") or t.get(
        "aot_quarantined"
    ):
        rate_s = "-" if aot_rate is None else f"{aot_rate:.1%}"
        print(f"== aot cache: {t.get('aot_disk_hits', 0)} disk hit / "
              f"{t.get('aot_misses', 0)} miss (rate {rate_s}); "
              f"{t.get('aot_stores', 0)} stored, "
              f"{t.get('aot_evictions', 0)} evicted, "
              f"{t.get('aot_quarantined', 0)} quarantined")
    # plan-feedback evidence (plan_feedback events); .get() because
    # compacted artifacts from pre-feedback runs lack the block
    fb = prof.get("feedback") or {}
    if fb.get("records") or fb.get("lookups"):
        rate = R.feedback_hit_rate(prof)
        rate_s = "-" if rate is None else f"{rate:.1%}"
        mean = R.feedback_err_mean(prof)
        mean_s = "-" if mean is None else f"{mean:.3f}"
        print(f"== plan feedback: {fb.get('records', 0)} actual(s) "
              f"recorded; {fb.get('hits', 0)}/{fb.get('lookups', 0)} "
              f"lookup(s) hit (rate {rate_s}); {fb.get('overrides', 0)} "
              f"estimate(s) overridden; mean |log(est/actual)| {mean_s}")
    kernels = sorted(
        prof.get("kernel_totals", {}).items(),
        key=lambda kv: -kv[1]["dur_ms"],
    )[:top]
    if kernels:
        print(f"\n== top {len(kernels)} kernels by dispatch time "
              f"(kernel_span; NDS_TRACE_KERNELS runs)")
        print(f"   {'kernel':<28}{'count':>6}{'total_ms':>12}"
              f"{'avg_ms':>10}{'rows':>14}")
        for name, k in kernels:
            avg = k["dur_ms"] / k["count"] if k["count"] else 0.0
            print(f"   {name:<28}{k['count']:>6}{k['dur_ms']:>12,.1f}"
                  f"{avg:>10,.3f}{k['n_rows']:>14,}")


def _accuracy_report(events, top: int) -> dict:
    """Budgeter est-vs-actual error distributions per operator class, from
    raw op_spans annotated by the plan-feedback loop (`est_rows` at budget
    time, `actual_rows` at execution). Raw events only, like
    --critical-path: compaction folds the spans away (the mergeable
    summary keeps only per-class mean/max)."""
    import math

    per_class = {}
    worst = []
    for ev in events:
        if ev.get("kind") != "op_span" or ev.get("est_rows") is None:
            continue
        actual = ev.get("actual_rows")
        if actual is None:
            actual = ev.get("rows")
        if actual is None:
            continue
        err = abs(
            math.log(max(int(ev["est_rows"]), 1))
            - math.log(max(int(actual), 1))
        )
        per_class.setdefault(ev.get("node") or "?", []).append(err)
        worst.append({
            "query": ev.get("query"),
            "node": ev.get("node"),
            "est_rows": int(ev["est_rows"]),
            "actual_rows": int(actual),
            "abs_log_err": round(err, 4),
        })
    classes = {}
    for node, errs in per_class.items():
        errs.sort()
        n = len(errs)
        classes[node] = {
            "n": n,
            "median": round(errs[n // 2], 4),
            "p90": round(errs[min(n - 1, (n * 9) // 10)], 4),
            "max": round(errs[-1], 4),
        }
    worst.sort(key=lambda s: -s["abs_log_err"])
    all_errs = sorted(e for errs in per_class.values() for e in errs)
    return {
        "samples": len(all_errs),
        "median": (
            round(all_errs[len(all_errs) // 2], 4) if all_errs else None
        ),
        "max": round(all_errs[-1], 4) if all_errs else None,
        "by_class": classes,
        "worst": worst[:top],
    }


def _render_accuracy(acc):
    if not acc["samples"]:
        print("== accuracy: no annotated op_spans (plan feedback off, or "
              "an untraced run)")
        return
    print(f"== budgeter accuracy: median |log(est/actual)| "
          f"{acc['median']:.3f}, max {acc['max']:.3f}, over "
          f"{acc['samples']} annotated span(s)")
    print(f"   {'operator':<18}{'n':>6}{'median':>10}{'p90':>10}{'max':>10}")
    for node, c in sorted(
        acc["by_class"].items(), key=lambda kv: -kv[1]["median"]
    ):
        print(f"   {node:<18}{c['n']:>6}{c['median']:>10.3f}"
              f"{c['p90']:>10.3f}{c['max']:>10.3f}")
    if acc["worst"]:
        print(f"\n== worst {len(acc['worst'])} misestimate(s)")
        for s in acc["worst"]:
            print(f"   {s['query'] or '?'}/{s['node']}: est "
                  f"{s['est_rows']:,} vs actual {s['actual_rows']:,} "
                  f"(|log err| {s['abs_log_err']:.3f})")


def _load_sqlite_shared(path):
    """The `sqlite_shared` block out of a bench artifact: a saved compact
    OUT line / bench JSON-lines output, or a driver capture whose `tail`
    holds the last emitted line. Returns the dict or None."""
    import re

    with open(path) as fh:
        text = fh.read()
    best = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj.get("sqlite_shared"), dict):
            best = obj["sqlite_shared"]
        elif isinstance(obj.get("tail"), str):
            # driver wrapper: scan the captured tail for the last block
            m = None
            for m in re.finditer(r'"sqlite_shared":\s*(\{[^{}]*\})',
                                 obj["tail"]):
                pass
            if m is not None:
                try:
                    best = json.loads(m.group(1))
                except ValueError:
                    pass
    return best


def _load_bench_accuracy(path):
    """The budgeter-accuracy fields (`budget_err_median`,
    `feedback_hit_rate`) out of a bench artifact: the bench OUT line /
    metrics report, or a driver capture whose `tail` holds it. Returns
    the dict (values may be None) or None when the artifact carries
    neither key — pre-feedback rounds compare as absent, not as zero."""
    import re

    try:
        with open(path) as fh:
            text = fh.read()
    except OSError:
        return None
    best = None
    for line in text.splitlines():
        line = line.strip()
        obj = None
        if line.startswith("{"):
            try:
                obj = json.loads(line)
            except ValueError:
                obj = None
        if isinstance(obj, dict) and isinstance(obj.get("tail"), str):
            line, obj = obj["tail"], None  # scan the captured tail below
        if isinstance(obj, dict):
            if "budget_err_median" in obj or "feedback_hit_rate" in obj:
                best = {
                    "budget_err_median": obj.get("budget_err_median"),
                    "feedback_hit_rate": obj.get("feedback_hit_rate"),
                }
            continue
        # metrics.csv rows ("key,value"), printed dict reprs, captured
        # tails — take the LAST occurrence, like the sqlite loader
        for key in ("budget_err_median", "feedback_hit_rate"):
            m = None
            for m in re.finditer(
                rf"['\"]?{key}['\"]?\s*[:,]\s*([0-9.]+|None|null)", line
            ):
                pass
            if m is not None:
                best = best if best is not None else {}
                v = m.group(1)
                best[key] = None if v in ("None", "null") else float(v)
    return best


def _compare_bench_accuracy(old_path, new_path):
    """Budgeter-accuracy headline comparison record (ISSUE 18: budgeter
    error is a published, shrinking number). Fail-soft like the other
    bench headlines: artifacts without the fields yield no record at
    all. Regression: the median |log(est/actual)| grew more than 25%
    AND by at least 0.1 (below that is sampling noise)."""
    old = _load_bench_accuracy(old_path) or {}
    new = _load_bench_accuracy(new_path)
    if new is None and not old:
        return []
    rec = {
        "level": "bench", "query": "budget_accuracy",
        "old_err": old.get("budget_err_median"),
        "new_err": (new or {}).get("budget_err_median"),
        "old_hit_rate": old.get("feedback_hit_rate"),
        "new_hit_rate": (new or {}).get("feedback_hit_rate"),
        "change": "headline",
    }
    e_old, e_new = rec["old_err"], rec["new_err"]
    if (
        e_old is not None and e_new is not None
        and e_new > e_old * 1.25 and e_new - e_old >= 0.1
    ):
        rec["change"] = "regression"
    return [rec]


def _compare_sqlite_shared(old_path, new_path):
    """sqlite_shared headline comparison records (ROADMAP item 3: publish
    the engine-vs-sqlite shared-subset ratio until it crosses 1.0, flag
    when it worsens). Regression: the ratio rose more than 2% — geomeans
    over ~100 queries are stable, so drift beyond that is a real loss."""
    old = _load_sqlite_shared(old_path)
    new = _load_sqlite_shared(new_path)
    out = []
    if new is None:
        out.append({
            "level": "bench", "change": "status_change",
            "query": "sqlite_shared",
            "detail": f"no sqlite_shared block in {new_path}",
        })
        return out
    r_new = new.get("ratio")
    r_old = old.get("ratio") if old else None
    rec = {
        "level": "bench", "query": "sqlite_shared",
        "old_ratio": r_old, "new_ratio": r_new,
        "queries": new.get("queries"),
        "change": "headline",
    }
    if r_old is not None and r_new is not None and r_new > r_old * 1.02:
        rec["change"] = "regression"
    out.append(rec)
    return out


def _load_multichip(path):
    """A MULTICHIP round artifact: the driver wrapper ({n_devices, rc, ok,
    tail}) or the mesh gate's metrics block (tools/mesh_stream_check.py).
    None when unreadable — comparison is fail-soft by contract."""
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError):
        return None
    return obj if isinstance(obj, dict) else None


def _compare_multichip(old_path, new_path):
    """MULTICHIP round comparison (ISSUE 13): the SF0.01 mesh-vs-oracle
    gate's artifact against the newest stored MULTICHIP_r*.json — the
    same fail-soft shape as the sqlite_shared headline. Old rounds
    (r01–r05 are driver wrappers with only {ok, tail}) predate the
    metrics block, so old_ratio starts null. Regression: the mesh run
    stopped being ok, or the mesh-vs-oracle wall ratio worsened > 25%."""
    old = _load_multichip(old_path) or {}
    new = _load_multichip(new_path)
    out = []
    if new is None:
        out.append({
            "level": "bench", "change": "status_change",
            "query": "multichip",
            "detail": f"unreadable multichip artifact {new_path}",
        })
        return out
    rec = {
        "level": "bench", "query": "multichip",
        "old_ratio": old.get("mesh_vs_oracle_wall_ratio"),
        "new_ratio": new.get("mesh_vs_oracle_wall_ratio"),
        "queries": new.get("matched"),
        "old_ok": old.get("ok"), "new_ok": new.get("ok"),
        "change": "headline",
    }
    r_old, r_new = rec["old_ratio"], rec["new_ratio"]
    if old.get("ok") and not new.get("ok"):
        rec["change"] = "regression"
    elif r_old is not None and r_new is not None and r_new > r_old * 1.25:
        rec["change"] = "regression"
    out.append(rec)
    return out


def _print_bench_rec(r):
    if r.get("query") == "budget_accuracy":
        def fmt(v):
            return "-" if v is None else f"{v:.3f}"

        hr = r.get("new_hit_rate")
        hr_s = "-" if hr is None else f"{hr:.1%}"
        flag = "  ** REGRESSED" if r["change"] == "regression" else ""
        print(f"== budgeter accuracy: median |log(est/actual)| "
              f"{fmt(r.get('old_err'))} -> {fmt(r.get('new_err'))} "
              f"(feedback hit rate {hr_s}){flag}")
        return
    if r.get("query") == "multichip":
        old_s = "-" if r.get("old_ratio") is None else f"{r['old_ratio']:.3f}"
        new_s = "-" if r.get("new_ratio") is None else f"{r['new_ratio']:.3f}"
        flag = "  ** REGRESSED" if r["change"] == "regression" else ""
        ok = "ok" if r.get("new_ok") else "NOT OK"
        print(f"== multichip mesh-vs-oracle wall ratio: {old_s} -> {new_s} "
              f"over {r.get('queries')} matched queries ({ok}){flag}")
        return
    old_s = "-" if r["old_ratio"] is None else f"{r['old_ratio']:.3f}"
    flag = "  ** REGRESSED" if r["change"] == "regression" else ""
    above = (
        "  (still above parity — target < 1.0)"
        if (r["new_ratio"] or 0) > 1.0
        else ""
    )
    print(f"== sqlite_shared ratio: {old_s} -> {r['new_ratio']:.3f} over "
          f"{r['queries']} shared queries{flag}{above}")


def _render_compare(regs, ratio, min_ms):
    # the sqlite_shared headline always prints, regressed or not (the
    # ratio is published every round until it crosses 1.0)
    headline = [r for r in regs if r["change"] == "headline"]
    regs = [r for r in regs if r["change"] != "headline"]
    for r in headline:
        _print_bench_rec(r)
    if not regs:
        print(f"== no regressions (threshold: {ratio:.2f}x and "
              f">= {min_ms:.0f} ms)")
        return
    print(f"== {len(regs)} regression(s) (threshold: {ratio:.2f}x and "
          f">= {min_ms:.0f} ms)")
    for r in regs:
        if r["change"] == "status_change":
            print(f"   {r['query']}: {r['detail']}")
        elif r.get("level") == "bench":
            _print_bench_rec(r)
        elif r["level"] == "query":
            print(f"   {r['query']}: wall {r['old_ms']:,.1f} -> "
                  f"{r['new_ms']:,.1f} ms ({r['ratio']:.2f}x)")
        else:
            print(f"   {r['query']}/{r['node']}: excl {r['old_ms']:,.1f} -> "
                  f"{r['new_ms']:,.1f} ms ({r['ratio']:.2f}x)")


def compact_main(argv=None) -> int:
    """`profile compact`: fold closed rotation segments into summary
    artifacts + drop the raw spans (obs.reader.compact_trace_dir)."""
    parser = argparse.ArgumentParser(
        prog="profile compact",
        description="fold closed trace-rotation segments into per-app "
        "compact-<app>.json summary artifacts and delete the raw files",
    )
    parser.add_argument("trace_dir", help="trace directory to compact")
    parser.add_argument(
        "--all", action="store_true", dest="fold_open",
        help="also fold each chain's open tail segment (post-run "
        "compaction; default keeps the highest-seq segment, which a "
        "live tracer may still be appending to)",
    )
    parser.add_argument(
        "--dry_run", action="store_true",
        help="report what would fold without writing or deleting",
    )
    args = parser.parse_args(argv)
    # --dry_run rides the SAME selection + readability classification as
    # the real run (reader.compact_trace_dir) — the preview cannot drift
    folded, skipped = R.compact_trace_dir(
        args.trace_dir, fold_open=args.fold_open, dry_run=args.dry_run
    )
    for app, files in folded:
        if args.dry_run:
            for f in files:
                print(f"compact: would fold {f}")
        else:
            print(
                f"compact: {app}: folded {len(files)} segment(s) into "
                f"compact-{app}.json"
            )
    for path, reason in skipped:
        verb = "would skip" if args.dry_run else "skipped (left in place)"
        print(f"compact: {verb} {path}: {reason}", file=sys.stderr)
    if not folded and not skipped:
        print("compact: nothing to fold")
    return 1 if skipped else 0


def main(argv=None):
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "compact":
        rc = compact_main(argv[1:])
        if rc:
            sys.exit(rc)
        return
    parser = argparse.ArgumentParser(
        description="aggregate nds-tpu event logs into operator-level "
        "profiles; compare two runs for regressions"
    )
    parser.add_argument(
        "paths", nargs="*",
        help="event-log files or trace directories (events-*.jsonl)",
    )
    parser.add_argument(
        "--compare", nargs=2, metavar=("OLD", "NEW"),
        help="A/B mode: two event logs / trace dirs to diff",
    )
    parser.add_argument(
        "--bench", nargs=2, metavar=("OLD", "NEW"),
        help="bench artifacts (saved compact OUT lines / driver captures) "
        "to diff the sqlite_shared headline ratio, alongside or instead "
        "of --compare",
    )
    parser.add_argument("--top", type=int, default=10,
                        help="top-N hottest operators (10)")
    parser.add_argument("--per_query", action="store_true",
                        help="print the per-operator table for every query")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the aggregate as JSON instead of text")
    parser.add_argument("--check", action="store_true",
                        help="exit 2 on any schema problem (CI gate); "
                        "malformed JSON lines always exit 2; failure-"
                        "bundle paths are structurally validated")
    parser.add_argument("--critical-path", "--critical_path",
                        action="store_true", dest="critical_path",
                        help="attribute per-query wall time to named "
                        "causes (and name the mesh straggler device) "
                        "instead of the operator breakdown")
    parser.add_argument("--accuracy", action="store_true",
                        help="report budgeter est-vs-actual error "
                        "distributions per operator class with the worst "
                        "misestimates, from raw op_spans annotated by the "
                        "plan-feedback loop, instead of the operator "
                        "breakdown")
    parser.add_argument("--min_attributed", type=float, metavar="FRAC",
                        help="with --critical-path: exit 1 when any "
                        "query's attributed wall share is below FRAC "
                        "(the CI diagnosis gate)")
    parser.add_argument("--min_exec_cache_hit_rate", type=float,
                        metavar="RATE",
                        help="exit 1 when the run's fused-executable cache "
                        "hit rate is below RATE (or no exec_cache events "
                        "were recorded at all) — the ci/tier1-check "
                        "microbench guard")
    parser.add_argument("--ratio", type=float, default=1.25,
                        help="compare: flag when new >= old * ratio (1.25)")
    parser.add_argument("--min_ms", type=float, default=50.0,
                        help="compare: minimum absolute delta in ms (50)")
    parser.add_argument("--fail_on_regression", action="store_true",
                        help="compare: exit 1 when regressions are flagged")
    args = parser.parse_args(argv)

    if args.compare or args.bench:
        regs = []
        if args.compare:
            old_prof = _load_profile([args.compare[0]], args.check)
            new_prof = _load_profile([args.compare[1]], args.check)
            regs = R.compare_profiles(
                old_prof, new_prof, ratio=args.ratio, min_ms=args.min_ms
            )
            # budgeter-accuracy delta rides every A/B compare: mean
            # |log(est/actual)| from the mergeable feedback summaries
            # (works on compacted dirs; --accuracy needs raw spans)
            e_old = R.feedback_err_mean(old_prof)
            e_new = R.feedback_err_mean(new_prof)
            if e_old is not None or e_new is not None:
                rec = {
                    "level": "bench", "query": "budget_accuracy",
                    "old_err": (
                        None if e_old is None else round(e_old, 4)
                    ),
                    "new_err": (
                        None if e_new is None else round(e_new, 4)
                    ),
                    "old_hit_rate": R.feedback_hit_rate(old_prof),
                    "new_hit_rate": R.feedback_hit_rate(new_prof),
                    "change": "headline",
                }
                if (
                    e_old is not None and e_new is not None
                    and e_new > e_old * 1.25 and e_new - e_old >= 0.1
                ):
                    rec["change"] = "regression"
                regs.append(rec)
        if args.bench:
            # artifact-type detection: a MULTICHIP round carries n_devices
            # (driver wrapper or mesh-gate metrics block); everything else
            # is a bench OUT line with the sqlite_shared headline. EITHER
            # side identifying as multichip routes here — an unreadable
            # NEW artifact (gate died before writing) must land on
            # _compare_multichip's fail-soft status_change record, not on
            # the sqlite loader's bare open()
            objs = [_load_multichip(p) for p in args.bench]
            if any(
                isinstance(o, dict) and "n_devices" in o for o in objs
            ):
                regs.extend(_compare_multichip(*args.bench))
            else:
                regs.extend(_compare_sqlite_shared(*args.bench))
                # accuracy headline beside the sqlite_shared ratio (bench
                # round arbitration: budgeter error must shrink)
                regs.extend(_compare_bench_accuracy(*args.bench))
        if args.as_json:
            print(json.dumps({"regressions": regs}, indent=2))
        else:
            _render_compare(regs, args.ratio, args.min_ms)
        bad = [r for r in regs if r["change"] != "headline"]
        if bad and args.fail_on_regression:
            sys.exit(1)
        return
    if not args.paths:
        parser.error("give event-log paths, or --compare OLD NEW")
    # flight-recorder failure bundles validate structurally; they are not
    # event logs and must not be parsed as one
    bundles = [p for p in args.paths if FL.is_bundle_path(p)]
    args.paths = [p for p in args.paths if not FL.is_bundle_path(p)]
    bundle_problems = 0
    for b in bundles:
        try:
            obj = FL.read_bundle(b)
            problems = FL.validate_bundle(obj)
        except (OSError, ValueError) as exc:
            problems = [str(exc)]
            obj = None
        for p in problems[:20]:
            print(f"profile: bundle {b}: {p}", file=sys.stderr)
        bundle_problems += len(problems)
        if obj is not None and not problems:
            print(
                f"== bundle {b}: reason {obj['reason']}, trace "
                f"{obj['trace_id']}, query {obj.get('query')}, "
                f"{len(obj['events'])} ring event(s)"
            )
    if bundle_problems and args.check:
        sys.exit(2)
    if not args.paths:
        return  # bundle-only invocation
    if args.critical_path or args.accuracy:
        # raw events only: compaction artifacts hold pre-aggregated
        # profiles, not the spans the reconstruction needs
        try:
            events = R.read_events(args.paths, strict=True)
        except (R.MalformedEventError, OSError) as exc:
            print(f"profile: {exc}", file=sys.stderr)
            sys.exit(2)
        if args.check:
            problems = R.validate_events(events)
            if problems:
                for p in problems[:20]:
                    print(f"profile: schema: {p}", file=sys.stderr)
                sys.exit(2)
        if args.accuracy:
            acc = _accuracy_report(events, args.top)
            if args.as_json:
                print(json.dumps(acc, indent=2))
            else:
                _render_accuracy(acc)
            return
        cp = CP.critical_path(events)
        if args.as_json:
            print(json.dumps(cp, indent=2))
        else:
            CP.render(cp)
        if args.min_attributed is not None:
            worst = CP.min_attributed_frac(cp)
            if worst is None or worst < args.min_attributed:
                print(
                    f"profile: critical-path attribution "
                    f"{'absent' if worst is None else f'{worst:.1%}'} is "
                    f"below the required {args.min_attributed:.1%}",
                    file=sys.stderr,
                )
                sys.exit(1)
        return
    prof = _load_profile(args.paths, args.check)
    if args.as_json:
        print(json.dumps(prof, indent=2))
    else:
        _render_profile(prof, args.top, args.per_query)
    if args.min_exec_cache_hit_rate is not None:
        rate = R.exec_cache_hit_rate(prof)
        if rate is None:
            print(
                "profile: no exec_cache events recorded (fusion disabled "
                "or tracing broken) — failing the hit-rate gate",
                file=sys.stderr,
            )
            sys.exit(1)
        if rate < args.min_exec_cache_hit_rate:
            print(
                f"profile: executable-cache hit rate {rate:.1%} below the "
                f"required {args.min_exec_cache_hit_rate:.1%}",
                file=sys.stderr,
            )
            sys.exit(1)


if __name__ == "__main__":
    main()
