"""Query-stream generation CLI.

Counterpart of the reference's stream generator (reference:
nds/nds_gen_query_stream.py — generate_query_streams :42-89, single-template
mode :115-119, seedable --rngseed per TPC-DS 4.3.1).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from nds_tpu.datagen import query_streams


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="Generate TPC-DS-style permuted query streams"
    )
    parser.add_argument("--template_dir", default=None,
                        help="directory containing queryN.tpl templates")
    parser.add_argument("--scale", type=float, required=True,
                        help="benchmark scale factor (parameters scale with it)")
    parser.add_argument("--output_dir", required=True)
    parser.add_argument("--streams", type=int, default=1,
                        help="number of streams (query_0.sql .. query_{n-1}.sql)")
    parser.add_argument("--rngseed", type=int, default=19620718,
                        help="random seed; TPC-DS 4.3.1 requires the load-test "
                        "end timestamp for a compliant run")
    parser.add_argument("--template", default=None,
                        help="generate a single query from this template "
                        "(e.g. query3.tpl)")
    args = parser.parse_args(argv)

    if args.template:
        path = query_streams.generate_single(
            args.output_dir, args.template, args.scale, args.rngseed,
            args.template_dir,
        )
        print(f"wrote {path}")
    else:
        qnums = query_streams.generate_streams(
            args.output_dir, args.streams, args.scale, args.rngseed,
            args.template_dir,
        )
        print(
            f"wrote {args.streams} stream(s) x {len(qnums)} queries to "
            f"{args.output_dir}"
        )


if __name__ == "__main__":
    main()
