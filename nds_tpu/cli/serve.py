"""`nds-tpu-submit serve`: the long-lived multi-tenant query service.

    python -m nds_tpu.cli.serve <warehouse_path>
        [--input_format lakehouse] [--port 8080] [--property_file F]
        [--stream query_0.sql] [--job_dir DIR] [--floats]

One process, one warm Session, one HTTP listener (shared with /metrics,
/statusz, /healthz — obs/httpserv.py):

    POST /query    {"sql": ...} or {"template": "query3", "params": {}}
                   + optional offset/limit; X-NDS-Tenant header keys the
                   per-tenant accounting. 429 = admission rejected (body
                   carries the modeled peak bytes) or shed (Retry-After).
    POST /stream   {"stream": <server-side stream file>} -> 202 job
    GET  /jobs/<id>  job progress (resumable, bench_state pattern)
    POST /drain    stop admitting, finish in-flight, flip /healthz to 503
    POST /reload   re-resolve the warehouse (fresh lakehouse heads)

SIGTERM/SIGINT drains before exit, so a rolling restart loses no
in-flight work inside the drain budget.
"""

from __future__ import annotations

import argparse
import os
import signal
import threading

from ..check import check_version
from ..engine.session import Session
from ..obs import metrics as obs_metrics
from ..power import gen_sql_from_stream, load_properties
from ..serve.service import QueryService, resolve_serve_port


def build_service(args):
    """Sessions + service + listener from CLI args. Returns
    (service, server) — split from main() so tests and tools/serve_bench
    drive the real construction path without a subprocess."""
    conf = {"app.name": "NDS - Serve"}
    if args.property_file:
        conf.update(load_properties(args.property_file))
    if args.port is not None:
        conf["engine.serve_port"] = args.port
    port = resolve_serve_port(conf)
    if port is None:
        raise SystemExit(
            "serve: no port configured (pass --port, set engine.serve_port "
            "in the property file, or NDS_SERVE_PORT; 0 binds ephemeral)"
        )
    # ONE listener: serve rides the process-wide metrics endpoint, so the
    # query routes, /metrics, /statusz and /healthz share a port
    conf["engine.metrics_port"] = port
    if args.job_dir:
        conf["engine.serve_job_dir"] = args.job_dir
    # fleet AOT warm-up: every replica pointed at ONE shared cache dir
    # deserializes the executables `cache warm --fleet` compiled once,
    # instead of paying a per-host compile (the cache is multi-process
    # safe). getattr: older Namespace callers (tools, tests) predate it.
    aot_dir = getattr(args, "aot_cache_dir", None)
    if aot_dir:
        conf["engine.aot_cache_dir"] = aot_dir
        os.environ["NDS_AOT_CACHE_DIR"] = aot_dir
    # fleet cardinality feedback: same wiring shape. A shared
    # --aot_cache_dir already shares feedback implicitly (the store
    # defaults to <aot dir>/feedback); this flag points replicas at a
    # standalone store when the AOT dir is per-host or disabled.
    fb_dir = getattr(args, "feedback_dir", None)
    if fb_dir:
        conf["engine.feedback_dir"] = fb_dir
        os.environ["NDS_FEEDBACK_DIR"] = fb_dir
    use_decimal = not args.floats
    session = Session(use_decimal=use_decimal, conf=conf)
    # DML runs on its own session (own caches, own last_plan_budget) so
    # the writer path can never perturb the warm read tier's planning;
    # both share the process lease table, so reader pins stay vacuum-safe
    wconf = dict(conf)
    wconf["app.name"] = "NDS - Serve writer"
    writer = Session(use_decimal=use_decimal, conf=wconf)

    def register(target):
        target.register_nds_tables(
            args.warehouse_path, fmt=args.input_format
        )
        return len(target.catalog.entries)

    n = register(session)
    register(writer)
    if n == 0:
        raise SystemExit(
            f"serve: no tables found under {args.warehouse_path!r} "
            f"(format {args.input_format})"
        )
    templates = {}
    if args.stream:
        templates = gen_sql_from_stream(args.stream)

    def reload_fn():
        return max(register(session), register(writer))

    service = QueryService(
        session, writer_session=writer, templates=templates,
        reload_fn=reload_fn,
    )
    server = obs_metrics.active_server()
    if server is None:
        raise SystemExit(
            f"serve: could not bind port {port} (already in use?) — a "
            f"query service without a listener is useless"
        )
    server.attach_app(service)
    return service, server


def main(argv=None):
    check_version()
    parser = argparse.ArgumentParser(
        description="long-lived multi-tenant query service over a warehouse"
    )
    parser.add_argument(
        "warehouse_path", help="warehouse root (transcoded tables)"
    )
    parser.add_argument(
        "--input_format", default="lakehouse",
        choices=("parquet", "orc", "csv", "lakehouse"),
        help="warehouse table format (default: lakehouse — DML needs it)",
    )
    parser.add_argument(
        "--port", type=int, default=None,
        help="HTTP port (0 = ephemeral; default: engine.serve_port / "
        "NDS_SERVE_PORT)",
    )
    parser.add_argument(
        "--property_file", help="property file for engine configuration"
    )
    parser.add_argument(
        "--stream",
        help="generated query stream file whose entries become named "
        "templates for POST /query {'template': ...}",
    )
    parser.add_argument(
        "--job_dir", help="stream-job checkpoint directory "
        "(engine.serve_job_dir)",
    )
    parser.add_argument(
        "--floats", action="store_true",
        help="use double instead of decimal for decimal-typed columns",
    )
    parser.add_argument(
        "--aot_cache_dir",
        help="shared AOT executable cache dir (engine.aot_cache_dir): "
        "point every fleet replica at the dir `cache warm --fleet` "
        "filled so N replicas pay one compile, not N",
    )
    parser.add_argument(
        "--feedback_dir",
        help="shared cardinality feedback store dir "
        "(engine.feedback_dir): replicas record and consume learned "
        "per-node actuals fleet-wide; defaults to <aot_cache_dir>/"
        "feedback when an AOT dir is set",
    )
    args = parser.parse_args(argv)
    service, server = build_service(args)
    stop = threading.Event()

    def _on_signal(signum, frame):
        print(f"serve: signal {signum}; draining "
              f"(budget {service.drain_timeout_s:.0f}s)", flush=True)
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    print(
        f"serve: listening on {server.host}:{server.port} "
        f"({service.workers} workers, row cap {service.row_cap}, "
        f"{len(service.templates)} templates, pid {os.getpid()})",
        flush=True,
    )
    stop.wait()
    service.handle_drain()
    print("serve: drained; bye", flush=True)


if __name__ == "__main__":
    main()
