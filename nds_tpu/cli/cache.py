"""Operator CLI for the persistent AOT executable cache
(engine/aotcache.py): inspect it, warm it ahead of serving, clean it up.

    nds-tpu-submit cache stats  [--cache_dir D] [--json]
    nds-tpu-submit cache warm   <data_dir> <stream.sql> [--cache_dir D]
                                [--format parquet|csv|lakehouse]
                                [--queries q1,q2] [--json]
    nds-tpu-submit cache vacuum [--cache_dir D] [--all] [--json]

`stats` reports entry count/bytes vs budget, quarantine/temp counts, and
persisted promotion verdicts. `warm` runs a query stream's templates once
against a registered warehouse with the cache armed, so every pipeline
executable (and promotion verdict) is ON DISK before a serving fleet's
first request — the fleet's cold start then deserializes instead of
compiling (the production half of "compile each pipeline once, ever";
the SF10 isolation parent does the same for its children through
NDS_AOT_CACHE_DIR). `vacuum` sweeps dead-pid temp orphans + quarantined
entries and re-enforces the byte budget; `--all` drops every committed
entry too (the operator reset after e.g. an engine upgrade soak).
"""

import argparse
import json
import os
import sys
import time


def _resolve_dir(args) -> str:
    from ..engine.aotcache import resolve_aot_cache_dir

    d = args.cache_dir or resolve_aot_cache_dir()
    if not d:
        print("cache: AOT cache disabled (NDS_AOT_CACHE_DIR=0) and no "
              "--cache_dir given", file=sys.stderr)
        sys.exit(2)
    return d


def _dir_stats(d: str) -> dict:
    from ..engine.aotcache import (
        AotCache,
        PromotionStore,
        resolve_aot_cache_bytes,
    )

    cache = AotCache(d, resolve_aot_cache_bytes(None, d))
    entries, total = cache.usage()
    names = os.listdir(d) if os.path.isdir(d) else []
    st = {
        "cache_dir": d,
        "entries": entries,
        "bytes": total,
        "budget_bytes": cache.budget,
        "quarantined": sum(1 for n in names if n.startswith("quarantine-")),
        "temps": sum(1 for n in names if ".tmp-" in n),
        "promotions": PromotionStore(d).count(),
    }
    # learned-cardinality feedback store: rides the cache dir as a
    # subdirectory (analysis/feedback.py), so the same stats/vacuum flow
    # covers it — absent dir means the fleet never recorded anything
    fb_dir = os.path.join(d, "feedback")
    if os.path.isdir(fb_dir):
        from ..analysis.feedback import FeedbackStore, resolve_feedback_bytes

        store = FeedbackStore(fb_dir, resolve_feedback_bytes(None, fb_dir))
        f_entries, f_bytes = store.usage()
        f_names = os.listdir(fb_dir)
        st["feedback"] = {
            "dir": fb_dir,
            "entries": f_entries,
            "bytes": f_bytes,
            "budget_bytes": store.budget,
            "quarantined": sum(
                1 for n in f_names if n.startswith("quarantine-")
            ),
            "temps": sum(1 for n in f_names if ".tmp-" in n),
        }
    return st


def stats_main(args) -> int:
    st = _dir_stats(_resolve_dir(args))
    if args.as_json:
        print(json.dumps(st, indent=2))
        return 0
    print(f"== aot cache {st['cache_dir']}")
    print(f"   entries      {st['entries']} "
          f"({st['bytes']:,} B of {st['budget_bytes']:,} B budget)")
    print(f"   quarantined  {st['quarantined']}")
    print(f"   temps        {st['temps']}")
    print(f"   promotions   {st['promotions']} persisted verdict(s)")
    fb = st.get("feedback")
    if fb:
        print(f"== feedback store {fb['dir']}")
        print(f"   entries      {fb['entries']} learned cardinalit(ies) "
              f"({fb['bytes']:,} B of {fb['budget_bytes']:,} B budget)")
        print(f"   quarantined  {fb['quarantined']}")
        print(f"   temps        {fb['temps']}")
    return 0


def warm_main(args) -> int:
    os.environ["NDS_AOT_CACHE_DIR"] = _resolve_dir(args)
    from ..engine.session import Session
    from ..power import gen_sql_from_stream

    sess = Session(conf={"engine.aot_cache_dir": os.environ["NDS_AOT_CACHE_DIR"]})
    sess.register_nds_tables(args.data_dir, fmt=args.format)
    if not sess.catalog.entries:
        print(f"cache warm: no tables found under {args.data_dir}",
              file=sys.stderr)
        return 2
    queries = gen_sql_from_stream(args.stream)
    if args.queries:
        keep = {s.strip() for s in args.queries.split(",") if s.strip()}
        queries = {n: q for n, q in queries.items() if n in keep}
    ok, failed = 0, {}
    t0 = time.perf_counter()
    for name, q in queries.items():
        try:
            r = sess.run_script(q)
            if r is not None:
                r.collect()
            ok += 1
        except Exception as exc:  # warm what warms; report the rest
            failed[name] = str(exc)[:200]
    aot = sess.aot_cache
    report = {
        "queries_warmed": ok,
        "queries_failed": len(failed),
        "wall_sec": round(time.perf_counter() - t0, 2),
        "aot": dict(aot.stats) if aot is not None else None,
        "stats": _dir_stats(_resolve_dir(args)),
    }
    if failed:
        report["failed"] = failed
    if getattr(args, "fleet", False):
        # fleet warm-up contract: ONE warm pass fills the shared dir,
        # every replica deserializes from it — print the exact flag the
        # replica launch needs so the deploy recipe is copy-pasteable
        d = os.environ["NDS_AOT_CACHE_DIR"]
        report["fleet"] = {
            "cache_dir": d,
            "replica_flag": f"--aot_cache_dir {d}",
        }
    if args.as_json:
        print(json.dumps(report, indent=2))
    else:
        a = report["aot"] or {}
        print(f"cache warm: {ok} template(s) warmed in "
              f"{report['wall_sec']}s ({len(failed)} failed); "
              f"{a.get('stores', 0)} executable(s) newly stored, "
              f"{a.get('disk_hits', 0)} already on disk; "
              f"{report['stats']['entries']} entr(ies) / "
              f"{report['stats']['bytes']:,} B total")
        for n, e in failed.items():
            print(f"   failed {n}: {e}", file=sys.stderr)
        if "fleet" in report:
            print("cache warm --fleet: shared dir ready; start each "
                  "replica with\n"
                  f"   nds-tpu-submit serve <warehouse> "
                  f"{report['fleet']['replica_flag']}\n"
                  "so N replicas pay one compile, not N")
    if queries and ok == 0:
        # "warm what warms" tolerates stragglers, but a warm run where
        # NOTHING warmed means the fleet will cold-start exactly as if
        # this step never ran — a deploy pipeline must see that
        print("cache warm: every template failed; cache is still cold",
              file=sys.stderr)
        return 1
    return 0


def vacuum_main(args) -> int:
    from ..engine.aotcache import AotCache, resolve_aot_cache_bytes

    d = _resolve_dir(args)
    cache = AotCache(d, resolve_aot_cache_bytes(None, d))
    removed = cache.vacuum(drop_all=args.drop_all)
    # the feedback store rides the cache dir: one vacuum covers both
    # (--all drops learned cardinalities too — the operator reset after
    # e.g. a data reload that keeps the same lake version)
    fb_removed = 0
    fb_dir = os.path.join(d, "feedback")
    if os.path.isdir(fb_dir):
        from ..analysis.feedback import FeedbackStore, resolve_feedback_bytes

        store = FeedbackStore(fb_dir, resolve_feedback_bytes(None, fb_dir))
        fb_removed = store.vacuum(drop_all=args.drop_all)
    st = _dir_stats(d)
    if args.as_json:
        print(json.dumps({
            "removed": removed, "feedback_removed": fb_removed, "stats": st,
        }, indent=2))
    else:
        print(f"cache vacuum: removed {removed} file(s) "
              f"(+{fb_removed} feedback); "
              f"{st['entries']} entr(ies) / {st['bytes']:,} B remain")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="nds-tpu-submit cache",
        description="inspect / warm / vacuum the persistent AOT "
        "executable cache",
    )
    sub = parser.add_subparsers(dest="cmd", required=True)

    def _common(p):
        p.add_argument("--cache_dir", default=None,
                       help="cache directory (default: the engine's "
                       "resolved NDS_AOT_CACHE_DIR)")
        p.add_argument("--json", action="store_true", dest="as_json",
                       help="emit the report as JSON")

    p_stats = sub.add_parser("stats", help="entry/bytes/promotions report")
    _common(p_stats)
    p_warm = sub.add_parser(
        "warm",
        help="run a stream's templates once so every executable is on "
        "disk before serving",
    )
    p_warm.add_argument("data_dir", help="warehouse directory to register")
    p_warm.add_argument("stream", help="query stream file (query_N.sql)")
    p_warm.add_argument("--format", default="parquet",
                        choices=["parquet", "csv", "lakehouse", "orc"],
                        help="warehouse format (parquet)")
    p_warm.add_argument("--queries", default=None,
                        help="comma-separated template subset")
    p_warm.add_argument("--fleet", action="store_true",
                        help="fleet warm-up: report the --aot_cache_dir "
                        "flag every serve replica should launch with so "
                        "N replicas share this one warmed dir")
    _common(p_warm)
    p_vac = sub.add_parser(
        "vacuum",
        help="sweep temp orphans + quarantines, re-enforce the budget",
    )
    p_vac.add_argument("--all", action="store_true", dest="drop_all",
                       help="also drop every committed entry (full reset)")
    _common(p_vac)

    args = parser.parse_args(argv)
    if args.cmd == "stats":
        return stats_main(args)
    if args.cmd == "warm":
        return warm_main(args)
    return vacuum_main(args)


if __name__ == "__main__":
    sys.exit(main())
