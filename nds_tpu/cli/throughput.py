"""Throughput Test CLI (reference: nds/nds-throughput:18-23).

    python -m nds_tpu.cli.throughput <input_prefix> <stream_dir> <streams>
        <time_log_base> [--input_format ...] [--floats] ...

`streams` is a comma-separated list of stream numbers, e.g. "1,2,3,4";
stream n reads <stream_dir>/query_<n>.sql and writes <time_log_base>_<n>.csv.
"""

import argparse
import os

from ..check import check_version
from ..throughput import run_throughput


def main(argv=None):
    check_version()
    parser = argparse.ArgumentParser()
    parser.add_argument("input_prefix", help="warehouse root path")
    parser.add_argument("stream_dir", help="directory with query_<n>.sql files")
    parser.add_argument(
        "streams",
        help="comma separated stream numbers to run concurrently, e.g. 1,2",
    )
    parser.add_argument(
        "time_log_base",
        help="per-stream time logs are written to <base>_<n>.csv",
    )
    parser.add_argument(
        "--input_format",
        choices=["parquet", "csv", "orc", "lakehouse"],
        default="parquet",
    )
    parser.add_argument("--property_file")
    parser.add_argument("--json_summary_folder")
    parser.add_argument("--output_prefix")
    parser.add_argument("--output_format", default="parquet")
    parser.add_argument("--floats", action="store_true")
    parser.add_argument(
        "--mode", choices=["thread", "process"], default="thread",
        help="stream concurrency: threads in one process (shared in-memory "
        "compile cache) or one forked Power Run per stream (the reference "
        "nds-throughput shape; shares the persistent XLA cache)",
    )
    parser.add_argument(
        "--sub_queries", type=lambda s: [x.strip() for x in s.split(",")],
        help="comma separated subset of queries to run in each stream",
    )
    parser.add_argument(
        "--query_timeout",
        type=float,
        help="per-query watchdog budget in seconds (a hung query becomes a "
        "classified 'timeout' failure instead of stalling the stream's Ttt "
        "window); also bounds process-mode child waits",
    )
    args = parser.parse_args(argv)
    nums = [int(s) for s in args.streams.split(",") if s.strip()]
    stream_paths = {
        n: os.path.join(args.stream_dir, f"query_{n}.sql") for n in nums
    }
    ttt = run_throughput(
        args.input_prefix,
        stream_paths,
        args.time_log_base,
        input_format=args.input_format,
        use_decimal=not args.floats,
        property_file=args.property_file,
        json_summary_folder=args.json_summary_folder,
        output_path=args.output_prefix,
        output_format=args.output_format,
        mode=args.mode,
        sub_queries=args.sub_queries,
        query_timeout=args.query_timeout,
    )
    print(f"====== Throughput Test Time: {ttt} seconds ======")


if __name__ == "__main__":
    main()
