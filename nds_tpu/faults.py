"""Failure-domain core: fault injection + a classified failure taxonomy.

The reference harness inherits its failure semantics from Spark — executor
loss becomes a task retry, a hung task is killed by the scheduler, and the
TaskFailureListener chain surfaces what happened (reference:
nds/jvm_listener/.../TaskFailureListener.scala:13-19). This engine has no
scheduler underneath it, so the equivalent failure domain lives here:

* a deterministic fault-injection registry (chaos-harness style) so every
  recovery path in the harness can be exercised on demand instead of hoping
  it fires correctly under a real OOM;
* a failure taxonomy (`classify`) replacing ad-hoc string matching, so the
  retry/degradation ladder in report.py and the phase retries in
  full_bench.py agree on what is transient and what is deterministic.

Fault spec grammar (conf `engine.fault_spec` / env `NDS_FAULT_SPEC`):

    spec  := rule (';' rule)*
    rule  := kind ':' site [':' arg]
    kind  := oom | hostoom | io | hang | crash
    site  := free-form label matched against injection points

e.g. ``oom:query5:1;io:store_sales:2;hang:query9:30;crash:power_test``.

`arg` is the number of times the rule fires (default 1) — except for
`hang`, where it is the number of seconds to sleep (the rule fires once).
Injection sites fired around the codebase:

    <query_name>          power/maintenance driver, per stream entry
    exec:<query_name>     executor root, inside the engine proper
    load:<table_name>     catalog device load of a registered table
    commit:<table_name>   lakehouse manifest commit
    stage:<table_name>    lakehouse staged-data write (io/crash kinds only)
    manifest:<table_name> lakehouse manifest read (io/crash kinds only)
    vacuum:<table_name>   lakehouse vacuum delete (io/crash kinds only)
    catalog:commit        fleet-catalog commit arbitration; on the tcp
                          coordinator it fires BETWEEN the WAL intent and
                          the manifest publish — the crash-mid-commit
                          chaos window (io/hang/crash kinds only)
    catalog:lease         fleet-catalog lease/writer registration
                          (io/hang/crash kinds only)
    catalog:fence         fleet-catalog fence bump during vacuum
                          (io/hang/crash kinds only)
    <phase_name>          full_bench phase runner (e.g. power_test)
    serve:admit           serve-mode admission path (request is SHED 429,
                          never the server)
    serve:exec            serve-mode request execution (walks the same
                          BenchReport ladder a bench query would)
    replica:kill          serve-mode SELECT execution, fleet family
                          (hang/crash kinds only): hang holds the request
                          open for a deterministic external SIGKILL window
                          (tools/fleet_check.py); crash kills the
                          connection thread mid-request so the socket
                          closes with no reply — what a mid-stream replica
                          death looks like to the router
    route:pick            router replica selection (serve/router.py): an
                          injected failure sheds the request at the edge,
                          never the router process (io/hang/crash kinds)
    route:forward         router -> replica forward hop: an injected io
                          failure looks like a dead replica and exercises
                          the failover retry budget (io/hang/crash kinds)
    catalog:unreachable   tcp catalog client transport (HttpCatalog._post
                          entry): the call fails CatalogUnreachableError
                          without touching the wire — coordinator-loss
                          drills without killing a process (io/hang kinds)
    any path substring    fs_open (fired via maybe_fire_path)

The registry is a module singleton; when no spec is installed every
injection point is a single ``is None`` check (zero-cost in production).
Counts decrement under a lock so concurrent throughput streams share one
deterministic budget.
"""

from __future__ import annotations

import os
import threading
import time
from .engine.lockdebug import make_lock

# ---------------------------------------------------------------------------
# failure taxonomy
# ---------------------------------------------------------------------------

DEVICE_OOM = "device_oom"  # accelerator memory exhausted (recover + retry)
HOST_OOM = "host_oom"  # host allocation failed (recover + retry)
IO_TRANSIENT = "io_transient"  # flaky storage/network (backoff + retry)
COMMIT_CONFLICT = "commit_conflict"  # OCC loser (re-run the transaction)
TIMEOUT = "timeout"  # watchdog fired (no retry: likely hangs again)
PLANNER = "planner"  # parse/bind/exec logic error (deterministic)
DATA = "data"  # malformed input data (deterministic)
UNKNOWN = "unknown"

#: kinds a retry can plausibly fix; everything else fails fast
RETRYABLE = frozenset({DEVICE_OOM, HOST_OOM, IO_TRANSIENT, COMMIT_CONFLICT})

_DEVICE_OOM_PAT = ("RESOURCE_EXHAUSTED", "Out of memory allocating")
_HOST_OOM_PAT = (
    "MemoryError",
    "Cannot allocate memory",
    "std::bad_alloc",
    "Unable to allocate",
    "host OOM",  # InjectedHostOOM renders as "injected host OOM at ..."
)
_TIMEOUT_PAT = ("watchdog", "DEADLINE_EXCEEDED")
_IO_PAT = (
    "transient io",
    "Connection reset",
    "Connection aborted",
    "ConnectionError",
    "Broken pipe",
    "Temporary failure",
    "temporarily unavailable",
    "EAGAIN",
    "timed out",
    "TimeoutError",
    "SlowDown",
    "Slow Down",
    # anchored: a bare "503" would match row counts / shapes in unrelated
    # error text, and XLA InternalError is deterministic, not transient
    "HTTP 503",
    "503 Service",
    # spill-pool segment IO (engine/spill.py:SpillIOError): a failed
    # host-tier write/read is storage flakiness, not a query bug — the
    # ladder's io_backoff_retry rung owns it
    "SpillIOError",
    # fleet-catalog coordinator down (lakehouse/catalog.py
    # CatalogUnreachableError, a ConnectionError subclass — this pattern
    # covers re-rendered strings): writes back off and retry while pinned
    # reads, which never need the coordinator, keep serving
    "catalog unreachable",
)
# CommitConflictError (lakehouse/table.py): an optimistic lakehouse commit
# lost the publish race and could not rebase. The transaction never
# published, so re-running it against the fresh head is safe — the report
# ladder's commit_rebase_retry rung owns it (with jittered backoff). Checked
# before DATA: the conflict is a LakehouseError subclass, but it is the one
# lakehouse failure that is TRANSIENT, not deterministic.
_COMMIT_PAT = (
    "CommitConflictError", "concurrent commit conflict",
    # CatalogFencedError (lakehouse/catalog.py): a vacuum fenced this
    # writer's epoch — the transaction never published and re-runs with a
    # fresh registration, same rung as a lost CAS race
    "CatalogFencedError", "fenced by catalog",
)
# PlanVerifyError: the static plan verifier (analysis/verifier.py) found a
# structural invariant violation — deterministic, so the ladder fails fast.
# PlanBudgetError: admission control (analysis/budget.py) refused the plan
# statically — equally deterministic for a given catalog, same fail-fast.
_PLANNER_PAT = (
    "ParseError", "BindError", "ExecError", "SyntaxError", "PlanVerifyError",
    "PlanBudgetError",
)
_DATA_PAT = ("malformed", "LakehouseError", "schema mismatch", "Invalid value")


def classify(err) -> str:
    """Map an exception (or its rendered text) to a taxonomy kind.

    Order matters: the watchdog marker contains "timed out"-adjacent words,
    so TIMEOUT is checked before IO_TRANSIENT; device OOM before host OOM
    (XLA OOM text can mention allocation too)."""
    if isinstance(err, BaseException):
        text = f"{type(err).__name__}: {err}"
        if isinstance(err, MemoryError):
            return HOST_OOM
        if isinstance(err, (ConnectionError, TimeoutError)):
            return IO_TRANSIENT
    else:
        text = str(err)
    for pat in _DEVICE_OOM_PAT:
        if pat in text:
            return DEVICE_OOM
    for pat in _HOST_OOM_PAT:
        if pat in text:
            return HOST_OOM
    for pat in _TIMEOUT_PAT:
        if pat in text:
            return TIMEOUT
    for pat in _IO_PAT:
        if pat in text:
            return IO_TRANSIENT
    for pat in _COMMIT_PAT:
        if pat in text:
            return COMMIT_CONFLICT
    for pat in _PLANNER_PAT:
        if pat in text:
            return PLANNER
    for pat in _DATA_PAT:
        if pat in text:
            return DATA
    return UNKNOWN


def backoff_delays(retries: int, base: float, cap: float = 30.0):
    """Exponential backoff with full jitter: delay_i ~ U(0, base * 2**i],
    capped. Deterministic tests set base ~ 0 so the jitter vanishes."""
    import random

    for i in range(retries):
        yield random.uniform(0, min(base * (2 ** i), cap)) if base > 0 else 0.0


# ---------------------------------------------------------------------------
# injected fault exceptions
# ---------------------------------------------------------------------------


class FaultError(Exception):
    """Base for injected faults (except crash, which must not be caught)."""


class InjectedOOM(FaultError):
    """Renders with RESOURCE_EXHAUSTED so it classifies (and is handled)
    exactly like a real XLA device OOM."""


class InjectedHostOOM(FaultError, MemoryError):
    pass


class TransientIOError(FaultError, OSError):
    """Renders with 'transient io' so it classifies as IO_TRANSIENT."""


class InjectedCrash(BaseException):
    """Simulated process death. Derives from BaseException so it sails
    through every `except Exception` recovery layer (like a SIGKILL would):
    the phase subprocess exits nonzero, the orchestrator stops at its last
    checkpoint."""


# ---------------------------------------------------------------------------
# fault registry
# ---------------------------------------------------------------------------

_KINDS = ("oom", "hostoom", "io", "hang", "crash")


class FaultRule:
    __slots__ = ("kind", "site", "arg", "remaining")

    def __init__(self, kind: str, site: str, arg: float):
        self.kind = kind
        self.site = site
        self.arg = arg
        # hang sleeps `arg` seconds and fires once; others fire `arg` times
        self.remaining = 1 if kind == "hang" else int(arg)

    def __repr__(self):
        return f"FaultRule({self.kind}:{self.site}:{self.arg}, remaining={self.remaining})"


class FaultRegistry:
    def __init__(self, rules):
        self.rules = list(rules)
        self._lock = make_lock("FaultRegistry._lock")

    @classmethod
    def parse(cls, spec: str) -> "FaultRegistry":
        rules = []
        for part in str(spec).split(";"):
            part = part.strip()
            if not part:
                continue
            bits = part.split(":")
            if len(bits) < 2 or bits[0] not in _KINDS or not bits[1]:
                raise ValueError(
                    f"bad fault rule {part!r} (want kind:site[:arg] with "
                    f"kind in {_KINDS})"
                )
            kind = bits[0]
            # sites may themselves contain ':' (e.g. exec:query3); a rule's
            # trailing segment is the arg only if it parses as a number
            arg, site_bits = 1.0, bits[1:]
            if len(site_bits) > 1:
                try:
                    arg = float(site_bits[-1])
                    site_bits = site_bits[:-1]
                except ValueError:
                    pass
            rules.append(FaultRule(kind, ":".join(site_bits), arg))
        return cls(rules)

    def _claim(self, site: str, substring: bool, kinds=None):
        with self._lock:
            for r in self.rules:
                if r.remaining <= 0 or (kinds is not None and r.kind not in kinds):
                    continue
                hit = (r.site in site) if substring else (r.site == site)
                if hit:
                    r.remaining -= 1
                    return r
        return None

    def fire(self, site: str, substring: bool = False, kinds=None):
        r = self._claim(site, substring, kinds)
        if r is None:
            return
        # observability: record the injection in the bound tracer's event
        # stream before the fault takes effect (a crash rule still leaves
        # its own evidence behind — parents classify child deaths from it).
        # Lazy import: the registry only reaches here when a rule fires.
        from .obs import trace as _obs_trace

        tracer = _obs_trace.current()
        if tracer is not None:
            tracer.emit("fault_injected", site=site, fault_kind=r.kind)
        if r.kind == "hang":
            print(f"faults: injected hang at {site!r} for {r.arg:.0f}s")
            time.sleep(r.arg)
            return
        if r.kind == "oom":
            raise InjectedOOM(
                f"RESOURCE_EXHAUSTED: injected device OOM at {site!r}"
            )
        if r.kind == "hostoom":
            raise InjectedHostOOM(f"injected host OOM at {site!r}")
        if r.kind == "io":
            raise TransientIOError(f"injected transient io failure at {site!r}")
        if r.kind == "crash":
            # simulated process death: flush the flight-recorder ring
            # BEFORE raising, so the black box survives the crash the way
            # a real crash handler would leave one (the fault_injected
            # event above is already in the ring — parents classify the
            # death from the bundle even with no trace dir configured)
            try:
                from .obs import flight as _obs_flight

                # prefer the bound tracer's ring: it honors a conf-tier
                # engine.flight_recorder=off for the session that is
                # actually crashing; the module recorder is the fallback
                # for session-less sites. (The bundle DIR resolves at the
                # env tier here — this layer has no conf in hand; the
                # report-side flushes pass the session conf through.)
                tracer = _obs_trace.current()
                if tracer is not None:
                    rec = getattr(tracer, "ring", None)
                else:
                    rec = _obs_flight.recorder()
                if rec is not None:
                    ctx = getattr(tracer, "context", None)
                    rec.flush(
                        "crash",
                        trace_id=getattr(ctx, "trace_id", None),
                        query=current_scope(),
                    )
            except Exception:
                pass  # forensics must never mask the injected death
            raise InjectedCrash(f"injected crash at {site!r}")


# module singleton; None == injection disabled (the zero-cost path)
_registry: FaultRegistry | None = None
_installed_spec: str | None = None


def install(spec: str | None):
    """(Re)build the registry from a spec string; None/"" disables injection.
    Idempotent for an unchanged spec so that per-stream Session construction
    does not reset the shared fire counts mid-run."""
    global _registry, _installed_spec
    if spec == _installed_spec:
        return
    _installed_spec = spec
    _registry = FaultRegistry.parse(spec) if spec else None


def install_from_env(conf: dict | None = None):
    """Install from conf `engine.fault_spec`, falling back to NDS_FAULT_SPEC.
    Called by Session construction and the full_bench orchestrator so a spec
    set in either tier reaches every injection point in the process."""
    spec = None
    if conf:
        spec = conf.get("engine.fault_spec")
    spec = spec or os.environ.get("NDS_FAULT_SPEC")
    if spec:
        install(str(spec))


def reset():
    global _registry, _installed_spec
    _registry = None
    _installed_spec = None


def active() -> bool:
    return _registry is not None


def maybe_fire(site: str, kinds=None):
    """Exact-match injection point. A single None check when no spec is
    installed. `kinds` restricts which rule kinds may fire here (the spill
    pool's `spill:<site>` points accept io/crash only: an `oom:` rule is
    about device allocation sites, not host-tier file IO)."""
    if _registry is None:
        return
    _registry.fire(site, kinds=kinds)


def maybe_fire_path(path):
    """Substring-match injection point for filesystem paths (a rule site
    `store_sales` hits any IO touching that table's files). Only io/crash
    rules match here: an `oom:query5` rule is about the query site, and a
    report filename that happens to contain "query5" must not trip it."""
    if _registry is None:
        return
    _registry.fire(str(path), substring=True, kinds=("io", "crash"))


# ---------------------------------------------------------------------------
# thread-local scope (which query is executing) for engine-level sites
# ---------------------------------------------------------------------------

_scope = threading.local()


class scope:
    """Context manager labelling the currently-executing query so deeper
    layers (the executor root) can fire scoped sites like exec:<query>."""

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self.prev = getattr(_scope, "name", None)
        _scope.name = self.name
        return self

    def __exit__(self, *exc):
        _scope.name = self.prev
        return False


def current_scope():
    return getattr(_scope, "name", None)


# late import installs the env-tier spec for processes that never build a
# Session (e.g. the orchestrator parent)
install_from_env()
