"""Transcode (Load Test) phase: raw pipe-delimited CSV -> columnar warehouse.

TPU-native counterpart of the reference load test (reference:
nds/nds_transcode.py:45-53 fact-table partitioning, :56-58 CSV scan with
schema, :61-144 store with repartition/coalesce, :146-215 timed loop +
report). Differences by design:

  * ingestion streams bounded-memory Arrow morsels (io/csv.iter_dat_batches)
    instead of a cluster CSV scan — the single-host path the reference gets
    from Spark local mode;
  * fact tables are hive-partitioned on their date surrogate key at write
    (the reference's `repartition(date_sk).sortWithinPartitions.partitionBy`),
    dims land as a single file (the reference's `coalesce(1)`);
  * the load report keeps the reference's exact line format, including the
    TPC-DS 4.3.1 RNGSEED = load-end timestamp the stream generator consumes.

Lakehouse ingest is PARALLEL and RESUMABLE: generator chunk files shard
round-robin across a multi-process decode pool (`--workers`); each worker
holds its own epoch-fenced writer lease and commits per chunk through the
catalog-arbitrated OCC path, recording the chunk id in the manifest's
ingest ledger. The ledger is the checkpoint — a killed run re-invoked
with `--resume` replays only unledgered chunks, and the commit point
itself skips already-ledgered ids (exactly-once even if two resumers
race). Each worker double-buffers: a decode-ahead thread parses chunk
i+1 while chunk i stages and commits. Fact chunks are sorted by their
date surrogate key and split into bounded files, so every committed file
covers a narrow key range and its zone map (lakehouse/zonemap.py)
actually prunes.
"""

from __future__ import annotations

import os
import queue
import shutil
import threading
import time
from datetime import datetime
from time import perf_counter as _perf
from types import SimpleNamespace

import pyarrow as pa
import pyarrow.dataset as pads

from .io.csv import iter_dat_batches
from .io.fs import fs_open_atomic
from .schema import TABLE_PARTITIONING, get_maintenance_schemas, get_schemas


def transcode_table(
    input_prefix: str,
    output_prefix: str,
    table: str,
    schema,
    output_format: str = "parquet",
    use_decimal: bool = True,
    compression: str | None = None,
    output_mode: str = "errorifexists",
    partition: bool = True,
    workers: int = 1,
    resume: bool = False,
) -> int:
    """Convert one table; returns rows written."""
    from .io.fs import get_fs, is_remote, join as fs_join

    src = os.path.join(input_prefix, table)
    dst = fs_join(output_prefix, table)
    if table == "dbgen_version" and not os.path.isdir(src):
        # audit table only emitted by newer generator runs; a raw dataset
        # generated before it existed must still transcode
        print(f"WARNING: skipping {table!r}: no source directory at {src}")
        return 0
    basename = "part-{i}." + output_format
    if is_remote(dst) and output_format != "lakehouse":
        # validate BEFORE any destructive overwrite branch can run: only
        # the lakehouse format carries the shared-filesystem seam; plain
        # file formats are the local-POSIX fast path
        raise ValueError(
            f"remote output {dst!r} requires --output_format lakehouse"
        )
    dst_fs, dst_path = get_fs(dst)
    if resume and output_format == "lakehouse":
        # resuming a killed ingest: the existing table IS the checkpoint
        # (its manifest ledger names the committed chunks) — the
        # output_mode exists-handling below must neither raise nor wipe it
        pass
    elif dst_fs.exists(dst_path):
        if output_mode in ("errorifexists", "error"):
            raise FileExistsError(f"{dst} exists (use --output_mode overwrite)")
        if output_mode == "ignore":
            return 0
        if output_mode == "overwrite":
            if is_remote(dst):
                dst_fs.rm(dst_path, recursive=True)
            else:
                shutil.rmtree(dst)
        elif output_mode == "append":
            # unique file names so new parts never clobber existing ones
            basename = f"part-{int(time.time() * 1000)}-{{i}}.{output_format}"

    arrow_schema = pa.schema(
        [(f.name, f.dtype.to_arrow(use_decimal)) for f in schema]
    )
    rows = 0

    def batches():
        nonlocal rows
        for b in iter_dat_batches(src, schema, use_decimal):
            rows += b.num_rows
            yield b

    if output_format == "lakehouse":
        # snapshot-manifest ACID table (Iceberg/Delta analogue) — the
        # warehouse format the Data Maintenance phase mutates. Ingest is
        # chunk-at-a-time through the manifest ledger (parallel when
        # workers > 1, resumable always)
        return _lakehouse_ingest(
            src, dst, table, schema, arrow_schema, use_decimal, workers
        )
    if output_format not in ("parquet", "csv", "orc", "json", "avro"):
        raise ValueError(f"unsupported output format {output_format}")

    if output_format == "avro":
        # container-file writer in nds_tpu/io/avro.py (reference:
        # nds_transcode.py:241-249 offers avro through the external
        # spark-avro plugin; here the subset of the spec NDS needs is
        # implemented directly)
        from .io.avro import write_avro

        os.makedirs(dst, exist_ok=True)
        write_avro(batches(), os.path.join(dst, basename.format(i=0)),
                   schema=arrow_schema, record_name=table)
        return rows

    if output_format == "json":
        # line-delimited JSON (reference: nds_transcode.py:61-144 'json'
        # via the Spark writer; pyarrow reads ndjson natively)
        import json as _json

        os.makedirs(dst, exist_ok=True)
        # bulk data part file, not a report/state artifact: a torn part is
        # re-created by re-running the table's transcode, and streaming
        # row-by-row through a temp rename would double the IO
        # nds-lint: disable=atomic-write
        with open(os.path.join(dst, basename.format(i=0)), "w") as f:
            for b in batches():
                for row in b.to_pylist():
                    f.write(_json.dumps(row, default=str) + "\n")
        return rows

    if output_format == "orc":
        # pyarrow's dataset writer has no ORC backend; stream batches
        # through an ORCWriter (single file, no hive partitioning —
        # reference: nds_transcode.py:100-112)
        from pyarrow import orc as paorc

        os.makedirs(dst, exist_ok=True)
        writer = paorc.ORCWriter(os.path.join(dst, basename.format(i=0)))
        try:
            for b in batches():
                writer.write(pa.Table.from_batches([b], schema=arrow_schema))
        finally:
            writer.close()
        return rows

    part_col = TABLE_PARTITIONING.get(table) if partition else None

    if part_col is not None and output_format == "parquet":
        # hive layout <col>=<value>/ — one directory per date key, matching
        # the reference's partitionBy(date_sk) warehouse layout. Written
        # directly (sort each generator chunk by the key, slice runs into
        # one persistent ParquetWriter per partition) instead of
        # pads.write_dataset: the dataset writer's per-batch partition
        # fanout ran ~10x slower than an unpartitioned write on this
        # 1-core host (the round-4 24.7k rows/s transcode bottleneck).
        return _write_hive_partitioned_parquet(
            src, dst, schema, arrow_schema, part_col, use_decimal,
            compression or "snappy", basename,
        )

    write_opts = {}
    if output_format == "parquet":
        fmt = pads.ParquetFileFormat()
        write_opts = fmt.make_write_options(compression=compression or "snappy")
    else:
        fmt = pads.CsvFileFormat()

    kwargs = {}
    if part_col is not None:
        kwargs["partitioning"] = pads.partitioning(
            pa.schema([arrow_schema.field(part_col)]), flavor="hive"
        )
        kwargs["max_partitions"] = 1 << 16
        kwargs["max_open_files"] = 1 << 14
    pads.write_dataset(
        batches(),
        base_dir=dst,
        format=fmt,
        file_options=write_opts or None,
        schema=arrow_schema,
        basename_template=basename,
        existing_data_behavior="overwrite_or_ignore",
        **kwargs,
    )
    return rows


def _write_hive_partitioned_parquet(
    src, dst, schema, arrow_schema, part_col, use_decimal, compression,
    basename,
):
    """Fact-table hive-partitioned write. Each generator chunk is sorted by
    the key once and sliced into zero-copy runs; runs accumulate in
    per-partition buffers that flush as ONE parquet write each (at a bytes
    threshold, a global cap, and at end). Per-file/per-call writer overhead
    dominated the dataset-fanout path this replaces (~10x an unpartitioned
    write on this 1-core host), so the design minimizes write_table calls:
    at SF1 every partition directory gets exactly one file with one row
    group, the reference's one-shuffle-partition-per-date layout. Only one
    file is open at any moment. Returns rows written."""
    import numpy as np
    import pyarrow.compute as pc
    import pyarrow.parquet as pq

    from .io.csv import iter_dat_chunk_tables

    file_schema = pa.schema(
        [f for f in arrow_schema if f.name != part_col]
    )
    FLUSH_BYTES = 32 << 20     # per-partition flush threshold
    GLOBAL_BYTES = 1 << 30     # total buffered bound (SF100+ fact tables)
    buffers = {}   # dirname -> [table slices]
    buf_bytes = {}  # dirname -> approx buffered bytes
    fileno = {}    # dirname -> next file sequence number
    total_buffered = 0
    rows = 0

    def flush(dirname):
        nonlocal total_buffered
        parts = buffers.pop(dirname, None)
        if not parts:
            return
        total_buffered -= buf_bytes.pop(dirname)
        pdir = os.path.join(dst, f"{part_col}={dirname}")
        os.makedirs(pdir, exist_ok=True)
        seq = fileno.get(dirname, 0)
        fileno[dirname] = seq + 1
        merged = pa.concat_tables(parts).combine_chunks()
        pq.write_table(
            merged, os.path.join(pdir, basename.format(i=seq)),
            compression=compression,
        )

    for chunk in iter_dat_chunk_tables(src, schema, use_decimal):
        if chunk.num_rows == 0:
            continue
        rows += chunk.num_rows
        order = pc.sort_indices(chunk, sort_keys=[(part_col, "ascending")])
        chunk = chunk.take(order)
        keys = chunk.column(part_col)
        vals = keys.to_numpy(zero_copy_only=False)
        # run boundaries over the sorted key (NaN run = nulls, at end)
        fv = vals.astype(np.float64)
        change = np.nonzero(
            np.diff(fv) != 0
        )[0] + 1  # NaN != NaN, so each null "changes"; regrouped below
        starts = np.concatenate([[0], change])
        null_start = None
        if keys.null_count:
            null_start = len(vals) - keys.null_count
            starts = starts[starts <= null_start]
            if starts[-1] != null_start:
                starts = np.concatenate([starts, [null_start]])
        bounds = np.concatenate([starts, [len(vals)]])
        body = chunk.drop_columns([part_col])
        row_bytes = max(1, body.nbytes // max(1, body.num_rows))
        for s, e2 in zip(bounds[:-1], bounds[1:]):
            if null_start is not None and s == null_start:
                dirname = "__HIVE_DEFAULT_PARTITION__"
            else:
                dirname = str(int(vals[s]))
            buffers.setdefault(dirname, []).append(body.slice(s, e2 - s))
            nb = (e2 - s) * row_bytes
            buf_bytes[dirname] = buf_bytes.get(dirname, 0) + nb
            total_buffered += nb
            if buf_bytes[dirname] >= FLUSH_BYTES:
                flush(dirname)
        if total_buffered >= GLOBAL_BYTES:
            # flush EVERYTHING: buffered parts are zero-copy slices that
            # pin their whole source chunk, so partial flushes would free
            # accounting but not RSS — only releasing every reference to
            # the chunks actually bounds host memory
            for d in list(buffers):
                flush(d)
    for d in list(buffers):
        flush(d)
    return rows


# ---------------------------------------------------------------------------
# parallel resumable lakehouse ingest
# ---------------------------------------------------------------------------


def _ingest_file_bytes() -> int:
    """Target bytes per committed data file: clustered chunks split at this
    size so each file covers a narrow key range its zone map can prune."""
    return int(os.environ.get("NDS_INGEST_FILE_BYTES", 64 << 20))


def _chunk_id(table: str, path: str) -> str:
    """Ledger id for one generator chunk file. Basename-only so a dataset
    moved between hosts (different input_prefix) still resumes."""
    return f"{table}:{os.path.basename(path)}"


def _chunk_files(src: str) -> list:
    """The generator chunk files for a table, in ledger order (same listing
    io/csv uses, so chunk ids are stable across runs)."""
    import glob

    if os.path.isfile(src):
        return [src]
    return sorted(glob.glob(os.path.join(src, "*.dat")))


class _Prefetch:
    """Depth-1 decode-ahead: a daemon thread parses chunk i+1 while the
    consumer stages and commits chunk i — the double buffer that overlaps
    CSV decode with parquet write + OCC commit. Queue depth 1 bounds the
    buffer to at most two decoded chunks in memory (one queued, one being
    consumed) plus the one mid-decode."""

    _END = object()

    def __init__(self, paths, schema, use_decimal):
        self._q = queue.Queue(maxsize=1)
        self._t = threading.Thread(
            target=self._run, args=(list(paths), schema, use_decimal),
            daemon=True,
        )
        self._t.start()

    def _run(self, paths, schema, use_decimal):
        from .io.csv import read_dat_file

        try:
            for p in paths:
                t0 = _perf()
                tbl = read_dat_file(p, schema, use_decimal)
                self._q.put((p, tbl, (_perf() - t0) * 1000.0))
        except BaseException as e:  # surfaced to the consumer thread
            self._q.put(e)
        else:
            self._q.put(self._END)

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is self._END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item


def _ingest_chunks(dst, table, schema, use_decimal, chunk_files, part_col):
    """Ingest a shard of chunk files into the table at `dst`, one ledgered
    commit per chunk. Runs inside the calling process (each pool worker
    calls this over its own shard); the LakehouseTable built here carries
    the process's own epoch-fenced writer lease. Returns
    (rows_committed, chunks_committed) — skipped (already-ledgered) chunks
    count toward neither."""
    from .lakehouse.table import LakehouseTable
    from .obs import trace as obs_trace

    lt = LakehouseTable(dst)
    tracer = obs_trace.current()
    ctx = owned = None
    if tracer is None:
        # CLI / pool-worker path: no session bound a tracer in this
        # thread, so build one from the environment (NDS_TRACE_DIR etc.)
        # and bind it — fault hooks and ingest events land in the stream
        # profile --critical-path reads
        tracer = owned = obs_trace.tracer_from_conf(None)
        if tracer is not None:
            ctx = obs_trace.bind(tracer)
            ctx.__enter__()
    rows = committed = 0
    try:
        for path, tbl, decode_ms in _Prefetch(chunk_files, schema,
                                              use_decimal):
            chunk = _chunk_id(table, path)
            t0 = _perf()
            version = lt.ingest_chunk(
                tbl, chunk, cluster_by=part_col,
                max_file_bytes=_ingest_file_bytes(),
            )
            commit_ms = (_perf() - t0) * 1000.0
            if tracer is not None:
                tracer.emit(
                    "ingest_chunk", table=table, chunk=chunk,
                    rows=tbl.num_rows, decode_ms=round(decode_ms, 3),
                    commit_ms=round(commit_ms, 3),
                    skipped=version is None, version=version,
                )
            if version is not None:
                rows += tbl.num_rows
                committed += 1
    finally:
        if ctx is not None:
            ctx.__exit__(None, None, None)
        if owned is not None:
            owned.close()
    return rows, committed


def _ingest_worker(payload):
    """Top-level (spawn-picklable) pool entry point."""
    return _ingest_chunks(*payload)


def _lakehouse_ingest(src, dst, table, schema, arrow_schema, use_decimal,
                      workers) -> int:
    """Parallel resumable ingest of one table. Chunk files shard
    round-robin over a spawn pool of decode workers; the manifest's ingest
    ledger is the only checkpoint (see module docstring). Returns rows
    committed by THIS run — a clean re-run over a complete table returns
    0, and the table's manifest num_rows is the durable total."""
    from .lakehouse.table import LakehouseTable

    if not LakehouseTable.is_table(dst):
        LakehouseTable.create(dst, schema=arrow_schema)
    done = LakehouseTable(dst).snapshot().ingest_chunks()
    pending = [p for p in _chunk_files(src)
               if _chunk_id(table, p) not in done]
    if not pending:
        return 0
    part_col = TABLE_PARTITIONING.get(table)
    workers = max(1, min(int(workers or 1), len(pending)))
    if workers == 1:
        rows, _ = _ingest_chunks(
            dst, table, schema, use_decimal, pending, part_col
        )
        return rows
    import multiprocessing as mp

    payloads = [
        (dst, table, schema, use_decimal, pending[i::workers], part_col)
        for i in range(workers)
    ]
    # spawn, not fork: workers re-import cleanly (no inherited JAX/Arrow
    # thread state) and each registers its own catalog writer lease
    with mp.get_context("spawn").Pool(processes=workers) as pool:
        results = pool.map(_ingest_worker, payloads)
    return sum(r for r, _ in results)


def transcode(args) -> dict:
    """Run the full load test; writes the report file; returns timing dict."""
    schemas = (
        get_maintenance_schemas(not args.floats)
        if args.update
        else get_schemas(not args.floats)
    )
    if args.tables:
        for t in args.tables:
            if t not in schemas:
                raise Exception(
                    f"invalid table name: {t}. Valid tables are: {list(schemas)}"
                )
        schemas = {t: schemas[t] for t in args.tables}

    results = {}
    row_counts = {}
    start_time = datetime.now()
    print(f"Load Test Start Time: {start_time}")
    for table, schema in schemas.items():
        t0 = time.perf_counter()
        row_counts[table] = transcode_table(
            args.input_prefix,
            args.output_prefix,
            table,
            schema,
            output_format=args.output_format,
            use_decimal=not args.floats,
            compression=args.compression,
            output_mode=args.output_mode,
            workers=getattr(args, "workers", 1),
            resume=getattr(args, "resume", False),
        )
        results[table] = time.perf_counter() - t0
    end_time = datetime.now()
    delta = (end_time - start_time).total_seconds()
    print(f"Load Test Finished at: {end_time}")
    print(f"Load Test Time: {delta} seconds")
    # RNGSEED format required at TPC-DS Spec 4.3.1 (mmddhhmmsss)
    end_time_formatted = end_time.strftime("%m%d%H%M%S%f")[:-5]
    print(f"RNGSEED used :{end_time_formatted}")

    report_text = f"Load Test Time: {delta} seconds\n"
    report_text += f"Load Test Finished at: {end_time}\n"
    report_text += f"RNGSEED used: {end_time_formatted}\n"
    for table, duration in results.items():
        report_text += "Time to convert '%s' was %.04fs\n" % (table, duration)
    total_rows = sum(row_counts.values())
    report_text += f"Total rows converted: {total_rows}\n"
    report_text += "\n\n\nSpark configuration follows:\n\n"
    conf_src = SimpleNamespace(
        use_decimal=not args.floats,
        conf={
            "transcode.output_format": args.output_format,
            "transcode.output_mode": args.output_mode,
            "transcode.compression": args.compression or "snappy",
            "transcode.update": bool(args.update),
        },
    )
    # lazy: report pulls in the engine stack, which spawn-mode ingest
    # workers must not pay to import
    from .report import engine_conf

    # atomic: the transcode report is a phase artifact downstream tooling
    # parses — a crash mid-write must not publish a torn file
    with fs_open_atomic(args.report_file, "w") as report:
        report.write(report_text)
        print(report_text)
        for item in sorted(engine_conf(conf_src).items()):
            report.write(str(item) + "\n")
            print(item)
    return {
        "load_time_s": delta,
        "per_table_s": results,
        "rows": row_counts,
        "rngseed": end_time_formatted,
    }
