"""Argument/build validation utilities shared by all phase CLIs.

Parity target: reference nds/check.py (check_version :38-44, check_build
:47-66, get_abs_path :69-85, valid_range :88-106, parallel_value_type
:109-123, get_dir_size :126-134, check_json_summary_folder :136-145,
check_query_subset_exists :147-152), re-targeted at our native generator
artifacts instead of the dsdgen jar.
"""

from __future__ import annotations

import argparse
import os
import sys

MIN_PYTHON = (3, 8)


def check_version():
    if sys.version_info < MIN_PYTHON:
        raise RuntimeError(
            f"Python {MIN_PYTHON[0]}.{MIN_PYTHON[1]}+ required, found {sys.version}"
        )


def get_abs_path(input_path: str) -> str:
    """Expand a relative path against this package's datagen directory so the
    generator binaries can be addressed from any CWD."""
    if os.path.isabs(input_path):
        return input_path
    return os.path.join(os.path.dirname(__file__), "datagen", input_path)


def check_build():
    """Verify the native generator library has been built, and build it on
    demand (the reference requires a manual `make`; we self-build)."""
    from .datagen.build import ensure_built

    return ensure_built()


def valid_range(range_str: str, parallel: int):
    """Validate a --range 'start,end' against the chunk count."""
    try:
        start, end = (int(x) for x in range_str.split(","))
    except Exception as exc:
        raise argparse.ArgumentTypeError(
            f"--range must be 'start,end' integers, got {range_str!r}"
        ) from exc
    if not (1 <= start <= end <= parallel):
        raise argparse.ArgumentTypeError(
            f"--range {range_str} invalid: need 1 <= start <= end <= parallel({parallel})"
        )
    return start, end


def parallel_value_type(s: str) -> int:
    v = int(s)
    if v < 2:
        raise argparse.ArgumentTypeError("--parallel must be >= 2")
    return v


def scale_of(s: str) -> float:
    """Scale factor; fractional scales < 1 are allowed for smoke tests."""
    v = float(s)
    if v <= 0:
        raise argparse.ArgumentTypeError("scale must be > 0")
    return v


def get_dir_size(start_path: str) -> int:
    total = 0
    for dirpath, _dirnames, filenames in os.walk(start_path):
        for f in filenames:
            fp = os.path.join(dirpath, f)
            if not os.path.islink(fp):
                total += os.path.getsize(fp)
    return total


def check_json_summary_folder(folder: str):
    """Create the summary folder if needed; refuse to clobber a non-empty one
    (user must clean it)."""
    if not folder:
        return folder
    try:
        if os.path.exists(folder):
            if os.listdir(folder):
                raise argparse.ArgumentTypeError(
                    f"json summary folder {folder!r} exists and is not empty"
                )
        else:
            os.makedirs(folder)
    except OSError as exc:  # existing file, permission, ...
        raise argparse.ArgumentTypeError(
            f"json summary folder {folder!r} unusable: {exc}"
        ) from exc
    return folder


def check_query_subset_exists(queries: dict, subset: list) -> bool:
    missing = [q for q in subset if q not in queries]
    if missing:
        raise Exception(f"queries not found in stream: {missing}")
    return True
