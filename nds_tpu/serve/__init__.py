"""Serve mode: the long-lived multi-tenant query service (ROADMAP item 4).

`nds-tpu-submit serve` turns the batch engine into a query *service*: one
warm Session (exec/plan/join-order/AOT caches shared across requests)
behind `POST /query`, stream jobs, and admin verbs on the SAME process-wide
HTTP endpoint that already serves /metrics, /statusz and /healthz
(obs/httpserv.py). Admission control is the static plan budgeter's verdict
per request; backpressure rides the RSS watermark; per-request isolation
reuses the lakehouse snapshot pins + reader leases.
"""

from .service import (  # noqa: F401
    QueryService,
    resolve_serve_port,
    resolve_row_cap,
    resolve_drain_timeout,
)
