"""QueryService: admission-controlled multi-tenant SQL over HTTP.

The reference harness never needs this tier — Spark's long-lived driver
IS the service (thrift server, concurrent scheduler pools, fair-share
queues). This engine's batch CLIs build a session, run a stream, and
exit; serve mode is the composition of every robustness component the
prior PRs landed into the missing tier:

* one warm read `Session` owns the multi-tenant caches (exec/plan/
  join-order/AOT — PR 4/11), shared by every request;
* admission control is the PR-7 plan budgeter's verdict per request:
  `reject` answers HTTP 429 carrying the modeled peak bytes before
  anything dispatches; `blocked`/`spill`/`over` admit DEGRADED with the
  verdict echoed in the response envelope;
* concurrency is gated by a semaphore sized from the device budget
  (analysis/budget.serve_concurrency) plus the PR-7 RSS watermark as
  backpressure — over-capacity and over-watermark requests are SHED with
  `Retry-After` instead of wedging the device;
* each request pins its lakehouse snapshot at plan time (PR-10 reader
  leases), so queries serve consistent reads while DM commits race them;
* DML routes through a dedicated writer session under a writer lock
  (single-writer in-process; OCC commits arbitrate across processes);
* per-tenant accounting (X-NDS-Tenant header) lands on /statusz and the
  `nds_serve_request_*` metric families via a per-request forwarding
  tracer that labels every engine event with the tenant + request id.

Failure domain: `serve:admit` / `serve:exec` are fault-injection sites
(faults.py registry), a failed execution walks the SAME BenchReport
degradation ladder a bench query would (device OOM recovers + retries,
transient IO backs off, the watchdog cuts off hangs), and a worker that
dies takes its request's connection down, never the pool.

Verdict -> HTTP status mapping (the admission contract):

    reject                   429 + modeled peak/budget bytes (never runs)
    over | spill | blocked   200, admitted degraded, verdict in envelope
    direct | unknown         200
    no capacity / watermark  429 + Retry-After   (shed)
    draining                 503 + Retry-After
    parse/bind error         400
    execution failed         500 + classified failureKind
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid

from .. import faults
from ..analysis.budget import PlanBudgetError, serve_concurrency
from ..engine.sql import ast as A
from ..engine.sql.parser import parse_script
from ..obs import trace as obs_trace
from ..obs.memwatch import rss_bytes
from ..report import BenchReport, host_rss_watermark
from ..engine.lockdebug import make_lock

#: default rows per response page; `engine.serve_row_cap` overrides. A
#: serve endpoint returning JSON must bound what one request can pull
#: through the host — callers paginate with offset/limit instead.
DEFAULT_ROW_CAP = 10_000

#: default seconds a request waits for an admission slot before it is
#: shed with Retry-After (`engine.serve_admit_timeout_s`)
DEFAULT_ADMIT_TIMEOUT_S = 10.0

#: default drain budget: how long /drain waits for in-flight work
#: (`engine.serve_drain_timeout_s`)
DEFAULT_DRAIN_TIMEOUT_S = 30.0

#: Retry-After seconds advertised on shed/draining responses — a load
#: balancer retry storm re-arriving in lockstep would re-shed forever
RETRY_AFTER_S = 2


def resolve_serve_port(conf: dict | None = None):
    """Serve port from conf `engine.serve_port`, else NDS_SERVE_PORT;
    None when unset. 0 binds ephemeral (tests). Serve mode feeds this
    into `engine.metrics_port` so ONE process-wide endpoint carries
    /metrics, /statusz, /healthz AND the query routes."""
    v = None
    if conf:
        v = conf.get("engine.serve_port")
    if v is None:
        v = os.environ.get("NDS_SERVE_PORT")
    if v is None or str(v).strip().lower() in ("", "off", "none"):
        return None
    try:
        port = int(v)
    except (TypeError, ValueError):
        return None
    return port if port >= 0 else None


def resolve_row_cap(conf: dict | None = None) -> int:
    v = None
    if conf:
        v = conf.get("engine.serve_row_cap")
    if v is None:
        v = os.environ.get("NDS_SERVE_ROW_CAP")
    try:
        return max(int(v), 1) if v else DEFAULT_ROW_CAP
    except (TypeError, ValueError):
        return DEFAULT_ROW_CAP


def resolve_admit_timeout(conf: dict | None = None) -> float:
    v = None
    if conf:
        v = conf.get("engine.serve_admit_timeout_s")
    if v is None:
        v = os.environ.get("NDS_SERVE_ADMIT_TIMEOUT_S")
    try:
        return max(float(v), 0.0) if v is not None and v != "" else (
            DEFAULT_ADMIT_TIMEOUT_S
        )
    except (TypeError, ValueError):
        return DEFAULT_ADMIT_TIMEOUT_S


def resolve_drain_timeout(conf: dict | None = None) -> float:
    v = None
    if conf:
        v = conf.get("engine.serve_drain_timeout_s")
    if v is None:
        v = os.environ.get("NDS_SERVE_DRAIN_TIMEOUT_S")
    try:
        return max(float(v), 0.0) if v is not None and v != "" else (
            DEFAULT_DRAIN_TIMEOUT_S
        )
    except (TypeError, ValueError):
        return DEFAULT_DRAIN_TIMEOUT_S


def resolve_tenant_cap(conf: dict | None, workers: int) -> int:
    """Per-tenant in-flight cap (`engine.serve_tenant_cap`): one tenant
    flooding the endpoint must never hold EVERY admission slot, so the
    default leaves at least one slot for other tenants."""
    v = None
    if conf:
        v = conf.get("engine.serve_tenant_cap")
    if v is None:
        v = os.environ.get("NDS_SERVE_TENANT_CAP")
    try:
        if v:
            return max(int(v), 1)
    except (TypeError, ValueError):
        pass
    return max(workers - 1, 1)


class _RequestTracer:
    """Per-request forwarding tracer: every event a request's execution
    emits (op_span, exec_cache, plan_cache, heartbeat, ladder_rung, ...)
    gets the request id + tenant stamped on, so concurrent identical
    queries from two tenants never alias in the sink's in-flight view and
    per-tenant cache traffic is attributable. Cache probes are tallied
    here as they pass through — the per-tenant hit rates on /statusz come
    from these tallies riding the request's `serve_request` event."""

    def __init__(self, inner, request_id: str, tenant: str):
        self._inner = inner
        self.request_id = request_id
        self.tenant = tenant
        self._tally_lock = make_lock("_RequestTracer._tally_lock")
        self.tallies = {
            "exec_cache_hits": 0, "exec_cache_lookups": 0,
            "plan_cache_hits": 0, "plan_cache_lookups": 0,
        }

    def __getattr__(self, name):
        # delegate app_id / sink / kernel_spans / close ... to the real
        # tracer (a None inner means an untraced session: emit() below
        # still tallies, then drops)
        return getattr(self._inner, name)

    def emit(self, kind: str, **fields):
        if kind in ("exec_cache", "plan_cache"):
            with self._tally_lock:
                self.tallies[f"{kind}_lookups"] += 1
                if fields.get("hit"):
                    self.tallies[f"{kind}_hits"] += 1
        fields.setdefault("request_id", self.request_id)
        # the request id IS the serve-entry trace_id: every event this
        # request's execution emits (op/kernel/exchange spans, ladder
        # rungs, DM commits, heartbeats) carries ONE trace_id, overriding
        # the shared session tracer's stream-level context — the whole
        # request is followable end to end by a single grep
        fields.setdefault("trace_id", self.request_id)
        fields.setdefault("tenant", self.tenant)
        if self._inner is not None:
            self._inner.emit(kind, **fields)


class _ShedError(Exception):
    """Internal: the request must be shed (429 — or 503 when the shed
    reason is a drain — plus Retry-After)."""

    def __init__(self, reason: str, status: int = 429,
                 label: str = "shed"):
        super().__init__(reason)
        self.reason = reason
        self.status = status
        self.label = label


class QueryService:
    """The serve-mode application behind obs/httpserv.py's route seam.

    `session` is the warm shared READ session; `writer_session` (optional)
    takes DML under a writer lock — when omitted, DML runs on the read
    session under both locks (test mode). `templates` maps template names
    (e.g. "query3") to SQL text, usually parsed from a generated stream
    file. `reload_fn` re-registers the warehouse on /reload (the CLI
    wires one; the default drops every cached snapshot pin + device
    column so the next statements re-resolve fresh heads)."""

    def __init__(self, session, writer_session=None, templates=None,
                 reload_fn=None, job_dir=None):
        self.session = session
        self.writer_session = writer_session
        self.templates = dict(templates or {})
        self._reload_fn = reload_fn
        conf = getattr(session, "conf", {}) or {}
        self.workers = serve_concurrency(conf)
        self.row_cap = resolve_row_cap(conf)
        self.admit_timeout_s = resolve_admit_timeout(conf)
        self.drain_timeout_s = resolve_drain_timeout(conf)
        self.tenant_cap = resolve_tenant_cap(conf, self.workers)
        # the bounded worker model: HTTP connection threads ARE the
        # workers, and this semaphore is the bound — at most `workers`
        # requests execute engine work concurrently, the rest wait a
        # bounded admit_timeout_s and then shed. (A separate executor
        # pool would add a thread hop per request for identical
        # semantics: every submit would be immediately awaited.)
        self._admission = threading.BoundedSemaphore(self.workers)
        # planning is serialized (Session.plan_sql holds cache_lock), but
        # the writer path needs its own mutual exclusion: one in-process
        # writer at a time, OCC arbitrates across processes
        self._writer_lock = make_lock("QueryService._writer_lock", conf)
        self._state_lock = make_lock("QueryService._state_lock", conf)
        self._in_flight = 0  # nds-guarded-by: _state_lock
        self._active_rids = set()  # nds-guarded-by: _state_lock
        # /reload lease hygiene: [(rids-still-running-at-reload, lease
        # ids dropped by that reload)] — each batch releases when the
        # LAST of its in-flight statements finishes, instead of
        # abandoning the leases to TTL expiry (the PR-12 leak bound)
        self._deferred_leases = []  # nds-guarded-by: _state_lock
        self._tenant_in_flight = {}  # nds-guarded-by: _state_lock
        # DML idempotency ledger (router retries): request_key -> the
        # recorded completed envelope, or None while the original
        # delivery is still running. Bounded FIFO — the keys are
        # router-minted uuids, one per client DML request.
        self._dml_keys = {}  # nds-guarded-by: _state_lock
        self._dml_key_order = []  # nds-guarded-by: _state_lock
        self.draining = False  # nds-guarded-by: _state_lock
        self.started_ts_ms = int(time.time() * 1000)
        from .jobs import StreamJobs

        self.jobs = StreamJobs(self, job_dir=job_dir)

    # ------------------------------------------------------------------
    # HTTP seam (obs/httpserv.py dispatches here for non-built-in routes)
    # ------------------------------------------------------------------
    def handle_http(self, method, path, headers, body):
        """Route one request; returns (status, ctype, body, extra_headers)
        or None for paths this app doesn't own (the caller 404s)."""
        tenant = str(headers.get("x-nds-tenant") or "default")
        if method == "POST" and path == "/query":
            return self.handle_query(
                self._json_body(body), tenant,
                rid=self._adopt_rid(headers),
                request_key=self._request_key(headers),
            )
        if method == "POST" and path == "/plan":
            return self.handle_plan(self._json_body(body), tenant)
        if method == "POST" and path == "/stream":
            return self.handle_stream(self._json_body(body), tenant)
        if method == "GET" and path.startswith("/jobs/"):
            return self.handle_job_get(path[len("/jobs/"):])
        if method == "POST" and path == "/drain":
            return self.handle_drain()
        if method == "POST" and path == "/reload":
            return self.handle_reload()
        return None

    @staticmethod
    def _adopt_rid(headers):
        """Router-stamped trace context: `x-nds-trace-context` is the
        HTTP carriage of NDS_TRACE_CONTEXT ("trace_id,parent"); the
        trace_id half becomes this request's rid, so ONE trace_id greps
        router -> replica -> catalog -> engine, and a failover retry of
        the same client request lands in BOTH replicas' event logs under
        the same id. Malformed/oversized values fall back to a local rid
        (the header is client-controllable in principle)."""
        raw = headers.get("x-nds-trace-context") or ""
        rid = str(raw).split(",", 1)[0].strip()
        if rid and len(rid) <= 64 and all(
            c.isalnum() or c in "-_." for c in rid
        ):
            return rid
        return None

    @staticmethod
    def _request_key(headers):
        """Router-minted DML idempotency key (`x-nds-request-key`): a
        re-delivered DML with a known key answers the recorded envelope
        instead of re-running the statement."""
        key = str(headers.get("x-nds-request-key") or "").strip()
        if key and len(key) <= 64 and all(
            c.isalnum() or c in "-_." for c in key
        ):
            return key
        return None

    @staticmethod
    def _json_body(body):
        if not body:
            return {}
        try:
            obj = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as exc:
            raise ValueError(f"malformed JSON request body: {exc}") from exc
        if not isinstance(obj, dict):
            raise ValueError("request body must be a JSON object")
        return obj

    @staticmethod
    def _reply(status, obj, extra_headers=()):
        return (
            status, "application/json",
            json.dumps(obj, default=str), tuple(extra_headers),
        )

    def _shed_reply(self, rid, tenant, t0, reason, status=429,
                    label="shed", extra=None):
        body = {
            "request_id": rid, "tenant": tenant, "status": label,
            "error": reason, "retry_after_s": RETRY_AFTER_S,
        }
        if extra:
            body.update(extra)
        self._emit_request(rid, tenant, label, t0, status)
        return self._reply(
            status, body, (("Retry-After", str(RETRY_AFTER_S)),)
        )

    # ------------------------------------------------------------------
    # request accounting + telemetry
    # ------------------------------------------------------------------
    def _emit_request(self, rid, tenant, status_label, t0, http_status,
                      query=None, verdict=None, rows=None, nbytes=None,
                      tallies=None):
        tracer = getattr(self.session, "tracer", None)
        if tracer is None:
            return
        fields = {
            "request_id": rid,
            # admission verdicts are part of the request's trace: the
            # serve_request event carries the same trace_id (= rid) the
            # execution's spans do, so shed/rejected requests trace too
            "trace_id": rid,
            "query": query,
            "verdict": verdict,
        }
        if rows is not None:
            fields["rows"] = int(rows)
        if nbytes is not None:
            fields["bytes"] = int(nbytes)
        if tallies:
            fields.update(tallies)
        tracer.emit(
            "serve_request",
            tenant=tenant,
            status=status_label,
            dur_ms=round((time.perf_counter() - t0) * 1000.0, 3),
            http_status=int(http_status),
            **fields,
        )

    def _enter(self, tenant, rid=None):
        """Claim an admission slot (semaphore + per-tenant cap) or raise
        _ShedError. The semaphore wait is bounded so an overloaded
        endpoint answers 429 instead of stacking blocked client threads.

        The tenant-cap reservation is taken ATOMICALLY with the check —
        a burst from one tenant must not all pass the check before any
        of them increments (the semaphore wait between check and
        increment can last the whole admit timeout)."""
        with self._state_lock:
            if self._tenant_in_flight.get(tenant, 0) >= self.tenant_cap:
                raise _ShedError(
                    f"tenant {tenant!r} is at its in-flight cap "
                    f"({self.tenant_cap}); retry later"
                )
            self._tenant_in_flight[tenant] = (
                self._tenant_in_flight.get(tenant, 0) + 1
            )
        if not self._admission.acquire(timeout=self.admit_timeout_s):
            self._drop_tenant_slot(tenant)
            raise _ShedError(
                f"no admission slot free within {self.admit_timeout_s:.0f}s "
                f"({self.workers} workers); retry later"
            )
        with self._state_lock:
            # re-check the drain flag AFTER the (up to admit_timeout_s)
            # semaphore wait: a request queued before /drain must not
            # start executing after drain reported drained=true and the
            # process began exiting. Both this check-and-increment and
            # handle_drain's flag flip hold _state_lock, so a request
            # that passes here is visible to the drain poll before the
            # poll can observe in_flight == 0.
            if self.draining:
                self._admission.release()
                self._drop_tenant_slot_locked(tenant)
                raise _ShedError(
                    "service is draining", status=503, label="draining"
                )
            self._in_flight += 1
            if rid is not None:
                self._active_rids.add(rid)

    def _drop_tenant_slot(self, tenant):
        with self._state_lock:
            self._drop_tenant_slot_locked(tenant)

    def _drop_tenant_slot_locked(self, tenant):
        n = self._tenant_in_flight.get(tenant, 1) - 1
        if n <= 0:
            self._tenant_in_flight.pop(tenant, None)
        else:
            self._tenant_in_flight[tenant] = n

    def _leave(self, tenant, rid=None):
        release_now = []
        with self._state_lock:
            self._in_flight -= 1
            if rid is not None:
                self._active_rids.discard(rid)
                # /reload lease hygiene: a dropped pin's lease batch
                # releases once the last statement that was in flight at
                # reload time finishes (it may still be scanning the
                # pinned snapshot's files until then)
                kept = []
                for rids, lease_ids in self._deferred_leases:
                    rids &= self._active_rids
                    if rids:
                        kept.append((rids, lease_ids))
                    else:
                        release_now.extend(lease_ids)
                self._deferred_leases = kept
        if release_now:
            from ..lakehouse.leases import LEASES

            for lid in release_now:
                LEASES.release(lid)
        self._drop_tenant_slot(tenant)
        self._admission.release()

    def in_flight(self) -> int:
        with self._state_lock:
            return self._in_flight

    # ------------------------------------------------------------------
    # /query
    # ------------------------------------------------------------------
    def resolve_sql(self, payload):
        """The SQL text of a request: `sql` verbatim, or `template` looked
        up in the loaded stream templates with `${key}` params applied."""
        sql = payload.get("sql")
        if sql:
            return str(sql), None
        name = payload.get("template")
        if not name:
            raise ValueError("request needs 'sql' or 'template'")
        text = self.templates.get(str(name))
        if text is None:
            raise KeyError(f"unknown template {name!r}")
        for k, v in (payload.get("params") or {}).items():
            text = text.replace("${" + str(k) + "}", str(v))
        return text, str(name)

    def handle_query(self, payload, tenant, rid=None, request_key=None):
        rid = rid or uuid.uuid4().hex[:12]
        t0 = time.perf_counter()
        if self.draining:
            return self._shed_reply(
                rid, tenant, t0, "service is draining", status=503,
                label="draining",
            )
        # backpressure BEFORE the queue: past the RSS watermark the right
        # move is shedding load, not admitting more working sets
        watermark = host_rss_watermark(self.session)
        if watermark:
            r = rss_bytes()
            if r is not None and r >= watermark:
                return self._shed_reply(
                    rid, tenant, t0,
                    f"host RSS {r} is over the serve watermark {watermark}",
                    extra={"rss_bytes": int(r),
                           "watermark_bytes": int(watermark)},
                )
        try:
            sql_text, qlabel = self.resolve_sql(payload)
        except KeyError as exc:
            self._emit_request(rid, tenant, "failed", t0, 404)
            return self._reply(404, {"request_id": rid, "error": str(exc)})
        except ValueError as exc:
            self._emit_request(rid, tenant, "failed", t0, 400)
            return self._reply(400, {"request_id": rid, "error": str(exc)})
        try:
            # admission fault site (io/oom/hang/crash injectable): an
            # injected failure here sheds the request, never the server
            faults.maybe_fire("serve:admit")
            self._enter(tenant, rid)
        except _ShedError as exc:
            return self._shed_reply(
                rid, tenant, t0, exc.reason, status=exc.status,
                label=exc.label,
            )
        except faults.FaultError as exc:
            return self._shed_reply(
                rid, tenant, t0, f"admission fault: {exc}",
                extra={"failure_kind": faults.classify(exc)},
            )
        try:
            return self._admitted_query(
                payload, tenant, rid, t0, sql_text, qlabel,
                request_key=request_key,
            )
        finally:
            self._leave(tenant, rid)

    def _classify_statements(self, sql_text):
        stmts = parse_script(sql_text)
        if not stmts:
            raise ValueError("empty statement")
        if all(isinstance(s, A.SelectStmt) for s in stmts):
            if len(stmts) != 1:
                raise ValueError(
                    "serve mode runs one SELECT per request (split "
                    "multi-statement scripts client-side)"
                )
            return "select", stmts
        if any(isinstance(s, (A.CreateViewStmt, A.DropViewStmt))
               for s in stmts):
            # session-mutating DDL on the SHARED warm session would leak
            # one tenant's views into every other tenant's namespace
            raise ValueError(
                "CREATE/DROP VIEW is not allowed in serve mode "
                "(the session is shared across tenants)"
            )
        return "dml", stmts

    def _admitted_query(self, payload, tenant, rid, t0, sql_text, qlabel,
                        request_key=None):
        try:
            kind, stmts = self._classify_statements(sql_text)
        except Exception as exc:
            self._emit_request(rid, tenant, "failed", t0, 400, query=qlabel)
            return self._reply(400, {"request_id": rid, "error": str(exc)})
        if kind == "dml":
            return self._run_dml(
                sql_text, tenant, rid, t0, qlabel, request_key=request_key
            )
        # plan + capture THIS statement's budgeter verdict atomically
        # (Session.plan_stmt holds the cache lock): admission control.
        # The classification pass above already parsed — plan the AST.
        try:
            res, budget = self.session.plan_stmt(stmts[0])
        except PlanBudgetError as exc:
            # the 429-with-modeled-bytes contract: rejected BEFORE any
            # device dispatch, and the client learns why (how big the
            # plan modeled vs what the device budget admits)
            self._emit_request(
                rid, tenant, "rejected", t0, 429, query=qlabel,
                verdict="reject",
            )
            return self._reply(429, {
                "request_id": rid, "tenant": tenant, "status": "rejected",
                "verdict": "reject", "error": str(exc),
                "peak_bytes": int(exc.peak_bytes),
                "budget_bytes": int(exc.budget_bytes),
            })
        except Exception as exc:
            self._emit_request(rid, tenant, "failed", t0, 400, query=qlabel)
            return self._reply(400, {
                "request_id": rid, "error": f"{type(exc).__name__}: {exc}",
            })
        verdict = (budget or {}).get("verdict")
        qname = qlabel or f"serve-{rid}"
        summary, arrow, tallies = self._execute_select(
            res, qname, rid, tenant, budget
        )
        status = summary["queryStatus"][-1]
        if status == "Failed":
            body = {
                "request_id": rid, "tenant": tenant, "status": "failed",
                "query": qlabel, "verdict": verdict,
                "failure_kind": summary.get("failureKind"),
                "error": (summary.get("exceptions") or ["failed"])[-1],
                "retries": summary.get("retries", 0),
            }
            self._emit_request(
                rid, tenant, "failed", t0, 500, query=qlabel,
                verdict=verdict, tallies=tallies,
            )
            return self._reply(500, body)
        envelope = self._page(arrow, payload)
        envelope.update({
            "request_id": rid,
            "tenant": tenant,
            "status": "completed",
            "query": qlabel,
            # the admission echo: a degraded admit (blocked window /
            # planned spill / armed-over) is visible to the client, not
            # silently slower
            "verdict": verdict,
            "admitted_degraded": verdict in ("blocked", "spill", "over"),
            "retries": summary.get("retries", 0),
            "elapsed_ms": round((time.perf_counter() - t0) * 1000.0, 3),
        })
        if summary.get("ladder"):
            envelope["ladder"] = [r["rung"] for r in summary["ladder"]]
        body = json.dumps(envelope, default=str)
        self._emit_request(
            rid, tenant, "completed", t0, 200, query=qlabel,
            verdict=verdict, rows=envelope["row_count"], nbytes=len(body),
            tallies=tallies,
        )
        return (200, "application/json", body, ())

    def handle_plan(self, payload, tenant):
        """Verdict probe for the fleet router (POST /plan): resolve +
        classify + plan one statement and answer the budget verdict
        WITHOUT consuming an admission slot and WITHOUT emitting a
        serve_request event — an edge-rejected 429 must provably never
        cost a replica worker slot, and the probe must not show up in
        per-tenant serve accounting (the router's own route_request
        event is the probe's telemetry). Planning still serializes on
        the session cache lock, which is exactly the cost the router's
        verdict cache amortizes."""
        rid = uuid.uuid4().hex[:12]
        try:
            sql_text, _ = self.resolve_sql(payload)
        except KeyError as exc:
            return self._reply(404, {"request_id": rid, "error": str(exc)})
        except ValueError as exc:
            return self._reply(400, {"request_id": rid, "error": str(exc)})
        try:
            kind, stmts = self._classify_statements(sql_text)
        except Exception as exc:
            return self._reply(400, {"request_id": rid, "error": str(exc)})
        if kind == "dml":
            # DML never has a budget verdict; the router routes it by
            # class (writer path), not by verdict
            return self._reply(200, {
                "request_id": rid, "kind": "dml", "verdict": None,
            })
        try:
            _res, budget = self.session.plan_stmt(stmts[0])
        except PlanBudgetError as exc:
            # a probe answering "reject" is a 200: the PROBE succeeded;
            # the router turns the verdict into the client's 429
            return self._reply(200, {
                "request_id": rid, "kind": "select", "verdict": "reject",
                "error": str(exc),
                "peak_bytes": int(exc.peak_bytes),
                "budget_bytes": int(exc.budget_bytes),
            })
        except Exception as exc:
            return self._reply(400, {
                "request_id": rid, "error": f"{type(exc).__name__}: {exc}",
            })
        budget = budget or {}
        return self._reply(200, {
            "request_id": rid, "kind": "select",
            "verdict": budget.get("verdict"),
            "peak_bytes": budget.get("peak_bytes"),
            "budget_bytes": budget.get("budget_bytes"),
        })

    def _execute_select(self, res, qname, rid, tenant, budget):
        """Run one planned SELECT under the BenchReport failure ladder
        with a request-scoped tracer (on the admitted connection thread —
        the admission semaphore is the worker bound). Returns
        (summary, arrow-or-None, cache tallies)."""
        rt = _RequestTracer(
            getattr(self.session, "tracer", None), rid, tenant
        )
        report = BenchReport(self.session, tracer=rt)
        box = {}

        def run():
            with faults.scope(qname):
                # engine-side fault site: exercises the ladder (an
                # injected OOM recovers + retries) and the pool-health
                # contract (a crash kills one request, not the pool)
                faults.maybe_fire("serve:exec")
                # fleet chaos site: `hang` holds this request open for a
                # deterministic external SIGKILL window (the fleet_check
                # failover drill); `crash` kills the connection thread
                # mid-request so the socket closes with NO reply — what a
                # mid-stream replica death looks like to the router. Fired
                # under the bound request tracer, so the fault_injected
                # event lands in this replica's log with the request's
                # trace_id (the failover trace evidence).
                faults.maybe_fire("replica:kill", kinds=("hang", "crash"))
                box["arrow"] = res.collect(tracer=rt)

        with obs_trace.bind(rt):
            summary = report.report_on(
                run, retry_oom=True, name=qname, request_id=rid,
                plan_budget=budget,
            )
        return summary, box.get("arrow"), dict(rt.tallies)

    def _page(self, arrow, payload) -> dict:
        """Row-cap + pagination: the response carries at most
        min(limit, engine.serve_row_cap) rows starting at `offset`."""
        total = arrow.num_rows
        try:
            offset = max(int(payload.get("offset") or 0), 0)
        except (TypeError, ValueError):
            offset = 0
        raw_limit = payload.get("limit")
        try:
            # `limit: 0` is a legitimate metadata-only probe (envelope
            # without row payload) — only an ABSENT limit defaults
            limit = self.row_cap if raw_limit is None else int(raw_limit)
        except (TypeError, ValueError):
            limit = self.row_cap
        limit = max(min(limit, self.row_cap), 0)
        window = arrow.slice(offset, limit)
        return {
            "columns": list(arrow.column_names),
            "rows": [list(r.values()) for r in window.to_pylist()],
            "row_count": window.num_rows,
            "total_rows": total,
            "offset": offset,
            "truncated": offset + window.num_rows < total,
        }

    # ------------------------------------------------------------------
    # DML (writer path)
    # ------------------------------------------------------------------
    #: DML idempotency keys remembered before FIFO eviction — deep enough
    #: that a router retry (seconds later) always finds its key, bounded
    #: so a long-lived replica never grows without limit
    DML_KEY_CAP = 1024

    def _dml_key_begin(self, key):
        """Claim a DML idempotency key. Returns "run" (first delivery —
        go), "inflight" (the original delivery is still executing: the
        duplicate is shed retryable instead of double-applying), or the
        recorded envelope dict (already committed: answer it verbatim,
        marked deduped)."""
        with self._state_lock:
            if key in self._dml_keys:
                hit = self._dml_keys[key]
                return "inflight" if hit is None else hit
            self._dml_keys[key] = None
            self._dml_key_order.append(key)
            while len(self._dml_key_order) > self.DML_KEY_CAP:
                self._dml_keys.pop(self._dml_key_order.pop(0), None)
        return "run"

    def _dml_key_end(self, key, envelope):
        """Record the completed envelope under the key — or, on failure
        (envelope None), release the claim so the router's classified
        retry can re-run the statement (an aborted OCC commit published
        nothing)."""
        with self._state_lock:
            if envelope is None:
                if self._dml_keys.get(key, "x") is None:
                    del self._dml_keys[key]
                    try:
                        self._dml_key_order.remove(key)
                    except ValueError:
                        pass
            else:
                self._dml_keys[key] = dict(envelope)

    def _run_dml(self, sql_text, tenant, rid, t0, qlabel,
                 request_key=None):
        """DML on the writer session, serialized in-process: statement-
        level commit-conflict re-runs ride maintenance's one retry home
        (an aborted OCC commit published nothing, so the re-run derives
        its writes from the fresh head). Readers never block — their
        statements pin the pre-commit snapshot.

        The writer lock is held by THIS (connection) thread around the
        report, never inside `run`: with a watchdog budget configured,
        report_on runs `run` on an abandonable daemon worker, and a
        lock taken there would be held FOREVER by a hung-then-abandoned
        attempt (DML down until restart). The cost of the handler-side
        lock: a watchdog-abandoned DML zombie may still be committing
        while the next DML starts — safe, because OCC commits arbitrate
        concurrent in-process writers anyway (the lock is contention
        avoidance, not the correctness mechanism)."""
        from ..maintenance import _run_dm_statement

        if request_key:
            # idempotency guard (router-minted x-nds-request-key): a
            # re-delivered committed DML answers the recorded envelope;
            # a concurrent duplicate sheds instead of double-applying
            claim = self._dml_key_begin(request_key)
            if claim == "inflight":
                return self._shed_reply(
                    rid, tenant, t0,
                    f"request key {request_key!r} is already in flight; "
                    "retry",
                )
            if isinstance(claim, dict):
                envelope = dict(claim)
                envelope.update({"request_id": rid, "deduped": True})
                self._emit_request(
                    rid, tenant, "completed", t0, 200, query=qlabel
                )
                return self._reply(200, envelope)
        session = self.writer_session or self.session
        qname = qlabel or f"serve-dm-{rid}"
        rt = _RequestTracer(getattr(session, "tracer", None), rid, tenant)
        report = BenchReport(session, tracer=rt)
        box = {}

        def run():
            with faults.scope(qname):
                faults.maybe_fire("serve:exec")
                box["result"] = _run_dm_statement(session, sql_text)

        try:
            with obs_trace.bind(rt), self._writer_lock:
                summary = report.report_on(
                    run, retry_oom=False, name=qname, request_id=rid,
                )
        except BaseException:
            # includes InjectedCrash: the claim must not orphan — the
            # router's classified retry needs to be able to re-run
            if request_key:
                self._dml_key_end(request_key, None)
            raise
        status = summary["queryStatus"][-1]
        if status == "Failed":
            if request_key:
                self._dml_key_end(request_key, None)
            self._emit_request(
                rid, tenant, "failed", t0, 500, query=qlabel,
                tallies=dict(rt.tallies),
            )
            return self._reply(500, {
                "request_id": rid, "tenant": tenant, "status": "failed",
                "failure_kind": summary.get("failureKind"),
                "error": (summary.get("exceptions") or ["failed"])[-1],
            })
        result = box.get("result")
        rows = getattr(result, "rows_affected", None)
        envelope = {
            "request_id": rid, "tenant": tenant, "status": "completed",
            "statement": "dml",
            "rows_affected": rows,
            "version": getattr(result, "version", None),
            "elapsed_ms": round((time.perf_counter() - t0) * 1000.0, 3),
        }
        if request_key:
            self._dml_key_end(request_key, envelope)
        self._emit_request(
            rid, tenant, "completed", t0, 200, query=qlabel, rows=rows,
            tallies=dict(rt.tallies),
        )
        return self._reply(200, envelope)

    # ------------------------------------------------------------------
    # stream jobs + admin verbs
    # ------------------------------------------------------------------
    def handle_stream(self, payload, tenant):
        try:
            job = self.jobs.submit(
                stream=payload.get("stream"),
                job_id=payload.get("job_id"),
                sub_queries=payload.get("queries"),
                tenant=tenant,
            )
        except (ValueError, OSError) as exc:
            return self._reply(400, {"error": str(exc)})
        return self._reply(202, job)

    def handle_job_get(self, job_id):
        job = self.jobs.get(job_id)
        if job is None:
            return self._reply(404, {"error": f"unknown job {job_id!r}"})
        return self._reply(200, job)

    def handle_drain(self):
        """Stop admitting, wait (bounded) for in-flight work. /healthz
        turns 503 `draining` the moment the flag is set, so a load
        balancer stops routing BEFORE the pool empties. The flag flips
        under _state_lock so it orders against _enter's post-acquire
        re-check: every request the drain poll can miss is one that
        will shed instead of executing."""
        with self._state_lock:
            self.draining = True
        deadline = time.monotonic() + self.drain_timeout_s
        while self.in_flight() > 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        remaining = self.in_flight()
        return self._reply(200, {
            "draining": True,
            "drained": remaining == 0,
            "in_flight": remaining,
            "jobs_paused": self.jobs.running_count(),
        })

    def handle_reload(self):
        """Re-resolve the warehouse: drop every snapshot pin + cached
        device column (and run the CLI-provided re-registration when
        wired) so the next statements read fresh lakehouse heads / newly
        added tables. In-flight statements keep their plan-time pins."""
        reloaded = {"reloaded": True}
        sessions = [self.session]
        if self.writer_session is not None:
            sessions.append(self.writer_session)
        if self._reload_fn is not None:
            reloaded["tables"] = self._reload_fn()
        dropped = []
        for s in sessions:
            s._catalog_changed()  # plan/join-order caches may be stale
            for e in s.catalog.entries.values():
                e.device_cols = {}
                e.nrows = None
                e.pk_verified = None
                # drop the pin WITHOUT releasing its reader lease here
                # (catalog.invalidate would): an in-flight statement may
                # still be scanning the pinned snapshot's files, and
                # releasing mid-scan would expose them to a concurrent
                # vacuum. The lease is released when the LAST statement
                # that was in flight at this reload finishes (below);
                # TTL expiry remains the crash backstop.
                e.pinned_version = None
                e.pinned_snapshot = None
                if e.lease_id is not None:
                    dropped.append(e.lease_id)
                    e.lease_id = None
        if dropped:
            release_now = []
            with self._state_lock:
                if self._active_rids:
                    self._deferred_leases.append(
                        (set(self._active_rids), dropped)
                    )
                else:
                    release_now = dropped
            if release_now:
                from ..lakehouse.leases import LEASES

                for lid in release_now:
                    LEASES.release(lid)
            reloaded["leases_dropped"] = len(dropped)
            reloaded["leases_deferred"] = 0 if release_now else len(dropped)
        reloaded["sessions"] = len(sessions)
        # a reloaded replica re-enters service: the rolling fleet recipe
        # is drain -> reload -> resume, and /reload is the resume (the
        # router stops routing the moment /healthz flips 503 on drain,
        # and starts again when the reload answer arrives)
        with self._state_lock:
            reloaded["undrained"] = self.draining
            self.draining = False
        return self._reply(200, reloaded)

    def close(self):
        """Terminal: stop admitting (tests + CLI shutdown). Idempotent.
        The flag flips under _state_lock like handle_drain's: an unlocked
        write would not order against _enter's post-acquire re-check, so
        a request could start executing after close() returned."""
        with self._state_lock:
            self.draining = True
