"""Resumable server-side stream jobs: `POST /stream` + `GET /jobs/<id>`.

A whole generated query stream runs as ONE background job on the warm
service session — the serve-mode analogue of a Power Run, submitted over
HTTP instead of a CLI invocation. Job progress checkpoints to an
atomically-rewritten per-job state file on the PR-2 `bench_state` pattern
(fingerprint-guarded: a resubmitted job with the same id + stream resumes
from its completed set instead of re-running finished queries; a state
file from a DIFFERENT stream under the same id is a loud error), so a
server restart — or a drain that paused the job mid-stream — loses at
most the in-flight query.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time

from ..io.fs import fs_open_atomic
from ..engine.lockdebug import make_lock


def resolve_job_dir(conf: dict | None = None) -> str:
    """Job-state directory (`engine.serve_job_dir` / NDS_SERVE_JOB_DIR);
    default under the system temp dir, per-user."""
    v = None
    if conf:
        v = conf.get("engine.serve_job_dir")
    if v is None:
        v = os.environ.get("NDS_SERVE_JOB_DIR")
    if v:
        return str(v)
    import tempfile

    return os.path.join(
        tempfile.gettempdir(), f"nds-tpu-serve-jobs-{os.getuid()}"
    )


class StreamJobs:
    """In-memory registry + on-disk checkpoints of stream jobs."""

    #: per-query shed (429) retry budget + linear backoff base: a job is
    #: background work, so it yields to interactive load and tries again
    SHED_RETRIES = 10
    SHED_BACKOFF_S = 0.5

    def __init__(self, service, job_dir: str | None = None):
        self.service = service
        self.job_dir = job_dir or resolve_job_dir(
            getattr(service.session, "conf", None)
        )
        self._lock = make_lock("StreamJobs._lock")
        self._jobs = {}  # job_id -> state dict  # nds-guarded-by: _lock

    # ------------------------------------------------------------------
    def _state_path(self, job_id: str) -> str:
        return os.path.join(self.job_dir, f"serve-job-{job_id}.json")

    @staticmethod
    def _fingerprint(stream: str, names) -> str:
        blob = json.dumps([str(stream), sorted(names)])
        return hashlib.sha256(blob.encode()).hexdigest()[:16]

    def submit(self, stream, job_id=None, sub_queries=None,
               tenant="default"):
        """Start (or resume) a job over a server-side stream file.
        Returns the public job snapshot immediately (202 semantics)."""
        from ..power import gen_sql_from_stream, get_query_subset

        if not stream:
            raise ValueError("stream job needs 'stream' (a server-side "
                             "generated stream file path)")
        queries = gen_sql_from_stream(str(stream))
        if sub_queries:
            queries = get_query_subset(queries, list(sub_queries))
        names = list(queries)
        fp = self._fingerprint(stream, names)
        if not job_id:
            job_id = fp
        job_id = str(job_id)
        with self._lock:
            live = self._jobs.get(job_id)
            if live is not None and live["state"] == "running":
                return self._public(live)
            completed = self._completed_from_checkpoint(job_id, fp)
            job = {
                "job_id": job_id,
                "fingerprint": fp,
                "stream": str(stream),
                "tenant": tenant,
                "state": "running",
                "total": len(names),
                "queries": dict(completed),
                "started_ts_ms": int(time.time() * 1000),
            }
            self._jobs[job_id] = job
        t = threading.Thread(
            target=self._run_job, args=(job, queries),
            name=f"nds-serve-job-{job_id}", daemon=True,
        )
        t.start()
        with self._lock:
            return self._public(job)

    def _completed_from_checkpoint(self, job_id, fp):
        """Completed-query records from a prior checkpoint with a
        MATCHING fingerprint (the resume set); a fingerprint mismatch is
        a loud error — resuming a different stream under the same id
        would silently mix two jobs' results."""
        path = self._state_path(job_id)
        if not os.path.exists(path):
            return {}
        try:
            with open(path, encoding="utf-8") as f:
                raw = json.load(f)
        except (OSError, ValueError):
            return {}  # torn/unreadable checkpoint: start fresh
        if raw.get("fingerprint") != fp:
            raise ValueError(
                f"job {job_id!r} checkpoint was written by a different "
                f"stream (fingerprint {raw.get('fingerprint')} != {fp}); "
                f"pick a new job_id or delete {path}"
            )
        return {
            name: rec
            for name, rec in (raw.get("queries") or {}).items()
            if rec.get("status") == "completed"
        }

    def _checkpoint(self, job):
        try:
            os.makedirs(self.job_dir, exist_ok=True)
            with fs_open_atomic(self._state_path(job["job_id"]), "w") as f:
                json.dump(job, f, indent=2, default=str)
        except OSError:
            pass  # checkpointing is resilience, not correctness

    def _run_job(self, job, queries):
        """Sequential stream execution through the service's OWN admission
        path (each query claims a slot like an external request would — a
        job must not starve interactive tenants). A drain pauses the job
        at the next query boundary; resubmission resumes it."""
        svc = self.service
        tenant = job["tenant"]
        for name, sql_text in queries.items():
            if name in job["queries"]:
                continue  # resumed: already completed in a prior run
            t0 = time.perf_counter()
            # a 429 here is BACKPRESSURE (the job competes for admission
            # slots with interactive tenants by design), not a query
            # failure: back off and retry the bounded budget, so a busy
            # minute doesn't brand the whole job 'failed'
            for attempt in range(self.SHED_RETRIES + 1):
                if svc.draining:
                    self._finish(job, state="paused")
                    return
                status, _, body, _ = svc.handle_query(
                    {"sql": sql_text, "limit": 1}, tenant
                )
                if status not in (429, 503):
                    break
                time.sleep(self.SHED_BACKOFF_S * (attempt + 1))
            if status == 503:
                # raced a drain flip mid-request: pause, resumable
                self._finish(job, state="paused")
                return
            rec = {
                "status": "completed" if status == 200 else "failed",
                "http_status": status,
                "ms": round((time.perf_counter() - t0) * 1000.0, 3),
            }
            if status != 200:
                try:
                    rec["error"] = json.loads(body).get("error")
                except ValueError:
                    pass
            # mutations hold the registry lock: GET /jobs iterates this
            # dict via _public while the job runs
            with self._lock:
                job["queries"][name] = rec
            self._checkpoint(job)
        with self._lock:
            failed = sum(
                1 for r in job["queries"].values()
                if r["status"] != "completed"
            )
            job["state"] = "failed" if failed else "completed"
            job["failed"] = failed
        self._checkpoint(job)

    def _finish(self, job, state):
        with self._lock:
            job["state"] = state
        self._checkpoint(job)

    # ------------------------------------------------------------------
    def _public(self, job) -> dict:
        done = sum(
            1 for r in job["queries"].values()
            if r.get("status") == "completed"
        )
        failed = sum(
            1 for r in job["queries"].values()
            if r.get("status") == "failed"
        )
        return {
            "job_id": job["job_id"],
            "state": job["state"],
            "stream": job["stream"],
            "tenant": job["tenant"],
            "total": job["total"],
            "completed": done,
            "failed": failed,
            "queries": dict(job["queries"]),
        }

    def get(self, job_id):
        with self._lock:
            job = self._jobs.get(str(job_id))
            if job is not None:
                # snapshot under the lock: the runner thread mutates
                # job["queries"] while we iterate it
                return self._public(job)
        # not live in this process: fall back to the checkpoint (a
        # restarted server can still report a prior run's progress)
        path = self._state_path(str(job_id))
        try:
            with open(path, encoding="utf-8") as f:
                raw = json.load(f)
        except (OSError, ValueError):
            return None
        return self._public(raw) if raw.get("queries") is not None else None

    def running_count(self) -> int:
        with self._lock:
            return sum(
                1 for j in self._jobs.values() if j["state"] == "running"
            )
