"""QueryRouter: the fault-tolerant front over N serve replicas.

The reference harness gets this tier free from Spark (driver + cluster
manager restart semantics); here the fleet story has to be built, and it
is the layer where every single-host guarantee either composes across
hosts or quietly doesn't. The router is a thin HTTP app riding the SAME
obs/httpserv.attach_app seam the replicas use (one listener per process,
never a second server stack) in its OWN process: /metrics, /statusz,
/healthz and the routed /query all answer from one port.

Routing is BY BUDGET VERDICT: the router asks a replica's POST /plan
probe for the statement's plan-budget verdict (cached by plan
fingerprint, so steady-state traffic never pays a probe), then

    reject                  429 at the edge — provably no replica worker
                            slot is consumed (the /plan probe takes no
                            admission slot and emits no serve_request)
    spill | blocked | over  the mesh-backed replica (the one with the
                            device capacity the verdict says it needs)
    direct | unknown        any warm replica, least-in-flight

Robustness fronts:

* failure detection + failover — per-replica health from /healthz probes
  plus passive signals (connect refused / mid-stream death / latency).
  A SIGKILL'd replica mid-query costs ONE classified failover retry:
  SELECTs retry on another replica under the per-request retry budget
  with decorrelated-jitter backoff; DML retries only when the statement
  provably never started (connection refused before dispatch) — a
  mid-stream DML death is AMBIGUOUS (the commit may have published), so
  it fails classified-retryable with the router-minted idempotency key
  echoed: the client's keyed retry is deduped by the replica ledger and
  arbitrated by the OCC statement path, never double-applied.
* anti-retry-storm — failover retries draw from a token bucket per
  (tenant, statement class); an exhausted bucket propagates the shed
  instead of amplifying it, and every 429/503 carries a Retry-After with
  decorrelated jitter so a shed burst never re-arrives in lockstep (the
  hazard documented at serve/service.py RETRY_AFTER_S).
* graceful degradation on coordinator loss — a DML that fails with
  "catalog unreachable" opens a DML circuit: further DML fast-fails at
  the edge (503, classified io_transient) while pinned SELECTs keep
  serving from replicas holding live leases; after a cooldown one
  half-open probe rides through and a success closes the circuit.
  /statusz's fleet section names exactly which capability is degraded.
* fleet lifecycle — POST /fleet/reload rolls drain -> reload across the
  replicas one at a time (the router stops routing to the draining
  replica first, so zero in-flight requests drop), and the fleet-wide
  per-tenant quota (`engine.route_tenant_cap`) is the router-enforced
  equivalent of the per-replica serve_tenant_cap.

Fault sites: `route:pick` (selection; an injected failure sheds the
request, never the router), `route:forward` (the forward hop; injected
io looks like a dead replica and exercises the failover budget).
"""

from __future__ import annotations

import hashlib
import json
import math
import random
import re
import threading
import time
import uuid

from .. import faults
from ..engine.lockdebug import make_lock

#: default per-request upstream attempt budget (first try + failovers)
DEFAULT_ROUTE_RETRIES = 3

#: default failover token bucket per (tenant, class): capacity / refill
DEFAULT_RETRY_BURST = 8
DEFAULT_RETRY_RATE = 2.0

#: decorrelated-jitter backoff between failover attempts (seconds)
DEFAULT_BACKOFF_BASE_S = 0.05
DEFAULT_BACKOFF_CAP_S = 2.0

#: active /healthz probe period; 0 disables the prober thread (tests)
DEFAULT_HEALTH_INTERVAL_S = 2.0

#: plan-fingerprint -> verdict cache entries kept (LRU)
DEFAULT_VERDICT_CACHE = 512

#: DML circuit-breaker cooldown after "catalog unreachable" (seconds)
DEFAULT_CATALOG_COOLDOWN_S = 5.0

#: upstream transport timeouts (seconds)
DEFAULT_CONNECT_TIMEOUT_S = 2.0
DEFAULT_REQUEST_TIMEOUT_S = 600.0

#: Retry-After base advertised on edge sheds (jittered per response)
EDGE_RETRY_AFTER_S = 2.0

_SELECT_LEAD = ("select", "with", "(")


def _resolve(conf, key, env, default, cast=float, floor=0.0):
    v = None
    if conf:
        v = conf.get(key)
    if v is None:
        import os

        v = os.environ.get(env)
    if v is None or str(v).strip() == "":
        return default
    try:
        return max(cast(v), floor)
    except (TypeError, ValueError):
        return default


class _ConnectError(Exception):
    """The upstream connection never opened — the request provably never
    reached the replica (safe to retry any statement class)."""


class _MidStreamError(Exception):
    """The replica died (or the socket broke) AFTER the request was
    sent — the outcome is ambiguous for writes."""


class Replica:
    """One registered upstream: address + live health/accounting state
    (mutated under the router lock)."""

    def __init__(self, url: str, mesh: bool = False):
        url = str(url).strip()
        if "//" in url:
            url = url.split("//", 1)[1]
        url = url.rstrip("/")
        host, _, port = url.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(
                f"bad replica address {url!r} (want host:port)"
            )
        self.host = host
        self.port = int(port)
        self.name = f"{host}:{port}"
        self.mesh = mesh
        self.healthy = True  # nds-guarded-by: _lock
        self.draining = False  # nds-guarded-by: _lock
        self.in_flight = 0  # nds-guarded-by: _lock
        self.requests = 0  # nds-guarded-by: _lock
        self.failures = 0  # nds-guarded-by: _lock
        self.consecutive_errors = 0  # nds-guarded-by: _lock
        self.last_latency_ms = None  # nds-guarded-by: _lock
        self.last_probe_ok_ts = None  # nds-guarded-by: _lock

    def snapshot(self) -> dict:
        return {
            "replica": self.name,
            "mesh": self.mesh,
            "healthy": self.healthy,
            "draining": self.draining,
            "in_flight": self.in_flight,
            "requests": self.requests,
            "failures": self.failures,
            "last_latency_ms": self.last_latency_ms,
        }


class QueryRouter:
    """The fleet-router application behind obs/httpserv.py's route seam
    (attach with `MetricsServer.attach_app`; the listener's built-in
    /healthz answers 503 while `self.draining`)."""

    def __init__(self, replicas, conf=None, tracer=None,
                 mesh_replica=None):
        conf = conf or {}
        self.tracer = tracer
        self.replicas = []
        mesh_name = str(mesh_replica).strip() if mesh_replica else None
        if mesh_name and "//" in mesh_name:
            mesh_name = mesh_name.split("//", 1)[1]
        for r in replicas:
            rep = r if isinstance(r, Replica) else Replica(r)
            if mesh_name and rep.name == mesh_name.rstrip("/"):
                rep.mesh = True
            self.replicas.append(rep)
        if not self.replicas:
            raise ValueError("router needs at least one replica")
        self.max_attempts = int(_resolve(
            conf, "engine.route_retries", "NDS_ROUTE_RETRIES",
            DEFAULT_ROUTE_RETRIES, cast=int, floor=1,
        ))
        self.retry_burst = _resolve(
            conf, "engine.route_retry_burst", "NDS_ROUTE_RETRY_BURST",
            DEFAULT_RETRY_BURST,
        )
        self.retry_rate = _resolve(
            conf, "engine.route_retry_rate", "NDS_ROUTE_RETRY_RATE",
            DEFAULT_RETRY_RATE,
        )
        self.backoff_base_s = _resolve(
            conf, "engine.route_backoff_base_s", "NDS_ROUTE_BACKOFF_BASE_S",
            DEFAULT_BACKOFF_BASE_S,
        )
        self.backoff_cap_s = _resolve(
            conf, "engine.route_backoff_cap_s", "NDS_ROUTE_BACKOFF_CAP_S",
            DEFAULT_BACKOFF_CAP_S,
        )
        self.health_interval_s = _resolve(
            conf, "engine.route_health_interval_s",
            "NDS_ROUTE_HEALTH_INTERVAL_S", DEFAULT_HEALTH_INTERVAL_S,
        )
        # 0 = no fleet cap (per-replica serve_tenant_cap still applies)
        self.tenant_cap = int(_resolve(
            conf, "engine.route_tenant_cap", "NDS_ROUTE_TENANT_CAP",
            0, cast=int,
        ))
        self.verdict_cache_cap = int(_resolve(
            conf, "engine.route_verdict_cache", "NDS_ROUTE_VERDICT_CACHE",
            DEFAULT_VERDICT_CACHE, cast=int, floor=0,
        ))
        self.catalog_cooldown_s = _resolve(
            conf, "engine.route_catalog_cooldown_s",
            "NDS_ROUTE_CATALOG_COOLDOWN_S", DEFAULT_CATALOG_COOLDOWN_S,
        )
        self.connect_timeout_s = _resolve(
            conf, "engine.route_connect_timeout_s",
            "NDS_ROUTE_CONNECT_TIMEOUT_S", DEFAULT_CONNECT_TIMEOUT_S,
            floor=0.1,
        )
        self.request_timeout_s = _resolve(
            conf, "engine.route_request_timeout_s",
            "NDS_ROUTE_REQUEST_TIMEOUT_S", DEFAULT_REQUEST_TIMEOUT_S,
            floor=1.0,
        )
        self._lock = make_lock("QueryRouter._lock")
        self._rr = 0  # nds-guarded-by: _lock
        self._tenant_in_flight = {}  # nds-guarded-by: _lock
        # (tenant, class) -> [tokens, last_refill_monotonic]
        self._buckets = {}  # nds-guarded-by: _lock
        # plan fingerprint -> /plan verdict payload (LRU via re-insert)
        self._verdicts = {}  # nds-guarded-by: _lock
        self._verdict_order = []  # nds-guarded-by: _lock
        # capability -> {"reason", "since_ts_ms"} while degraded
        self._degraded = {}  # nds-guarded-by: _lock
        self._dml_half_open_at = 0.0  # nds-guarded-by: _lock
        self.draining = False  # nds-guarded-by: _lock
        self.started_ts_ms = int(time.time() * 1000)
        self._closed = threading.Event()
        self._prober = None
        if self.health_interval_s > 0:
            self._prober = threading.Thread(
                target=self._probe_loop, name="nds-route-health",
                daemon=True,
            )
            self._prober.start()

    # ------------------------------------------------------------------
    # HTTP seam
    # ------------------------------------------------------------------
    def handle_http(self, method, path, headers, body):
        tenant = str(headers.get("x-nds-tenant") or "default")
        if method == "POST" and path == "/query":
            try:
                payload = self._json_body(body)
            except ValueError as exc:
                return self._reply(400, {"error": str(exc)})
            return self.handle_query(payload, tenant)
        if method == "GET" and path == "/fleet":
            return self._reply(200, self.fleet_snapshot())
        if method == "POST" and path == "/fleet/reload":
            return self.handle_fleet_reload()
        if method == "POST" and path == "/drain":
            return self.handle_drain()
        return None

    @staticmethod
    def _json_body(body):
        if not body:
            return {}
        try:
            obj = json.loads(body.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as exc:
            raise ValueError(f"malformed JSON request body: {exc}") from exc
        if not isinstance(obj, dict):
            raise ValueError("request body must be a JSON object")
        return obj

    @staticmethod
    def _reply(status, obj, extra_headers=()):
        return (
            status, "application/json",
            json.dumps(obj, default=str), tuple(extra_headers),
        )

    # ------------------------------------------------------------------
    # telemetry
    # ------------------------------------------------------------------
    def _emit_request(self, rid, tenant, status_label, t0, http_status,
                      replica=None, verdict=None, stmt_class=None,
                      attempts=0, queue_ms=None, forward_ms=None,
                      query=None):
        if self.tracer is None:
            return
        fields = {
            "request_id": rid,
            # the router-minted rid IS the fleet trace_id: the same value
            # rides x-nds-trace-context to every replica attempt, so one
            # grep follows router -> replica(s) -> catalog -> engine
            "trace_id": rid,
            "replica": replica,
            "verdict": verdict,
            "stmt_class": stmt_class,
            "attempts": int(attempts),
            "retries": max(int(attempts) - 1, 0),
            "query": query,
        }
        if queue_ms is not None:
            fields["queue_ms"] = round(float(queue_ms), 3)
        if forward_ms is not None:
            fields["forward_ms"] = round(float(forward_ms), 3)
        self.tracer.emit(
            "route_request",
            tenant=tenant,
            status=status_label,
            dur_ms=round((time.perf_counter() - t0) * 1000.0, 3),
            http_status=int(http_status),
            **fields,
        )

    def _emit_retry(self, replica, reason, tenant, rid, attempt,
                    delay_s=None):
        if self.tracer is None:
            return
        fields = {"tenant": tenant, "request_id": rid, "trace_id": rid,
                  "attempt": int(attempt)}
        if delay_s is not None:
            fields["delay_ms"] = round(float(delay_s) * 1000.0, 3)
        self.tracer.emit(
            "route_retry", replica=replica, reason=reason, **fields
        )

    # ------------------------------------------------------------------
    # fleet state
    # ------------------------------------------------------------------
    def fleet_snapshot(self) -> dict:
        """The live fleet view merged into /statusz's "fleet" section
        (MetricsSink.set_fleet_provider) and served raw on GET /fleet."""
        with self._lock:
            return {
                "replicas": [r.snapshot() for r in self.replicas],
                "degraded": {k: dict(v) for k, v in self._degraded.items()},
                "tenant_in_flight": dict(self._tenant_in_flight),
                "tenant_cap": self.tenant_cap,
                "verdict_cache_entries": len(self._verdicts),
                "draining": self.draining,
            }

    def _probe_loop(self):
        while not self._closed.wait(self.health_interval_s):
            for rep in self.replicas:
                self.probe_replica(rep)

    def probe_replica(self, rep: Replica):
        """One active /healthz probe: 200 -> healthy, 503 -> draining
        (alive but not routable), transport error -> unhealthy."""
        try:
            status, body, _ = self._http(
                rep, "GET", "/healthz", None, (),
                timeout=self.connect_timeout_s,
            )
        except (_ConnectError, _MidStreamError):
            with self._lock:
                rep.healthy = False
                rep.consecutive_errors += 1
            return False
        with self._lock:
            rep.healthy = True
            rep.consecutive_errors = 0
            rep.draining = (status == 503)
            rep.last_probe_ok_ts = time.time()
        return status == 200

    def close(self):
        self._closed.set()
        # under the router lock: an unlocked flip would not order against
        # a concurrent handle_query's drain check on another thread
        with self._lock:
            self.draining = True

    # ------------------------------------------------------------------
    # transport
    # ------------------------------------------------------------------
    def _http(self, rep, method, path, payload, headers, timeout=None):
        """One upstream exchange. Raises _ConnectError when the request
        provably never reached the replica, _MidStreamError when the
        socket broke after dispatch (ambiguous outcome)."""
        import http.client

        body = None
        hdrs = dict(headers or ())
        if payload is not None:
            body = json.dumps(payload).encode("utf-8")
            hdrs["Content-Type"] = "application/json"
        conn = http.client.HTTPConnection(
            rep.host, rep.port,
            timeout=self.request_timeout_s if timeout is None else timeout,
        )
        conn.timeout = self.connect_timeout_s
        try:
            try:
                conn.connect()
            except OSError as exc:
                raise _ConnectError(
                    f"{rep.name}: {type(exc).__name__}: {exc}"
                ) from exc
            conn.sock.settimeout(
                self.request_timeout_s if timeout is None else timeout
            )
            try:
                conn.request(method, path, body=body, headers=hdrs)
                resp = conn.getresponse()
                data = resp.read()
            except (OSError, http.client.HTTPException) as exc:
                raise _MidStreamError(
                    f"{rep.name}: {type(exc).__name__}: {exc}"
                ) from exc
            return resp.status, data, dict(resp.getheaders())
        finally:
            conn.close()

    def _forward_query(self, rep, payload, tenant, rid, request_key):
        """Forward POST /query with the trace context + idempotency
        headers stamped; accounts in-flight + passive health."""
        faults.maybe_fire("route:forward", kinds=("io", "hang", "crash"))
        parent = getattr(
            getattr(self.tracer, "context", None), "trace_id", None
        )
        hdrs = {
            "X-NDS-Tenant": tenant,
            # the HTTP carriage of NDS_TRACE_CONTEXT: the replica adopts
            # the trace_id half as its request id
            "X-NDS-Trace-Context": f"{rid},{parent or rid}",
        }
        if request_key:
            hdrs["X-NDS-Request-Key"] = request_key
        with self._lock:
            rep.in_flight += 1
            rep.requests += 1
        t0 = time.perf_counter()
        try:
            status, data, rhdrs = self._http(
                rep, "POST", "/query", payload, hdrs
            )
        except (_ConnectError, _MidStreamError):
            with self._lock:
                rep.in_flight -= 1
                rep.failures += 1
                rep.consecutive_errors += 1
                # passive failure detection: stop routing here until the
                # prober (or a probe on pick-starvation) clears it
                rep.healthy = False
            raise
        with self._lock:
            rep.in_flight -= 1
            rep.consecutive_errors = 0
            rep.last_latency_ms = round(
                (time.perf_counter() - t0) * 1000.0, 3
            )
            if status >= 500:
                rep.failures += 1
        return status, data, rhdrs

    # ------------------------------------------------------------------
    # selection + verdicts
    # ------------------------------------------------------------------
    def _pick(self, verdict=None, exclude=()):
        """Least-in-flight healthy replica (round-robin tiebreak); a
        spill/blocked/over verdict narrows to the mesh-backed replica
        when one is registered + healthy. With NO healthy candidate the
        least-loaded non-draining one gets a second chance (the request
        itself is the probe — the alternative is failing the whole fleet
        on one stale health bit)."""
        faults.maybe_fire("route:pick", kinds=("io", "hang", "crash"))
        with self._lock:
            cands = [
                r for r in self.replicas
                if r not in exclude and not r.draining and r.healthy
            ]
            if not cands:
                cands = [
                    r for r in self.replicas
                    if r not in exclude and not r.draining
                ]
            if not cands:
                return None
            v = (verdict or {}).get("verdict")
            if v in ("spill", "blocked", "over"):
                mesh = [r for r in cands if r.mesh]
                if mesh:
                    cands = mesh
            low = min(r.in_flight for r in cands)
            cands = [r for r in cands if r.in_flight == low]
            rep = cands[self._rr % len(cands)]
            self._rr += 1
            return rep

    @staticmethod
    def classify_payload(payload) -> str:
        """select | dml from the leading keyword — cheap edge routing
        only; the replica's parser is the authority (templates are
        SELECT streams by construction)."""
        sql = payload.get("sql")
        if not sql:
            return "select"
        head = re.sub(r"(?:\s|--[^\n]*\n?)*", "", str(sql), count=1)
        word = re.split(r"[\s(]", head.lower(), maxsplit=1)[0] or head[:1]
        return "select" if head[:1] == "(" or word in _SELECT_LEAD else "dml"

    @staticmethod
    def fingerprint(payload):
        """Plan fingerprint for the verdict cache: whitespace-folded SQL
        text, or template name + params (the verdict depends on both)."""
        sql = payload.get("sql")
        if sql:
            key = " ".join(str(sql).split()).lower()
        else:
            name = payload.get("template")
            if not name:
                return None
            params = {
                str(k): str(v)
                for k, v in (payload.get("params") or {}).items()
            }
            key = json.dumps(["tmpl", str(name), params], sort_keys=True)
        return hashlib.sha1(key.encode("utf-8")).hexdigest()[:16]

    def _verdict_for(self, payload, tenant, stmt_class):
        """Cached budget verdict, else one /plan probe against a warm
        replica. The probe consumes NO admission slot replica-side
        (handle_plan's contract) — an edge 429 never costs a worker."""
        if stmt_class != "select" or self.verdict_cache_cap <= 0:
            return None
        fp = self.fingerprint(payload)
        if fp is None:
            return None
        with self._lock:
            hit = self._verdicts.get(fp)
            if hit is not None:
                return hit
        rep = self._pick()
        if rep is None:
            return None
        try:
            status, data, _ = self._http(
                rep, "POST", "/plan", payload,
                {"X-NDS-Tenant": tenant},
                timeout=min(30.0, self.request_timeout_s),
            )
        except (_ConnectError, _MidStreamError):
            with self._lock:
                rep.healthy = False
                rep.consecutive_errors += 1
            return None
        if status != 200:
            return None
        try:
            obj = json.loads(data.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return None
        if not isinstance(obj, dict):
            return None
        obj.pop("request_id", None)
        with self._lock:
            if fp not in self._verdicts:
                self._verdict_order.append(fp)
            self._verdicts[fp] = obj
            while len(self._verdict_order) > self.verdict_cache_cap:
                self._verdicts.pop(self._verdict_order.pop(0), None)
        return obj

    # ------------------------------------------------------------------
    # retry budget (anti-storm)
    # ------------------------------------------------------------------
    def _take_token(self, tenant, stmt_class) -> bool:
        """One failover retry costs one token from the (tenant, class)
        bucket; the FIRST attempt is free. An empty bucket means the
        fleet propagates the failure instead of amplifying it."""
        now = time.monotonic()
        with self._lock:
            tokens, last = self._buckets.get(
                (tenant, stmt_class), (self.retry_burst, now)
            )
            tokens = min(
                self.retry_burst, tokens + (now - last) * self.retry_rate
            )
            if tokens < 1.0:
                self._buckets[(tenant, stmt_class)] = (tokens, now)
                return False
            self._buckets[(tenant, stmt_class)] = (tokens - 1.0, now)
            return True

    def _jitter_retry_after(self, base=None):
        """Decorrelated Retry-After: clients that shed together must not
        re-arrive together (serve/service.py's documented lockstep
        hazard). Returns (float seconds for the body, header tuple)."""
        base = float(base or EDGE_RETRY_AFTER_S)
        ra = round(random.uniform(base * 0.5, base * 1.5), 2)
        ra = max(ra, 0.1)
        return ra, (("Retry-After", str(int(math.ceil(ra)))),)

    def _backoff_sleep(self, prev_s):
        """Decorrelated-jitter backoff between failover attempts."""
        delay = min(
            self.backoff_cap_s,
            random.uniform(self.backoff_base_s, max(prev_s, 0.001) * 3.0),
        )
        time.sleep(delay)
        return delay

    # ------------------------------------------------------------------
    # /query
    # ------------------------------------------------------------------
    def handle_query(self, payload, tenant):
        rid = uuid.uuid4().hex[:12]
        t0 = time.perf_counter()
        if self.draining:
            return self._edge_shed(
                rid, tenant, t0, "router is draining", status=503,
                label="draining",
            )
        stmt_class = self.classify_payload(payload)
        if self.tenant_cap and not self._tenant_enter(tenant):
            return self._edge_shed(
                rid, tenant, t0,
                f"tenant {tenant!r} is at the fleet in-flight cap "
                f"({self.tenant_cap}); retry later",
                stmt_class=stmt_class,
            )
        try:
            return self._routed_query(payload, tenant, rid, t0, stmt_class)
        finally:
            if self.tenant_cap:
                self._tenant_leave(tenant)

    def _tenant_enter(self, tenant) -> bool:
        with self._lock:
            if self._tenant_in_flight.get(tenant, 0) >= self.tenant_cap:
                return False
            self._tenant_in_flight[tenant] = (
                self._tenant_in_flight.get(tenant, 0) + 1
            )
            return True

    def _tenant_leave(self, tenant):
        with self._lock:
            n = self._tenant_in_flight.get(tenant, 1) - 1
            if n <= 0:
                self._tenant_in_flight.pop(tenant, None)
            else:
                self._tenant_in_flight[tenant] = n

    def _edge_shed(self, rid, tenant, t0, reason, status=429,
                   label="shed", stmt_class=None, extra=None,
                   attempts=0):
        ra, hdrs = self._jitter_retry_after()
        body = {
            "request_id": rid, "tenant": tenant, "status": label,
            "error": reason, "retry_after_s": ra,
        }
        if extra:
            body.update(extra)
        self._emit_request(
            rid, tenant, label, t0, status, stmt_class=stmt_class,
            attempts=attempts,
        )
        return self._reply(status, body, hdrs)

    def _dml_degraded_reason(self):
        """The degraded-DML circuit: fast-fail at the edge during the
        cooldown, then let exactly one half-open probe through."""
        with self._lock:
            deg = self._degraded.get("dml")
            if not deg:
                return None
            now = time.monotonic()
            if now >= self._dml_half_open_at:
                # this request is the half-open probe; hold the circuit
                # for everyone else for another cooldown
                self._dml_half_open_at = now + self.catalog_cooldown_s
                return None
            return deg.get("reason") or "catalog unreachable"

    def _open_dml_circuit(self, reason):
        with self._lock:
            self._degraded["dml"] = {
                "reason": str(reason)[:200],
                "since_ts_ms": int(time.time() * 1000),
            }
            self._dml_half_open_at = (
                time.monotonic() + self.catalog_cooldown_s
            )

    def _close_dml_circuit(self):
        with self._lock:
            self._degraded.pop("dml", None)

    @staticmethod
    def _is_catalog_unreachable(obj) -> bool:
        if not isinstance(obj, dict):
            return False
        err = str(obj.get("error") or "")
        return (
            obj.get("failure_kind") == faults.IO_TRANSIENT
            and "catalog unreachable" in err.lower()
        )

    def _routed_query(self, payload, tenant, rid, t0, stmt_class):
        if stmt_class == "dml":
            reason = self._dml_degraded_reason()
            if reason is not None:
                # SELECTs keep serving pinned reads; DML is the degraded
                # capability and fails classified-retryable at the edge
                return self._edge_shed(
                    rid, tenant, t0,
                    f"DML degraded: {reason}", status=503, label="failed",
                    stmt_class=stmt_class,
                    extra={"failure_kind": faults.IO_TRANSIENT,
                           "degraded": "dml"},
                )
        try:
            verdict = self._verdict_for(payload, tenant, stmt_class)
        except faults.FaultError as exc:
            return self._edge_shed(
                rid, tenant, t0, f"route fault: {exc}",
                stmt_class=stmt_class,
                extra={"failure_kind": faults.classify(exc)},
            )
        if (verdict or {}).get("verdict") == "reject":
            # 429 at the edge — no replica worker slot consumed (the
            # serve_bench fleet smoke proves the reject tenant never
            # appears in any replica's /statusz tenants section)
            ra, hdrs = self._jitter_retry_after()
            self._emit_request(
                rid, tenant, "rejected", t0, 429, verdict="reject",
                stmt_class=stmt_class,
            )
            return self._reply(429, {
                "request_id": rid, "tenant": tenant, "status": "rejected",
                "verdict": "reject",
                "error": verdict.get("error") or "plan budget reject",
                "peak_bytes": verdict.get("peak_bytes"),
                "budget_bytes": verdict.get("budget_bytes"),
                "retry_after_s": ra,
            }, hdrs)
        # DML failovers carry a router-minted idempotency key the replica
        # ledger dedups (the OCC statement path stays the arbiter)
        request_key = uuid.uuid4().hex[:16] if stmt_class == "dml" else None
        queue_ms = (time.perf_counter() - t0) * 1000.0
        return self._forward_with_retries(
            payload, tenant, rid, t0, stmt_class, verdict, request_key,
            queue_ms,
        )

    def _forward_with_retries(self, payload, tenant, rid, t0, stmt_class,
                              verdict, request_key, queue_ms):
        tried = []
        attempts = 0
        forward_ms = 0.0
        prev_delay = self.backoff_base_s
        last_error = None
        vlabel = (verdict or {}).get("verdict")
        qlabel = payload.get("template")
        while attempts < self.max_attempts:
            try:
                rep = self._pick(verdict, exclude=tried)
            except faults.FaultError as exc:
                return self._edge_shed(
                    rid, tenant, t0, f"route fault: {exc}",
                    stmt_class=stmt_class, attempts=attempts,
                    extra={"failure_kind": faults.classify(exc)},
                )
            if rep is None:
                if not tried:
                    return self._edge_shed(
                        rid, tenant, t0, "no healthy replica", status=503,
                        label="failed", stmt_class=stmt_class,
                        extra={"failure_kind": faults.IO_TRANSIENT},
                    )
                break
            attempts += 1
            f0 = time.perf_counter()
            try:
                status, data, rhdrs = self._forward_query(
                    rep, payload, tenant, rid, request_key
                )
            except faults.FaultError as exc:
                forward_ms += (time.perf_counter() - f0) * 1000.0
                last_error = f"injected fault at route:forward: {exc}"
                tried.append(rep)
                self._emit_retry(rep.name, "fault", tenant, rid, attempts)
                if attempts >= self.max_attempts or not self._take_token(
                    tenant, stmt_class
                ):
                    break
                prev_delay = self._backoff_sleep(prev_delay)
                continue
            except _ConnectError as exc:
                forward_ms += (time.perf_counter() - f0) * 1000.0
                last_error = f"connect: {exc}"
                tried.append(rep)
                delay = None
                # the request never reached the replica: ANY class is
                # safe to fail over, DML included
                if attempts < self.max_attempts and self._take_token(
                    tenant, stmt_class
                ):
                    delay = self._backoff_sleep(prev_delay)
                    prev_delay = delay
                    self._emit_retry(
                        rep.name, "connect", tenant, rid, attempts,
                        delay_s=delay,
                    )
                    continue
                self._emit_retry(rep.name, "connect", tenant, rid, attempts)
                break
            except _MidStreamError as exc:
                forward_ms += (time.perf_counter() - f0) * 1000.0
                last_error = f"mid-stream: {exc}"
                tried.append(rep)
                if stmt_class == "dml":
                    # AMBIGUOUS: the replica may have committed before
                    # dying. Fail classified-retryable with the key
                    # echoed — a keyed client retry is deduped by the
                    # replica ledger, never double-applied.
                    self._emit_retry(
                        rep.name, "midstream", tenant, rid, attempts
                    )
                    ra, hdrs = self._jitter_retry_after()
                    self._emit_request(
                        rid, tenant, "failed", t0, 503, replica=rep.name,
                        verdict=vlabel, stmt_class=stmt_class,
                        attempts=attempts, queue_ms=queue_ms,
                        forward_ms=forward_ms, query=qlabel,
                    )
                    return self._reply(503, {
                        "request_id": rid, "tenant": tenant,
                        "status": "failed",
                        "failure_kind": faults.IO_TRANSIENT,
                        "error": (
                            "replica died mid-DML (outcome ambiguous); "
                            f"retry with request_key: {last_error}"
                        ),
                        "request_key": request_key,
                        "retry_after_s": ra,
                        "route": self._route_info(rep, attempts),
                    }, hdrs)
                if attempts < self.max_attempts and self._take_token(
                    tenant, stmt_class
                ):
                    delay = self._backoff_sleep(prev_delay)
                    prev_delay = delay
                    self._emit_retry(
                        rep.name, "midstream", tenant, rid, attempts,
                        delay_s=delay,
                    )
                    continue
                self._emit_retry(
                    rep.name, "midstream", tenant, rid, attempts
                )
                break
            forward_ms += (time.perf_counter() - f0) * 1000.0
            obj = self._parse_json(data)
            if status in (429, 503):
                # upstream shed/drain: prefer another replica if the
                # budget allows, else propagate with jittered Retry-After
                tried.append(rep)
                if obj.get("status") == "draining":
                    with self._lock:
                        rep.draining = True
                can_retry = attempts < self.max_attempts
                try:
                    alt = self._pick(verdict, exclude=tried)
                except faults.FaultError:
                    alt = None
                if can_retry and alt is not None and self._take_token(
                    tenant, stmt_class
                ):
                    delay = self._backoff_sleep(prev_delay)
                    prev_delay = delay
                    self._emit_retry(
                        rep.name, "shed", tenant, rid, attempts,
                        delay_s=delay,
                    )
                    continue
                return self._finish(
                    rid, tenant, t0, rep, status, obj, rhdrs, attempts,
                    vlabel, stmt_class, queue_ms, forward_ms, qlabel,
                    request_key,
                )
            if status >= 500:
                fk = obj.get("failure_kind")
                if stmt_class == "dml" and self._is_catalog_unreachable(
                    obj
                ):
                    # coordinator loss: open the DML circuit so the
                    # fleet degrades at the edge instead of timing out
                    # request by request
                    self._open_dml_circuit(obj.get("error"))
                retryable = fk in faults.RETRYABLE
                tried.append(rep)
                if (
                    stmt_class == "select" and retryable
                    and attempts < self.max_attempts
                    and self._take_token(tenant, stmt_class)
                ):
                    delay = self._backoff_sleep(prev_delay)
                    prev_delay = delay
                    self._emit_retry(
                        rep.name, "upstream", tenant, rid, attempts,
                        delay_s=delay,
                    )
                    continue
                return self._finish(
                    rid, tenant, t0, rep, status, obj, rhdrs, attempts,
                    vlabel, stmt_class, queue_ms, forward_ms, qlabel,
                    request_key,
                )
            if status == 200 and stmt_class == "dml":
                self._close_dml_circuit()
            return self._finish(
                rid, tenant, t0, rep, status, obj, rhdrs, attempts,
                vlabel, stmt_class, queue_ms, forward_ms, qlabel,
                request_key,
            )
        # attempts/budget exhausted without an upstream answer
        ra, hdrs = self._jitter_retry_after()
        self._emit_request(
            rid, tenant, "failed", t0, 503,
            replica=tried[-1].name if tried else None, verdict=vlabel,
            stmt_class=stmt_class, attempts=attempts, queue_ms=queue_ms,
            forward_ms=forward_ms, query=qlabel,
        )
        return self._reply(503, {
            "request_id": rid, "tenant": tenant, "status": "failed",
            "failure_kind": faults.IO_TRANSIENT,
            "error": (
                f"no replica answered after {attempts} attempt(s) "
                f"(last: {last_error})"
            ),
            "request_key": request_key,
            "retry_after_s": ra,
            "route": {
                "attempts": attempts,
                "retries": max(attempts - 1, 0),
                "tried": [r.name for r in tried],
            },
        }, hdrs)

    @staticmethod
    def _parse_json(data):
        try:
            obj = json.loads(data.decode("utf-8")) if data else {}
        except (ValueError, UnicodeDecodeError):
            obj = {}
        return obj if isinstance(obj, dict) else {}

    def _route_info(self, rep, attempts):
        return {
            "replica": rep.name if rep else None,
            "attempts": attempts,
            "retries": max(attempts - 1, 0),
        }

    def _finish(self, rid, tenant, t0, rep, status, obj, rhdrs, attempts,
                vlabel, stmt_class, queue_ms, forward_ms, qlabel,
                request_key):
        """Relay the replica's answer with the route hop annotated; the
        route_request event is the router's own accounting of the SAME
        outcome the client saw."""
        label = {
            200: "completed", 202: "completed",
        }.get(status)
        if label is None:
            body_label = str(obj.get("status") or "")
            if status == 429:
                label = "rejected" if body_label == "rejected" else "shed"
            elif status == 503:
                label = "draining" if body_label == "draining" else "failed"
            else:
                label = "failed"
        out = dict(obj)
        out.setdefault("request_id", rid)
        out["route"] = self._route_info(rep, attempts)
        if request_key:
            out["route"]["request_key"] = request_key
        extra = []
        if status in (429, 503):
            ra, hdrs = self._jitter_retry_after(
                base=obj.get("retry_after_s")
            )
            out["retry_after_s"] = ra
            extra = list(hdrs)
        self._emit_request(
            rid, tenant, label, t0, status,
            replica=rep.name if rep else None,
            verdict=obj.get("verdict") or vlabel, stmt_class=stmt_class,
            attempts=attempts, queue_ms=queue_ms, forward_ms=forward_ms,
            query=qlabel,
        )
        return self._reply(status, out, extra)

    # ------------------------------------------------------------------
    # fleet lifecycle
    # ------------------------------------------------------------------
    def handle_fleet_reload(self):
        """Rolling drain + reload, one replica at a time: the router
        stops routing to the replica FIRST (zero new requests land on
        it), the replica's /drain waits out its in-flight work, /reload
        re-resolves the warehouse and re-opens admission, and only then
        does the roll move on — in a 2-replica fleet the other replica
        keeps serving the whole time (zero dropped in-flight)."""
        results = []
        for rep in list(self.replicas):
            with self._lock:
                rep.draining = True
            rec = {"replica": rep.name, "drained": False,
                   "reloaded": False}
            try:
                st, data, _ = self._http(rep, "POST", "/drain", {}, ())
                obj = self._parse_json(data)
                rec["drained"] = bool(st == 200 and obj.get("drained"))
                rec["in_flight"] = obj.get("in_flight")
                st2, data2, _ = self._http(rep, "POST", "/reload", {}, ())
                rec["reloaded"] = st2 == 200
            except (_ConnectError, _MidStreamError) as exc:
                rec["error"] = str(exc)
                with self._lock:
                    rep.healthy = False
            finally:
                with self._lock:
                    rep.draining = False
            results.append(rec)
        ok = all(r.get("drained") and r.get("reloaded") for r in results)
        return self._reply(200 if ok else 500, {
            "rolled": len(results), "ok": ok, "replicas": results,
        })

    def handle_drain(self):
        """Drain the ROUTER: stop accepting (healthz flips 503 via the
        listener's draining contract); replicas are left running."""
        with self._lock:
            self.draining = True
        return self._reply(200, {"draining": True, "drained": True})
