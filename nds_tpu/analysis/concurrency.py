"""Concurrency contract analyzer: the static half of the thread-safety
story (ISSUE 20), built on the PR-5 lint substrate.

The reference harness inherits thread-safety from Spark's JVM driver; this
reimplementation built its multi-thread tier by hand (serve worker pool,
router prober, DM/maintenance threads, memwatch sampler, http handlers,
spill eviction, fleet-shared stores). The chaos gates can *witness* a race
or deadlock once; the rules here make the whole class unwritable:

  guarded-by            every mutation of declared-shared state must happen
                        under the declared lock. Shared state is declared
                        at its initialising assignment with a
                        `# nds-guarded-by: <lock-attr>` comment (same line
                        or the line above); `# nds-guarded-by: none` plus a
                        reason declares by-design unguarded state (atomic
                        word stamps, monotonic beats). Any OTHER attribute
                        of a MULTITHREAD_CLASSES class that is mutated
                        outside __init__ is an UNDECLARED shared attr — the
                        annotation map must be the complete inventory.
                        Methods named `*_locked` follow the caller-holds-
                        the-lock convention and are exempt from the span
                        check. Subsumes PR-7's `cache-lock-discipline`
                        (the Session-cache half below is its old body; the
                        old rule name still works in pragmas via
                        RULE_ALIASES).
  blocking-under-lock   no filesystem / network / jit-compile / sleep call
                        inside a `with <lock>:` span: a blocking call under
                        a hot lock convoys every other thread behind a
                        syscall (and a compile under a lock can stall the
                        fleet for seconds). Known-bounded writes that the
                        lock exists to serialize carry a justified pragma.
  lock-order            the static lock-acquisition graph (nested `with`
                        spans plus call edges, resolved through the named-
                        lock registry) must stay acyclic and must match the
                        canonical order pinned in anchors/lock_order.golden
                        — regenerate with
                        `python -m nds_tpu.cli.lint --write-lock-order`.
                        The runtime half (engine/lockdebug.py,
                        `engine.lock_debug`) asserts the same pinned order
                        on live acquisitions.
  thread-leak           every `threading.Thread(...)` must either be
                        daemonized (`daemon=True`) or have its binding
                        (variable or attribute) `.join()`ed somewhere in
                        the same module — the PR-2 throughput child-handle
                        bug class, for threads.

Scope note (honest limits): span detection is line-based and per-file, the
same bet `cache-lock-discipline` made — a lock held by a caller needs a
`*_locked` method name or a justified pragma; aliasing a shared attr into
a local and mutating the alias dodges the rule. Lock-order call edges
resolve `self.m()` within a class, bare `f()` within a module, and
`<expr>.m()` only when `m` names exactly one lock-acquiring method across
the tree (generic names are blocklisted) — the golden file pins whatever
the model finds, so resolution drift is visible in review.
"""

from __future__ import annotations

import ast
import os
import re

from .lint import (
    Finding,
    RULE_ALIASES,
    _rule,
    _scope_all,
    iter_py_files,
    package_root,
)

# ---------------------------------------------------------------------------
# shared-state model: who runs on more than one thread
# ---------------------------------------------------------------------------

#: thread entry points (informational — the reason the classes below are
#: multi-thread): methods reachable from any two of these run concurrently
THREAD_ENTRY_POINTS = {
    "serve worker pool": "serve/service.py QueryService (ThreadPoolExecutor)",
    "router prober": "serve/router.py QueryRouter._probe_loop (daemon)",
    "stream job runners": "serve/jobs.py StreamJobs._run_job (daemon)",
    "DM/maintenance threads": "lakehouse/maintenance.py + serve DM lane",
    "memwatch sampler": "obs/memwatch.py MemorySampler (daemon)",
    "http handlers": "obs/httpserv.py ThreadingHTTPServer (daemon)",
    "lockdebug watchdog": "engine/lockdebug.py hold-budget sweeper (daemon)",
}

#: classes whose methods run on more than one of the entry points above;
#: the guarded-by rule requires every attr they mutate outside __init__ to
#: be declared (`# nds-guarded-by: <lock>` / `none`). Keyed by package-
#: relative path so the rule stays per-file (the lint substrate contract).
MULTITHREAD_CLASSES = {
    "engine/session.py": ("Session", "Catalog"),
    "engine/aotcache.py": ("AotCache", "PromotionStore"),
    "engine/spill.py": ("SpillPool",),
    "serve/service.py": ("QueryService",),
    "serve/jobs.py": ("StreamJobs",),
    "serve/router.py": ("QueryRouter", "Replica"),
    "obs/trace.py": ("Tracer",),
    "obs/metrics.py": ("MetricsRegistry", "MetricsSink"),
    "obs/flight.py": ("FlightRecorder",),
    "obs/memwatch.py": ("MemorySampler",),
    "analysis/feedback.py": ("FeedbackStore",),
    "lakehouse/leases.py": ("ReaderLeases",),
    "lakehouse/catalog.py": ("CatalogCoordinator",),
}

_GUARD_DECL_RE = re.compile(
    r"#\s*nds-guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*|none)\b"
)

#: constructors whose product is itself a synchronizer (internally safe);
#: attrs initialised from one are exempt from the declaration requirement
_SYNC_CTORS = (
    "Lock", "RLock", "Event", "Condition", "Semaphore", "BoundedSemaphore",
    "Barrier", "local", "make_lock",
)

#: container-mutator method names treated as writes to the receiver
_CONTAINER_MUTATORS = (
    "append", "appendleft", "add", "update", "pop", "popleft", "popitem",
    "clear", "extend", "remove", "discard", "insert", "setdefault",
    "move_to_end", "sort",
)


def _is_lockish(name: str) -> bool:
    return name.lower().endswith("lock")


def _is_sync_ctor(value) -> bool:
    if not isinstance(value, ast.Call):
        return False
    f = value.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None
    )
    return name in _SYNC_CTORS


def guard_decls(src: str) -> dict:
    """line number -> declared lock-attr name (or "none")."""
    out = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = _GUARD_DECL_RE.search(line)
        if m:
            out[i] = m.group(1)
    return out


def class_guard_map(tree, src: str) -> dict:
    """{class name: {attr: lock-attr | "none"}} from `# nds-guarded-by:`
    comments attached to `self.<attr> = ...` assignments (the comment sits
    on the assignment's first/last line or the line above)."""
    decls = guard_decls(src)
    out = {}
    for cls in (n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)):
        amap = {}
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            else:
                continue
            lock = (
                decls.get(node.lineno)
                or decls.get(node.lineno - 1)
                or decls.get(node.end_lineno)
            )
            if not lock:
                continue
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    amap[t.attr] = lock
        out[cls.name] = amap
    return out


def lock_spans(tree):
    """[(start, end, {identifier})] for every `with` statement whose
    context expression mentions a lock-ish name. Line-span based, like the
    PR-7 rule: everything inside the span counts as guarded by the names."""
    spans = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        names = set()
        for item in node.items:
            for x in ast.walk(item.context_expr):
                if isinstance(x, ast.Attribute):
                    names.add(x.attr)
                elif isinstance(x, ast.Name):
                    names.add(x.id)
        if any(_is_lockish(n) for n in names):
            spans.append((node.lineno, node.end_lineno, names))
    return spans


def _sync_attrs(cls) -> set:
    """Attrs of `cls` initialised from a synchronizer constructor (or from
    a `threading.Thread(...)`): internally safe, exempt from declaration."""
    out = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        if not (_is_sync_ctor(node.value) or _is_thread_ctor(node.value)):
            continue
        for t in node.targets:
            if (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                out.add(t.attr)
    return out


def iter_attr_mutations(fn):
    """Yield (receiver expr, attr, lineno, description) for attribute-state
    mutations lexically inside `fn` (nested defs included: closures run on
    the same thread entry points as their definer)."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign)
                else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Attribute):
                    yield t.value, t.attr, node.lineno, "assignment to"
                elif isinstance(t, ast.Subscript) and isinstance(
                    t.value, ast.Attribute
                ):
                    yield (
                        t.value.value, t.value.attr, node.lineno,
                        "subscript store into",
                    )
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Attribute):
                    yield t.value, t.attr, node.lineno, "delete of"
                elif isinstance(t, ast.Subscript) and isinstance(
                    t.value, ast.Attribute
                ):
                    yield (
                        t.value.value, t.value.attr, node.lineno,
                        "subscript delete from",
                    )
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _CONTAINER_MUTATORS
            and isinstance(node.func.value, ast.Attribute)
        ):
            recv = node.func.value
            yield (
                recv.value, recv.attr, node.lineno,
                f".{node.func.attr}() on",
            )


def _class_findings(tree, src, classes):
    """The declared-attr half of guarded-by, over one file's multithread
    classes."""
    gmap = class_guard_map(tree, src)
    spans = lock_spans(tree)

    def guarded(line, lock):
        return any(a <= line <= b and lock in names for a, b, names in spans)

    # attr -> (owner class, lock) for attrs declared by exactly one of the
    # file's multithread classes: lets `rep.healthy = ...` in QueryRouter
    # methods resolve to Replica's declared guard without type inference
    uniq = {}
    for cls_name in classes:
        for attr, lock in gmap.get(cls_name, {}).items():
            uniq[attr] = None if attr in uniq else (cls_name, lock)

    out = []
    for cls in (n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)):
        if cls.name not in classes:
            continue
        declared = gmap.get(cls.name, {})
        sync = _sync_attrs(cls)
        for meth in cls.body:
            if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if meth.name == "__init__":
                continue
            holds_callers_lock = meth.name.endswith("_locked")
            for recv, attr, line, desc in iter_attr_mutations(meth):
                is_self = isinstance(recv, ast.Name) and recv.id == "self"
                if is_self:
                    owner, lock = cls.name, declared.get(attr)
                    if lock is None:
                        if attr in sync:
                            continue
                        out.append((line, (
                            f"{desc} undeclared attr `self.{attr}` of "
                            f"multithread class {cls.name} outside __init__;"
                            f" declare it at its initialising assignment "
                            f"(`# nds-guarded-by: <lock>` or "
                            f"`# nds-guarded-by: none -- <reason>`) so the "
                            f"shared-state inventory stays complete"
                        )))
                        continue
                else:
                    hit = uniq.get(attr)
                    if not hit:
                        continue
                    owner, lock = hit
                if lock == "none" or holds_callers_lock:
                    continue
                if not guarded(line, lock):
                    out.append((line, (
                        f"{desc} `{attr}` (declared "
                        f"`# nds-guarded-by: {lock}` on {owner}) outside a "
                        f"`with ...{lock}:` span; every unguarded mutation "
                        f"of declared-shared state is a latent race "
                        f"(caller-holds-lock helpers use the `_locked` "
                        f"name suffix)"
                    )))
    return out


# ---------------------------------------------------------------------------
# guarded-by: the Session-cache half (PR-7's cache-lock-discipline, moved
# here verbatim when that rule was retired into this one)
# ---------------------------------------------------------------------------

#: session-level caches whose mutation must hold the session cache lock
#: (Session.cache_lock): the serve work (ROADMAP item 4) makes these
#: multi-tenant, and every unguarded mutation is a latent race today.
#: `aot_cache` (the persistent executable cache) and `promotion_store`
#: (the persisted A/B verdicts) are internally locked AND cross-process
#: atomic (tempfile+rename), but their session-level mutation sites hold
#: the same discipline so a future refactor cannot silently regress them.
_GUARDED_CACHES = (
    "exec_cache", "join_order_cache", "pallas_promotions", "plan_cache",
    "aot_cache", "promotion_store", "feedback_store",
)

#: attribute calls that mutate a cache object (ExecutableCache.lookup
#: builds + inserts; AotCache.store/vacuum write + unlink entries;
#: PromotionStore.record merges a verdict; FeedbackStore.lookup caches
#: misses, record/record_skew buffer deltas, flush commits them;
#: OrderedDict/dict mutators). Plain `.get`/`.load` reads are not
#: flagged — the LRU caches' own get() sites are lock-wrapped anyway.
_CACHE_MUTATORS = (
    "clear", "put", "pop", "popitem", "update", "setdefault", "lookup",
    "store", "vacuum", "record", "record_skew", "flush",
)


def _chain_cache_name(expr):
    """The guarded-cache attribute name reachable in an expression's
    attribute chain (session.exec_cache.map -> "exec_cache"), or None."""
    for x in ast.walk(expr):
        if isinstance(x, ast.Attribute) and x.attr in _GUARDED_CACHES:
            return x.attr
    return None


def _session_cache_findings(tree):
    spans = lock_spans(tree)

    def guarded(line):
        return any(a <= line <= b for a, b, _ in spans)

    # local-alias taint: `cache = self._session_cache()` / `c = s.plan_cache`
    # / `c = getattr(s, "plan_cache", None)` — the string-constant getattr
    # form reaches the same object with no Attribute node, so without it
    # an alias could silently dodge the rule
    def _getattr_cache_name(src):
        if (
            isinstance(src, ast.Call)
            and isinstance(src.func, ast.Name)
            and src.func.id == "getattr"
            and len(src.args) >= 2
            and isinstance(src.args[1], ast.Constant)
            and src.args[1].value in _GUARDED_CACHES
        ):
            return src.args[1].value
        return None

    tainted = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, (ast.Attribute, ast.Call)
        ):
            src = node.value
            hit = (
                _chain_cache_name(src) is not None
                or _getattr_cache_name(src) is not None
                or (
                    isinstance(src, ast.Call)
                    and isinstance(src.func, ast.Attribute)
                    and src.func.attr == "_session_cache"
                )
            )
            if hit:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)

    def receiver_is_cache(value):
        if _chain_cache_name(value) is not None:
            return True
        return isinstance(value, ast.Name) and value.id in tainted

    out = []
    for node in ast.walk(tree):
        line = msg = None
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _CACHE_MUTATORS
            and receiver_is_cache(node.func.value)
        ):
            line = node.lineno
            msg = f".{node.func.attr}() on a session cache"
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                if isinstance(t, ast.Subscript) and receiver_is_cache(t.value):
                    line = node.lineno
                    msg = "subscript store into a session cache"
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript) and receiver_is_cache(t.value):
                    line = node.lineno
                    msg = "subscript delete from a session cache"
        if line is not None and not guarded(line):
            out.append((line, (
                f"{msg} outside a held session lock "
                f"(`with session.cache_lock:`); exec/join-order/pallas/"
                f"plan caches go multi-tenant under the serve work and "
                f"every unguarded mutation is a latent race"
            )))
    return out


@_rule("guarded-by", _scope_all)
def _r_guarded_by(tree, relpath):
    out = list(_session_cache_findings(tree))
    classes = MULTITHREAD_CLASSES.get(relpath)
    if classes:
        src = getattr(tree, "_nds_lint_source", "") or ""
        out.extend(_class_findings(tree, src, classes))
    return out


# the retired rule's name keeps working in `# nds-lint: disable=` pragmas
RULE_ALIASES["cache-lock-discipline"] = "guarded-by"


# ---------------------------------------------------------------------------
# blocking-under-lock
# ---------------------------------------------------------------------------

#: module-qualified blocking calls: (receiver module name, attr)
_BLOCKING_QUALIFIED = {
    ("time", "sleep"), ("os", "replace"), ("os", "rename"),
    ("os", "makedirs"), ("os", "unlink"), ("os", "remove"),
    ("os", "listdir"), ("os", "scandir"),
    ("shutil", "rmtree"), ("shutil", "copy"), ("shutil", "copyfile"),
    ("shutil", "move"),
    ("json", "dump"), ("json", "load"),
    ("pickle", "dump"), ("pickle", "load"),
    ("subprocess", "run"), ("subprocess", "check_call"),
    ("subprocess", "check_output"), ("subprocess", "Popen"),
    ("socket", "create_connection"),
    ("jax", "jit"), ("jax", "device_put"),
}

#: bare-name blocking calls (direct or `from x import y` forms)
_BLOCKING_BARE = {"open", "fs_open", "fs_open_atomic", "urlopen", "sleep",
                  "jit"}

#: method names that block regardless of receiver (network handshake /
#: HTTP round-trip / AOT compile). `.lower(...)` is jax AOT lowering only
#: when it takes arguments (str.lower() never does); `.compile()` on `re`
#: is exempt (CPU-bound and bounded).
_BLOCKING_METHODS = {"connect", "request", "getresponse", "compile"}


def _blocking_call_desc(node):
    f = node.func
    if isinstance(f, ast.Name):
        if f.id in _BLOCKING_BARE:
            return f"{f.id}()"
        return None
    if not isinstance(f, ast.Attribute):
        return None
    recv = f.value
    if isinstance(recv, ast.Name) and (recv.id, f.attr) in _BLOCKING_QUALIFIED:
        return f"{recv.id}.{f.attr}()"
    if f.attr in _BLOCKING_METHODS:
        if isinstance(recv, ast.Name) and recv.id == "re":
            return None
        return f".{f.attr}()"
    if f.attr == "lower" and (node.args or node.keywords):
        return ".lower(...) (jax AOT lowering)"
    return None


@_rule("blocking-under-lock", _scope_all)
def _r_blocking_under_lock(tree, relpath):
    spans = lock_spans(tree)
    if not spans:
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        desc = _blocking_call_desc(node)
        if desc is None:
            continue
        if any(a <= node.lineno <= b for a, b, _ in spans):
            out.append((node.lineno, (
                f"blocking call {desc} inside a `with <lock>:` span; a "
                f"syscall or compile under a hot lock convoys every other "
                f"thread behind it — move the slow work outside the span "
                f"(or pragma with a reason when the lock exists to "
                f"serialize exactly this bounded write)"
            )))
    return out


# ---------------------------------------------------------------------------
# thread-leak
# ---------------------------------------------------------------------------


def _is_thread_ctor(node) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr == "Thread"
    return isinstance(f, ast.Name) and f.id == "Thread"


@_rule("thread-leak", _scope_all)
def _r_thread_leak(tree, relpath):
    # every identifier (variable or attribute name) that gets `.join()`ed
    # or `.daemon = True`d anywhere in the module
    joined, daemonized = set(), set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "join"
        ):
            for x in ast.walk(node.func.value):
                if isinstance(x, ast.Name):
                    joined.add(x.id)
                elif isinstance(x, ast.Attribute):
                    joined.add(x.attr)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and t.attr == "daemon"
                    and getattr(node.value, "value", None) is True
                ):
                    for x in ast.walk(t.value):
                        if isinstance(x, ast.Name):
                            daemonized.add(x.id)
                        elif isinstance(x, ast.Attribute):
                            daemonized.add(x.attr)

    # `for t in threads: t.join()` joins every handle in `threads`: map
    # loop vars back to the names they iterate (two passes cover a
    # nested `for group in batches: for t in group: t.join()`)
    loops = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            tgt = {
                x.id for x in ast.walk(node.target)
                if isinstance(x, ast.Name)
            }
            src = set()
            for x in ast.walk(node.iter):
                if isinstance(x, ast.Name):
                    src.add(x.id)
                elif isinstance(x, ast.Attribute):
                    src.add(x.attr)
            loops.append((tgt, src))
    for _ in range(2):
        for tgt, src in loops:
            if tgt & joined:
                joined |= src
            if tgt & daemonized:
                daemonized |= src

    # Thread(...) ctor -> the names its handle is bound to
    bound = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for x in ast.walk(node.value):
            if _is_thread_ctor(x):
                names = bound.setdefault(id(x), set())
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
                    elif isinstance(t, ast.Attribute):
                        names.add(t.attr)

    out = []
    for node in ast.walk(tree):
        if not _is_thread_ctor(node):
            continue
        if any(
            kw.arg == "daemon" and getattr(kw.value, "value", None) is True
            for kw in node.keywords
        ):
            continue
        names = bound.get(id(node), set())
        if names & joined or names & daemonized:
            continue
        out.append((node.lineno, (
            "non-daemon Thread with no `.join()` of its handle in this "
            "module: a leaked worker outlives shutdown and pins the "
            "process (the PR-2 throughput child-handle class). Pass "
            "`daemon=True`, join the handle on the shutdown path, or "
            "pragma with the lifecycle reason"
        )))
    return out


# ---------------------------------------------------------------------------
# lock-order: static acquisition graph, cycles, pinned canonical order
# ---------------------------------------------------------------------------

#: method names too generic for cross-object call resolution (a `.get()`
#: could be anything; resolving it to one class's method would fabricate
#: lock edges)
_GENERIC_METHODS = frozenset({
    "acquire", "release", "locked", "wait", "notify", "notify_all",
    "set", "clear", "get", "put", "items", "keys", "values", "append",
    "add", "pop", "update", "copy", "read", "write", "close", "flush",
    "start", "join", "run", "submit", "record", "send", "recv", "result",
    "cancel", "done", "shutdown", "encode", "decode", "format", "strip",
    "split", "lower", "upper", "observe", "inc",
})

#: model edges known to be artifacts of coarse name-based resolution, not
#: real nested acquisitions: (outer, inner) -> reason. Reviewed config,
#: the tree-wide analogue of a pragma.
FALSE_EDGES = {}


class LockModel:
    """The tree-wide lock model: named locks, the acquisition graph, its
    cycles, and the canonical (topological) order."""

    def __init__(self):
        self.locks = {}    # canonical name -> "relpath:line" definition
        self.edges = {}    # (outer, inner) -> sorted ["relpath:line", ...]
        self.cycles = []   # [[name, ...], ...] (each a cycle)
        self.order = []    # canonical order over all named locks


def _lock_name_for_attr_assign(cls_name, target, value):
    if not (_is_sync_ctor(value) and isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self" and _is_lockish(target.attr)):
        return None
    return f"{cls_name}.{target.attr}"


class _Fn:
    __slots__ = ("key", "spans", "calls", "direct")

    def __init__(self, key):
        self.key = key        # (relpath, class name | None, func name)
        self.spans = []       # (lock name | None, start, end)
        self.calls = []       # (kind, payload, lineno)
        self.direct = set()   # lock names acquired directly


def _walk_excluding_defs(node):
    """Yield every node in `node`'s subtree without descending into nested
    function/class definitions or lambdas: a nested def's body executes at
    call time, so its acquisitions are NOT lexically nested under the
    enclosing function's lock spans (modelling it inline would fabricate
    containment edges)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.ClassDef, ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _walk_file(relpath, tree, locks, attr_owner, module_locks, fns,
               method_index, module_fns):
    """Pass 2 over one parsed file: collect per-function spans and calls.
    `fns` etc. are the tree-wide accumulators."""

    def visit_fn(fn_node, cls_name):
        fn = _Fn((relpath, cls_name, fn_node.name))
        fns[fn.key] = fn
        if cls_name is not None:
            method_index.setdefault(fn_node.name, []).append(fn.key)
        else:
            module_fns[(relpath, fn_node.name)] = fn.key

        def resolve_lock(expr):
            # `self.X` inside the owning class
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
                and cls_name is not None
                and f"{cls_name}.{expr.attr}" in locks
            ):
                return f"{cls_name}.{expr.attr}"
            # unique attr name across every class in the tree
            if isinstance(expr, ast.Attribute):
                owners = attr_owner.get(expr.attr, ())
                if len(owners) == 1:
                    return next(iter(owners))
            # module-level lock in this module
            if isinstance(expr, ast.Name):
                name = f"{relpath}:{expr.id}"
                if name in module_locks:
                    return name
            return None

        for node in _walk_excluding_defs(fn_node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    lockish = [
                        x for x in ast.walk(item.context_expr)
                        if isinstance(x, (ast.Attribute, ast.Name))
                        and _is_lockish(
                            x.attr if isinstance(x, ast.Attribute) else x.id
                        )
                    ]
                    if not lockish:
                        continue
                    resolved = resolve_lock(lockish[0])
                    fn.spans.append((resolved, node.lineno, node.end_lineno))
                    if resolved:
                        fn.direct.add(resolved)
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Name):
                    fn.calls.append(("module", f.id, node.lineno))
                elif isinstance(f, ast.Attribute):
                    if (
                        isinstance(f.value, ast.Name)
                        and f.value.id == "self"
                        and cls_name is not None
                    ):
                        fn.calls.append(("self", f.attr, node.lineno))
                    elif f.attr not in _GENERIC_METHODS:
                        fn.calls.append(("unique", f.attr, node.lineno))

        # directly-nested defs (thread targets, callbacks): separate model
        # functions, reachable by bare name within the module; visit_fn
        # recurses for deeper nesting
        stack = list(ast.iter_child_nodes(fn_node))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visit_fn(n, cls_name)
                continue
            if isinstance(n, (ast.ClassDef, ast.Lambda)):
                continue
            stack.extend(ast.iter_child_nodes(n))

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            visit_fn(node, None)
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    visit_fn(sub, node.name)


def build_lock_model(root: str | None = None) -> LockModel:
    """Parse the tree once and build the static lock model."""
    root = root or package_root()
    nested = os.path.join(root, "nds_tpu")
    if os.path.basename(os.path.abspath(root)) != "nds_tpu" and os.path.isdir(
        nested
    ):
        root = nested

    model = LockModel()
    trees = {}
    attr_owner = {}     # lock attr -> {canonical names}
    module_locks = set()

    for path in iter_py_files(root):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            try:
                tree = ast.parse(f.read())
            except SyntaxError:
                continue
        trees[rel] = tree
        for node in tree.body:
            if isinstance(node, ast.Assign) and _is_sync_ctor(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name) and _is_lockish(t.id):
                        name = f"{rel}:{t.id}"
                        model.locks[name] = f"{rel}:{node.lineno}"
                        module_locks.add(name)
        for cls in (n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)):
            for node in ast.walk(cls):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    name = _lock_name_for_attr_assign(cls.name, t, node.value)
                    if name:
                        model.locks[name] = f"{rel}:{node.lineno}"
                        attr_owner.setdefault(t.attr, set()).add(name)

    fns, method_index, module_fns = {}, {}, {}
    for rel, tree in trees.items():
        _walk_file(rel, tree, model.locks, attr_owner, module_locks, fns,
                   method_index, module_fns)

    def resolve_call(fn, kind, payload):
        if kind == "self":
            key = (fn.key[0], fn.key[1], payload)
            return key if key in fns else None
        if kind == "module":
            return module_fns.get((fn.key[0], payload))
        owners = method_index.get(payload, ())
        if len(owners) == 1:
            return owners[0]
        return None

    # fixpoint: transitive acquire sets across the resolved call graph
    acquires = {k: set(fn.direct) for k, fn in fns.items()}
    changed = True
    while changed:
        changed = False
        for key, fn in fns.items():
            cur = acquires[key]
            before = len(cur)
            for kind, payload, _line in fn.calls:
                callee = resolve_call(fn, kind, payload)
                if callee is not None:
                    cur |= acquires[callee]
            if len(cur) != before:
                changed = True

    def add_edge(outer, inner, site):
        if outer == inner or (outer, inner) in FALSE_EDGES:
            return
        model.edges.setdefault((outer, inner), set()).add(site)

    for key, fn in fns.items():
        rel = key[0]
        for outer, start, end in fn.spans:
            if outer is None:
                continue
            for inner, s2, _e2 in fn.spans:
                if inner is not None and start < s2 <= end:
                    add_edge(outer, inner, f"{rel}:{s2}")
            for kind, payload, line in fn.calls:
                if not (start <= line <= end):
                    continue
                callee = resolve_call(fn, kind, payload)
                if callee is None:
                    continue
                for inner in acquires[callee]:
                    add_edge(outer, inner, f"{rel}:{line}")

    model.edges = {k: sorted(v) for k, v in model.edges.items()}
    model.cycles = _find_cycles(model.edges)
    model.order = _canonical_order(set(model.locks), model.edges)
    return model


def _find_cycles(edges) -> list:
    adj = {}
    for (a, b) in edges:
        adj.setdefault(a, set()).add(b)
    cycles, done = [], set()
    for start in sorted(adj):
        if start in done:
            continue
        stack, path, onpath = [(start, iter(sorted(adj.get(start, ()))))], \
            [start], {start}
        while stack:
            node, it = stack[-1]
            for nxt in it:
                if nxt in onpath:
                    cycles.append(path[path.index(nxt):] + [nxt])
                elif nxt not in done:
                    stack.append((nxt, iter(sorted(adj.get(nxt, ())))))
                    path.append(nxt)
                    onpath.add(nxt)
                    break
            else:
                done.add(node)
                onpath.discard(node)
                path.pop()
                stack.pop()
    return cycles


def _canonical_order(nodes, edges) -> list:
    """Deterministic topological order (Kahn, alphabetical tie-break) over
    every named lock; nodes stuck in a cycle are appended alphabetically
    (the cycle itself is a separate, blocking finding)."""
    nodes = set(nodes)
    for a, b in edges:
        nodes.add(a)
        nodes.add(b)
    indeg = {n: 0 for n in nodes}
    adj = {n: set() for n in nodes}
    for (a, b) in edges:
        if b not in adj[a]:
            adj[a].add(b)
            indeg[b] += 1
    ready = sorted(n for n in nodes if indeg[n] == 0)
    out = []
    while ready:
        n = ready.pop(0)
        out.append(n)
        for m in sorted(adj[n]):
            indeg[m] -= 1
            if indeg[m] == 0:
                ready.append(m)
        ready.sort()
    out.extend(sorted(nodes - set(out)))
    return out


# ---------------------------------------------------------------------------
# golden file
# ---------------------------------------------------------------------------

GOLDEN_RELPATH = os.path.join("anchors", "lock_order.golden")


def golden_path(root: str | None = None) -> str:
    root = root or package_root()
    nested = os.path.join(root, "nds_tpu")
    if os.path.basename(os.path.abspath(root)) != "nds_tpu" and os.path.isdir(
        nested
    ):
        root = nested
    repo = os.path.dirname(os.path.abspath(root))
    return os.path.join(repo, GOLDEN_RELPATH)


def format_golden(model: LockModel) -> str:
    lines = [
        "# nds-tpu canonical lock order (anchors/lock_order.golden).",
        "# Acquire locks in nondecreasing `order:` position; every",
        "# `edge: A -> B` is a static nested-acquisition site (A held",
        "# while B is taken). Drift fails the lock-order lint;",
        "# regenerate with `python -m nds_tpu.cli.lint "
        "--write-lock-order`",
        "# after reviewing the new nesting. engine.lock_debug asserts",
        "# this same order on live acquisitions.",
    ]
    lines += [f"order: {name}" for name in model.order]
    for (a, b), sites in sorted(model.edges.items()):
        lines.append(f"edge: {a} -> {b}  # {sites[0]}")
    return "\n".join(lines) + "\n"


def load_golden(path: str):
    """(order list, edge set) from a golden file, or None if unreadable."""
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return None
    order, edges = [], set()
    for line in text.splitlines():
        line = line.split("#", 1)[0].strip() if not line.startswith("#") \
            else ""
        if line.startswith("order:"):
            order.append(line[len("order:"):].strip())
        elif line.startswith("edge:"):
            a, _, b = line[len("edge:"):].partition("->")
            edges.add((a.strip(), b.strip()))
    return order, edges


def write_golden(root: str | None = None) -> str:
    model = build_lock_model(root)
    path = golden_path(root)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        f.write(format_golden(model))
    return path


def load_pinned_order(root: str | None = None) -> dict:
    """{lock name: rank} from the checked-in golden, for the runtime
    sanitizer (engine/lockdebug.py). Empty when no golden ships (installed
    package without the repo) — the sanitizer then skips order assertions
    for unranked locks."""
    got = load_golden(golden_path(root))
    if got is None:
        return {}
    order, _edges = got
    return {name: i for i, name in enumerate(order)}


def run_lock_order_lint(root: str | None = None) -> list[Finding]:
    """Tree-wide lock-order pass (run by lint.run_lint, like the unread-
    knob pass): cycles are always findings; the computed model must match
    the checked-in golden byte-for-byte in content."""
    model = build_lock_model(root)
    findings = []
    for cycle in model.cycles:
        first = model.edges.get((cycle[0], cycle[1]), ["?:0"])[0]
        path, _, line = first.partition(":")
        findings.append(Finding(path or GOLDEN_RELPATH, int(line or 0),
                                "lock-order", (
            f"lock-acquisition cycle {' -> '.join(cycle)}: two threads "
            f"taking these locks in opposite orders deadlock; break the "
            f"cycle (release before re-acquiring, or split the lock) — "
            f"a genuinely-false call-graph edge goes in "
            f"analysis/concurrency.py FALSE_EDGES with a reason"
        )))
    gpath = golden_path(root)
    if not os.path.isdir(os.path.dirname(gpath)):
        return findings  # installed package without the repo: nothing to sync
    got = load_golden(gpath)
    if got is None:
        findings.append(Finding(GOLDEN_RELPATH, 0, "lock-order", (
            "lock-order golden file missing; generate and check it in: "
            "python -m nds_tpu.cli.lint --write-lock-order"
        )))
        return findings
    order, edges = got
    new_edges = set(model.edges) - edges
    gone_edges = edges - set(model.edges)
    if order != model.order or new_edges or gone_edges:
        detail = []
        if new_edges:
            detail.append("new edges: " + ", ".join(
                f"{a} -> {b}" for a, b in sorted(new_edges)))
        if gone_edges:
            detail.append("removed edges: " + ", ".join(
                f"{a} -> {b}" for a, b in sorted(gone_edges)))
        if order != model.order:
            detail.append("canonical order changed")
        findings.append(Finding(GOLDEN_RELPATH, 0, "lock-order", (
            "lock model drifted from the checked-in golden "
            f"({'; '.join(detail)}); review the new nesting, then "
            "regenerate: python -m nds_tpu.cli.lint --write-lock-order"
        )))
    return findings


# ---------------------------------------------------------------------------
# shared-state report (the discovery half of the model, as a CLI)
# ---------------------------------------------------------------------------


def shared_state_report(root: str | None = None) -> str:
    """Human-readable inventory: every multithread class's declared attrs
    with their guards, plus the named-lock table and acquisition edges."""
    root = root or package_root()
    nested = os.path.join(root, "nds_tpu")
    if os.path.basename(os.path.abspath(root)) != "nds_tpu" and os.path.isdir(
        nested
    ):
        root = nested
    lines = ["shared-state inventory (guarded-by declarations)", ""]
    for rel, classes in sorted(MULTITHREAD_CLASSES.items()):
        path = os.path.join(root, *rel.split("/"))
        try:
            with open(path, encoding="utf-8") as f:
                src = f.read()
        except OSError:
            continue
        gmap = class_guard_map(ast.parse(src), src)
        for cls in classes:
            amap = gmap.get(cls, {})
            lines.append(f"  {rel} {cls}: {len(amap)} declared attr(s)")
            for attr, lock in sorted(amap.items()):
                lines.append(f"    {attr:28s} guarded-by {lock}")
    model = build_lock_model(root)
    lines += ["", f"named locks ({len(model.locks)}):"]
    for name, site in sorted(model.locks.items()):
        lines.append(f"  {name:40s} {site}")
    lines += ["", f"acquisition edges ({len(model.edges)}):"]
    for (a, b), sites in sorted(model.edges.items()):
        lines.append(f"  {a} -> {b}  ({sites[0]})")
    if model.cycles:
        lines += ["", "CYCLES:"] + [
            "  " + " -> ".join(c) for c in model.cycles
        ]
    return "\n".join(lines)


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="nds-tpu concurrency model (shared-state + lock-order)"
    )
    ap.add_argument("root", nargs="?", default=None)
    ap.add_argument("--report", action="store_true",
                    help="print the shared-state / lock-model inventory")
    ap.add_argument("--write-lock-order", action="store_true",
                    help="regenerate anchors/lock_order.golden")
    args = ap.parse_args(argv)
    if args.write_lock_order:
        print(f"wrote {write_golden(args.root)}")
        return 0
    print(shared_state_report(args.root))
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
