"""Engine lint: AST rules codifying the repo's known bug classes.

Every rule below encodes a bug this codebase actually shipped (and fixed):

  mutable-module-global   PR 3's TRACE_NODES: a module-global dict in the
                          executor corrupted spans across concurrent
                          throughput streams. Per-stream state must live on
                          Session/Executor instances. Scope: engine/, ops/.
  perf-counter            PR 3 again: durations computed from time.time()
                          jump with wall-clock adjustments (NTP steps
                          mid-benchmark corrupt Tpower). Durations must use
                          time.perf_counter(); epoch stamps are fine.
  atomic-write            PR 2: a crash mid-`open(path, "w")` leaves a torn
                          report/state/summary a later reader chokes on.
                          Harness artifacts must go through
                          io.fs.fs_open_atomic (tmp + rename).
                          Scope: top-level harness modules (nds_tpu/*.py).
  host-sync-in-fuse       fuse.py traced regions run under jax.jit: a host
                          sync (np.asarray, .block_until_ready(), int() on
                          a device value) either breaks the trace or forces
                          a device round-trip per call. Scope: the traced
                          FusedPipeline bodies in engine/fuse.py.
  local-import            PR 3: a function-local `import` in the op-span
                          hot path paid a sys.modules lookup per executed
                          plan node. Hot-path modules import at module
                          level; genuinely-cold lazy imports carry a
                          pragma. Scope: engine/exec.py, engine/expr.py,
                          engine/fuse.py, ops/kernels.py.
  trace-event-schema      every `tracer.emit("<kind>", ...)` call's kind
                          must exist in obs/trace.py:EVENT_SCHEMA and pass
                          the kind's required fields (or forward **fields),
                          so schema drift breaks lint instead of the
                          tolerant trace reader. Context fields
                          (trace_id — obs/trace.py:CONTEXT_FIELDS) are
                          stamped centrally by Tracer.emit: a call site
                          passing one explicitly must declare it in the
                          kind's EVENT_SCHEMA entry. Scope: everywhere. In
                          obs/metrics.py the same rule also checks the
                          LIVE-metric taxonomy: every family in
                          METRIC_KINDS must map to a real EVENT_SCHEMA
                          kind AND embed that kind in its name, and every
                          literal metric name passed to a registry
                          mutator must be a registered family — live
                          metric names cannot drift from the event
                          taxonomy (the PR-8 /metrics contract).
  undocumented-conf-knob  carry-forward hygiene: every `engine.*` conf key
                          the code reads must appear in the README knob
                          tables or a properties/ template — an invisible
                          knob can't be tuned, and its emitted engineConf
                          entry can't be interpreted. Scope: everywhere
                          (skipped when no README is present, e.g. an
                          installed package without the repo).
  unread-conf-knob        the inverse (tree-wide, run_unread_knob_lint):
                          every documented `engine.*` key must be
                          mentioned somewhere in code, so dead knob rows
                          can't accumulate in the docs. Same README-on-
                          disk skip as above.
  debug-route-seam        the PR-12 single-listener invariant: /debug
                          routes register on the ONE process-wide
                          listener (obs/httpserv.py) or dispatch through
                          its attach_app seam, and nothing else may
                          construct an HTTP server. Scope: everywhere
                          except obs/httpserv.py.
  manifest-write-seam     the PR-15 single-committer invariant (the
                          debug-route-seam pattern, applied to storage):
                          lakehouse manifest/commit-log writes happen
                          ONLY inside the committer/catalog API
                          (lakehouse/table.py `_commit` +
                          lakehouse/catalog.py) — a `put_if_absent` call
                          or a `_manifests` path built anywhere else is
                          a second committer that bypasses OCC
                          arbitration, the fence check, and the
                          coordinator's WAL. Scope: everywhere except
                          the two committer modules.
  guarded-by              the concurrency contract (analysis/
                          concurrency.py): every mutation of declared-
                          shared state (`# nds-guarded-by: <lock>` at the
                          initialising assignment) must sit inside a
                          `with <lock>:` span, and every attr a
                          MULTITHREAD_CLASSES class mutates outside
                          __init__ must be declared. Subsumes PR-7's
                          `cache-lock-discipline` (the Session-cache half
                          is its old body; the old name still works in
                          pragmas via RULE_ALIASES). Scope: everywhere.
  blocking-under-lock     no fs/network/jit-compile/sleep call inside a
                          `with <lock>:` span — a syscall under a hot
                          lock convoys every thread behind it. Scope:
                          everywhere (analysis/concurrency.py).
  lock-order              tree-wide (run_lock_order_lint): the static
                          lock-acquisition graph (nested `with` spans +
                          call edges) must stay acyclic and match
                          anchors/lock_order.golden; regenerate with
                          `--write-lock-order`. engine.lock_debug asserts
                          the same pinned order at runtime.
  thread-leak             every `threading.Thread(` must be daemonized
                          or have its handle `.join()`ed in the same
                          module (the PR-2 child-handle class, for
                          threads). Scope: everywhere (analysis/
                          concurrency.py).
  scan-path-listing       the PR-16 zone-map invariant: the scan path
                          discovers table files ONLY through the pinned
                          manifest (TableSnapshot.files()/file_stats()),
                          never by glob/listdir of data directories — a
                          raw listing sees uncommitted staged files,
                          vacuum-doomed debris, and files from other
                          snapshot versions, and silently bypasses
                          zone-map pruning. Scope: engine/session.py,
                          engine/exec.py (the modules that resolve a
                          Scan node to files).

Pragma: append `# nds-lint: disable=<rule>[,<rule>...]` (with a
justification!) on the offending line or the line directly above to
acknowledge a known-sound exception. `disable=all` silences every rule for
that line.

Run: `./nds-tpu-submit lint` (or `python -m nds_tpu.cli.lint [path]`);
exits non-zero on any finding. Wired into ci/tier1-check.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from dataclasses import dataclass

#: rule registry: name -> (scope predicate over package-relative path,
#: checker). Populated at module bottom (and by analysis/concurrency.py,
#: imported at the bottom of this module so its rules always register).
RULES = {}

#: retired rule name -> successor: pragmas written against the old name
#: keep silencing the rule that absorbed it (`cache-lock-discipline` ->
#: `guarded-by`, registered by analysis/concurrency.py)
RULE_ALIASES = {}

_PRAGMA_RE = re.compile(r"#\s*nds-lint:\s*disable=([A-Za-z0-9_,\- ]+)")

_MUTABLE_CTORS = ("dict", "list", "set", "defaultdict", "OrderedDict",
                  "Counter", "deque")

#: the FusedPipeline methods that execute under jax tracing (fuse.py)
_TRACED_FNS = ("_run_full", "_run_kept", "_flat_inputs")

#: hot-path modules where function-local imports are banned
_HOT_MODULES = (
    "engine/exec.py", "engine/expr.py", "engine/fuse.py", "ops/kernels.py",
)


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def _rule(name, scope):
    def deco(fn):
        RULES[name] = (scope, fn)
        return fn
    return deco


def _scope_all(relpath):
    return True


def _scope_engine_ops(relpath):
    return relpath.startswith(("engine/", "ops/"))


def _scope_harness(relpath):
    # top-level harness modules: report/state/summary artifacts are written
    # here (engine/io/datagen layers have their own seams)
    return "/" not in relpath


def _scope_fuse(relpath):
    return relpath == "engine/fuse.py"


def _scope_hot(relpath):
    return relpath in _HOT_MODULES


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


@_rule("mutable-module-global", _scope_engine_ops)
def _r_mutable_module_global(tree, relpath):
    out = []
    for node in tree.body:
        if isinstance(node, ast.Assign):
            value, line = node.value, node.lineno
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            value, line = node.value, node.lineno
        else:
            continue
        if _is_mutable_ctor(value):
            out.append((line, (
                "module-global mutable container; per-stream state must "
                "live on Session/Executor instances (the TRACE_NODES "
                "cross-stream corruption class)"
            )))
    for node in ast.walk(tree):
        if isinstance(node, ast.Global):
            out.append((node.lineno, (
                f"function rebinds module global(s) "
                f"{', '.join(node.names)}; shared mutable module state is "
                f"unsafe across concurrent streams"
            )))
    return out


def _is_mutable_ctor(value):
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call) and isinstance(value.func, ast.Name):
        return value.func.id in _MUTABLE_CTORS
    return False


@_rule("perf-counter", _scope_all)
def _r_perf_counter(tree, relpath):
    # names `time` resolves to in this file (import time / from time import
    # time as x)
    bare_time_names = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for a in node.names:
                if a.name == "time":
                    bare_time_names.add(a.asname or "time")

    def is_epoch_call(n):
        if not isinstance(n, ast.Call):
            return False
        f = n.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "time"
            and isinstance(f.value, ast.Name)
            and f.value.id == "time"
        ):
            return True
        return isinstance(f, ast.Name) and f.id in bare_time_names

    tainted = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
            is_epoch_call(x) for x in ast.walk(node.value)
        ):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    tainted.add(t.id)
    out = []
    seen_lines = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub):
            for side in (node.left, node.right):
                hit = is_epoch_call(side) or (
                    isinstance(side, ast.Name) and side.id in tainted
                )
                if hit and node.lineno not in seen_lines:
                    seen_lines.add(node.lineno)
                    out.append((node.lineno, (
                        "duration computed from time.time(); wall-clock "
                        "steps (NTP) corrupt elapsed figures — use "
                        "time.perf_counter() for durations (epoch stamps "
                        "themselves are fine)"
                    )))
    return out


@_rule("atomic-write", _scope_harness)
def _r_atomic_write(tree, relpath):
    out = []
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "open"
        ):
            continue
        mode = None
        if len(node.args) >= 2:
            mode = node.args[1]
        for kw in node.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if (
            isinstance(mode, ast.Constant)
            and isinstance(mode.value, str)
            and "w" in mode.value
        ):
            out.append((node.lineno, (
                "bare open(..., 'w') on a harness artifact; a crash "
                "mid-write leaves a torn file — use io.fs.fs_open_atomic "
                "(tmp + rename) for report/state/summary paths"
            )))
    return out


@_rule("host-sync-in-fuse", _scope_fuse)
def _r_host_sync_in_fuse(tree, relpath):
    out = []
    seen = set()  # a _TRACED_FNS name nested in another would double-walk
    for fn in ast.walk(tree):
        if not (
            isinstance(fn, ast.FunctionDef) and fn.name in _TRACED_FNS
        ):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            seen.add(id(node))
            f = node.func
            msg = None
            if isinstance(f, ast.Attribute):
                if f.attr in ("block_until_ready", "item"):
                    msg = f".{f.attr}() forces a host sync"
                elif (
                    f.attr in ("asarray", "array")
                    and isinstance(f.value, ast.Name)
                    and f.value.id in ("np", "numpy")
                ):
                    msg = f"np.{f.attr}() pulls a device value to host"
                elif (
                    f.attr == "device_get"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "jax"
                ):
                    msg = "jax.device_get() forces a host sync"
            elif isinstance(f, ast.Name) and f.id in ("int", "float", "bool"):
                # int() on a .shape element is static metadata, not a sync
                shapes_only = all(
                    any(
                        isinstance(x, ast.Attribute) and x.attr == "shape"
                        for x in ast.walk(a)
                    )
                    for a in node.args
                )
                if not shapes_only:
                    msg = (
                        f"{f.id}() on a traced value forces a host sync "
                        f"(or breaks the trace)"
                    )
            if msg is not None:
                out.append((node.lineno, (
                    f"{msg} inside a jitted FusedPipeline region "
                    f"({fn.name}); host work belongs at build/call "
                    f"boundaries"
                )))
    return out


@_rule("local-import", _scope_hot)
def _r_local_import(tree, relpath):
    # dedupe by node id: ast.walk yields nested functions from the outer
    # function's walk too, which would double-report their imports
    out = []
    seen = set()
    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(fn):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                if id(node) in seen:
                    continue
                seen.add(id(node))
                out.append((node.lineno, (
                    "function-local import in a hot-path module pays a "
                    "sys.modules lookup per call; import at module level "
                    "(pragma genuinely-cold lazy imports with a reason)"
                )))
    return out


@_rule("trace-event-schema", _scope_all)
def _r_trace_event_schema(tree, relpath):
    from ..obs.trace import CONTEXT_FIELDS, EVENT_SCHEMA

    out = []
    for kind, kwargs, has_star, line in iter_emit_calls(tree):
        if kind not in EVENT_SCHEMA:
            out.append((line, (
                f"trace event kind {kind!r} is not in "
                f"obs/trace.py:EVENT_SCHEMA; register it (with its "
                f"required fields) before emitting"
            )))
            continue
        # `query` is auto-bound from faults.scope by Tracer.emit
        missing = set(EVENT_SCHEMA[kind]) - set(kwargs) - {"query"}
        if missing and not has_star:
            out.append((line, (
                f"trace event {kind!r} missing required field(s) "
                f"{sorted(missing)} (EVENT_SCHEMA contract)"
            )))
        # trace-context discipline: trace_id (and friends) are stamped
        # centrally by Tracer.emit from the tracer's TraceContext; an
        # emission site passing one ad hoc either aliases another run's
        # trace or silently shadows the stamp — a kind that legitimately
        # needs an explicit value must DECLARE the field in EVENT_SCHEMA
        for ctx_field in CONTEXT_FIELDS:
            if ctx_field in kwargs and ctx_field not in EVENT_SCHEMA[kind]:
                out.append((line, (
                    f"trace event {kind!r} passes {ctx_field!r} "
                    f"explicitly but does not declare it in EVENT_SCHEMA; "
                    f"context fields are stamped centrally by Tracer.emit "
                    f"— declare the field or drop the kwarg"
                )))
    if relpath == "obs/metrics.py":
        out.extend(_metric_name_findings(tree, EVENT_SCHEMA))
    return out


#: modules allowed to construct an HTTP listener / own /debug routes: the
#: ONE process-wide endpoint (PR-12 invariant: no second listener)
_LISTENER_MODULE = "obs/httpserv.py"

_HTTP_SERVER_CTORS = ("HTTPServer", "ThreadingHTTPServer", "TCPServer")


@_rule("debug-route-seam", _scope_all)
def _r_debug_route_seam(tree, relpath):
    """The PR-12 single-listener invariant, mechanized: /debug routes
    register on the shared listener (obs/httpserv.py) — or dispatch
    through its `attach_app` seam — and nothing outside it may construct
    its own HTTP server. A second listener forks the diagnosis surface
    (two ports, one of them unmonitored) and breaks the serve-mode
    contract that ONE port carries the whole surface."""
    # the listener itself, and this rule's own definition (its prefix
    # literal + finding text), are the two legitimate homes of the string
    if relpath in (_LISTENER_MODULE, "analysis/lint.py"):
        return []
    out = []
    # collect docstring constants (module/class/function first-statement
    # strings): route tables documented in prose must not trip the rule
    doc_ids = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr) and isinstance(
                body[0].value, ast.Constant
            ) and isinstance(body[0].value.value, str):
                doc_ids.add(id(body[0].value))
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and node.value.startswith("/debug")
            and id(node) not in doc_ids
        ):
            out.append((node.lineno, (
                f"/debug route {node.value!r} referenced outside "
                f"{_LISTENER_MODULE}; debug routes register on the one "
                f"process-wide listener (or dispatch via attach_app) — "
                f"no second listener"
            )))
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, (ast.Name, ast.Attribute))
            and (
                node.func.id if isinstance(node.func, ast.Name)
                else node.func.attr
            ) in _HTTP_SERVER_CTORS
        ):
            out.append((node.lineno, (
                f"HTTP server constructed outside {_LISTENER_MODULE}; "
                f"the process has ONE listener (obs/httpserv.py) — "
                f"attach new surfaces through attach_app"
            )))
    return out


#: the only modules allowed to publish lakehouse manifests / touch the
#: commit log: the table committer and the fleet catalog it routes through
_COMMITTER_MODULES = ("lakehouse/table.py", "lakehouse/catalog.py")


def _collect_docstring_ids(tree):
    """ids of module/class/function docstring Constant nodes (shared by
    the seam rules: prose route tables / path examples must not trip)."""
    doc_ids = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr) and isinstance(
                body[0].value, ast.Constant
            ) and isinstance(body[0].value.value, str):
                doc_ids.add(id(body[0].value))
    return doc_ids


@_rule("manifest-write-seam", _scope_all)
def _r_manifest_write_seam(tree, relpath):
    """The single-committer invariant, mechanized: every manifest publish
    routes through `LakehouseTable._commit` (which itself routes through
    lakehouse/catalog.py when a fleet catalog is configured). A
    `put_if_absent` call or a `_manifests` path literal anywhere else is
    a second committer — it would bypass OCC arbitration, the epoch
    fence, and the coordinator's WAL, exactly the storage-corruption
    class the catalog service exists to close."""
    if relpath in _COMMITTER_MODULES or relpath == "analysis/lint.py":
        return []
    out = []
    doc_ids = _collect_docstring_ids(tree)
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and "_manifests" in node.value
            and id(node) not in doc_ids
        ):
            out.append((node.lineno, (
                f"manifest path {node.value!r} built outside the committer "
                f"modules ({', '.join(_COMMITTER_MODULES)}); manifest/"
                f"commit-log writes go through LakehouseTable._commit and "
                f"the catalog API — no second committer"
            )))
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, (ast.Name, ast.Attribute))
            and (
                node.func.id if isinstance(node.func, ast.Name)
                else node.func.attr
            ) == "put_if_absent"
        ):
            out.append((node.lineno, (
                f"put_if_absent() called outside the committer modules "
                f"({', '.join(_COMMITTER_MODULES)}); the create-exclusive "
                f"publish primitive belongs to the commit seam — route "
                f"writes through LakehouseTable._commit / the catalog"
            )))
    return out


#: MetricsRegistry mutators whose first argument is a metric family name
_METRIC_MUTATORS = ("inc", "set_gauge", "max_gauge", "observe")


def metric_kinds_literal(tree) -> dict:
    """{family name: (source kind, lineno)} from the METRIC_KINDS dict
    literal in obs/metrics.py's AST (empty when absent). Shared with the
    golden-sync test that keeps the live-metric taxonomy anchored to
    EVENT_SCHEMA."""
    families = {}
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "METRIC_KINDS"
                for t in node.targets
            )
            and isinstance(node.value, ast.Dict)
        ):
            continue
        for k, v in zip(node.value.keys, node.value.values):
            if (
                isinstance(k, ast.Constant) and isinstance(k.value, str)
                and isinstance(v, ast.Constant) and isinstance(v.value, str)
            ):
                families[k.value] = (v.value, k.lineno)
    return families


def _metric_name_findings(tree, event_schema):
    """obs/metrics.py half of the trace-event-schema rule: the live-metric
    taxonomy must DERIVE from the event taxonomy. Every METRIC_KINDS entry
    maps a family to a real EVENT_SCHEMA kind and embeds that kind in the
    family name; every literal family name a registry mutator is called
    with must be registered — a free-floating metric name cannot appear
    on /metrics without first anchoring to an event kind."""
    out = []
    families = metric_kinds_literal(tree)
    for name, (kind, line) in families.items():
        if kind not in event_schema:
            out.append((line, (
                f"metric family {name!r} derives from {kind!r}, which is "
                f"not an obs/trace.py:EVENT_SCHEMA kind — live metrics "
                f"must anchor to the event taxonomy"
            )))
        elif kind not in name:
            out.append((line, (
                f"metric family {name!r} does not embed its source event "
                f"kind {kind!r} in its name — free-floating metric names "
                f"drift from the event taxonomy"
            )))
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _METRIC_MUTATORS
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            continue
        name = node.args[0].value
        if name not in families:
            out.append((node.lineno, (
                f"metric name {name!r} is used in a registry mutator but "
                f"not registered in METRIC_KINDS (family -> event kind); "
                f"register it before exposing it"
            )))
    return out


_CONF_DOC_CACHE = None


def documented_conf_keys(repo: str | None = None):
    """`engine.*` keys named in the repo's README (knob tables, prose) or
    any properties/ template — the set the code's reads must stay inside.
    None when the repo docs aren't present (installed package): the rule
    then skips rather than flagging everything. The default (installed)
    repo's key set is cached; an explicit `repo` re-reads (tests)."""
    global _CONF_DOC_CACHE
    if repo is not None:
        return _read_conf_doc_keys(repo)
    if _CONF_DOC_CACHE is None:
        _CONF_DOC_CACHE = (
            _read_conf_doc_keys(os.path.dirname(package_root())),
        )
    return _CONF_DOC_CACHE[0]


def _read_conf_doc_keys(repo: str):
    readme = os.path.join(repo, "README.md")
    if not os.path.isfile(readme):
        return None
    keys = set()
    with open(readme, encoding="utf-8") as f:
        keys.update(re.findall(r"engine\.[a-z0-9_]+", f.read()))
    propdir = os.path.join(repo, "properties")
    if os.path.isdir(propdir):
        for name in os.listdir(propdir):
            if not name.endswith(".properties"):
                continue
            with open(os.path.join(propdir, name), encoding="utf-8") as f:
                keys.update(re.findall(r"engine\.[a-z0-9_]+", f.read()))
    return keys


def iter_conf_keys(tree):
    """Yield (key, lineno) for every `engine.*` conf-key literal read or
    written in the AST: `<obj>.get("engine.x"[, default])`,
    `<obj>.setdefault("engine.x", ...)`, and `<obj>["engine.x"]`."""
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("get", "setdefault")
            and node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
            and node.args[0].value.startswith("engine.")
        ):
            yield node.args[0].value, node.lineno
        elif (
            isinstance(node, ast.Subscript)
            and isinstance(node.slice, ast.Constant)
            and isinstance(node.slice.value, str)
            and node.slice.value.startswith("engine.")
        ):
            yield node.slice.value, node.lineno


@_rule("undocumented-conf-knob", _scope_all)
def _r_undocumented_conf_knob(tree, relpath):
    documented = documented_conf_keys()
    if documented is None:
        return []
    out = []
    for key, line in iter_conf_keys(tree):
        if key not in documented:
            out.append((line, (
                f"conf knob {key!r} is read by code but absent from the "
                f"README knob tables / properties templates — document it "
                f"(with its default) or drop the dead knob"
            )))
    return out


#: directory-listing calls the scan path must not make: file discovery
#: goes through the pinned manifest (TableSnapshot.files()/dataset()),
#: never the filesystem — a raw listing sees uncommitted staged files,
#: vacuum-doomed debris, and files from OTHER snapshot versions
_LISTING_ATTRS = ("glob", "iglob", "listdir", "scandir", "walk")


@_rule("scan-path-listing", lambda rp: rp in ("engine/session.py",
                                              "engine/exec.py"))
def _r_scan_path_listing(tree, relpath):
    out = []
    from_imports = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module in ("glob", "os"):
            for a in node.names:
                if a.name in _LISTING_ATTRS:
                    from_imports.add(a.asname or a.name)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        hit = (
            isinstance(f, ast.Attribute) and f.attr in _LISTING_ATTRS
            and isinstance(f.value, ast.Name) and f.value.id in ("glob", "os")
        ) or (isinstance(f, ast.Name) and f.id in from_imports)
        if hit:
            out.append((node.lineno, (
                "filesystem listing on the scan path; table-file discovery "
                "must go through the pinned manifest / zone-map API "
                "(TableSnapshot.files()/file_stats()) — a raw glob/listdir "
                "sees uncommitted staged files, vacuum debris, and other "
                "snapshot versions' files"
            )))
    return out


def run_unread_knob_lint(root: str | None = None,
                         mentioned: set | None = None) -> list[Finding]:
    """Inverse of `undocumented-conf-knob` (tree-wide, so not a per-file
    rule): every `engine.*` key named in the README knob tables or a
    properties/ template must be MENTIONED somewhere in the code (read,
    written, or emitted) — dead knobs in the docs otherwise accumulate and
    mis-teach operators. Findings point at README.md / the template.
    `mentioned`: pre-collected engine.* mention set (run_lint passes the
    one it gathered while reading the tree for the AST rules); None =
    standalone invocation, read the tree here."""
    root = root or package_root()
    nested = os.path.join(root, "nds_tpu")
    if os.path.basename(os.path.abspath(root)) != "nds_tpu" and os.path.isdir(
        nested
    ):
        root = nested
    documented = documented_conf_keys(os.path.dirname(os.path.abspath(root)))
    if documented is None:
        return []
    if mentioned is None:
        mentioned = set()
        for path in iter_py_files(root):
            with open(path, encoding="utf-8") as f:
                mentioned.update(
                    re.findall(r"engine\.[a-z0-9_]+", f.read())
                )
    dead = sorted(documented - mentioned)
    if not dead:
        return []
    repo = os.path.dirname(root)
    findings = []
    sources = [("README.md", os.path.join(repo, "README.md"))]
    propdir = os.path.join(repo, "properties")
    if os.path.isdir(propdir):
        sources += [
            (f"properties/{n}", os.path.join(propdir, n))
            for n in sorted(os.listdir(propdir))
            if n.endswith(".properties")
        ]
    for key in dead:
        for rel, path in sources:
            try:
                with open(path, encoding="utf-8") as f:
                    lines = f.read().splitlines()
            except OSError:
                continue
            for i, line in enumerate(lines, start=1):
                if key in line:
                    findings.append(Finding(rel, i, "unread-conf-knob", (
                        f"conf knob {key!r} is documented here but no code "
                        f"reads it — drop the dead knob row or wire the "
                        f"knob back up"
                    )))
                    break
            else:
                continue
            break
    return findings


def iter_emit_calls(tree):
    """Yield (kind, kwarg names, has_star_kwargs, lineno) for every
    `<obj>.emit("<literal>", ...)` call in the AST. Shared with the
    golden-sync test that keeps emitted kinds and EVENT_SCHEMA equal."""
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "emit"
        ):
            continue
        if not (
            node.args
            and isinstance(node.args[0], ast.Constant)
            and isinstance(node.args[0].value, str)
        ):
            continue
        kwargs = [kw.arg for kw in node.keywords if kw.arg is not None]
        has_star = any(kw.arg is None for kw in node.keywords)
        yield node.args[0].value, kwargs, has_star, node.lineno


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def _pragmas(src: str) -> dict:
    """line number -> set of disabled rule names (or {'all'})."""
    out = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = _PRAGMA_RE.search(line)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def lint_source(src: str, relpath: str) -> list[Finding]:
    """Lint one file's source under its package-relative path (the path
    selects which rules apply)."""
    tree = ast.parse(src)
    # comment-level annotations (`# nds-guarded-by:`) are invisible to the
    # AST; rules that need them read the source off the tree
    tree._nds_lint_source = src
    pragmas = _pragmas(src)
    findings = []
    for name, (scope, check) in RULES.items():
        if not scope(relpath):
            continue
        for line, message in check(tree, relpath):
            disabled = pragmas.get(line, set()) | pragmas.get(line - 1, set())
            disabled |= {RULE_ALIASES.get(r, r) for r in disabled}
            if name in disabled or "all" in disabled:
                continue
            findings.append(Finding(relpath, line, name, message))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def package_root() -> str:
    """The nds_tpu package directory this lint module ships inside."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def iter_py_files(root: str):
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [
            d for d in dirnames if d not in ("__pycache__", "native")
        ]
        for f in sorted(filenames):
            if f.endswith(".py"):
                yield os.path.join(dirpath, f)


def run_lint(root: str | None = None) -> list[Finding]:
    root = root or package_root()
    # path-scoped rules key off package-relative paths ("engine/exec.py"):
    # linting from the REPO root would silently skip every scoped rule and
    # mis-scope the harness rule onto repo-level scripts — rebase onto the
    # contained nds_tpu package when the caller passed its parent
    nested = os.path.join(root, "nds_tpu")
    if os.path.basename(os.path.abspath(root)) != "nds_tpu" and os.path.isdir(
        nested
    ):
        root = nested
    findings = []
    mentioned = set()
    for path in iter_py_files(root):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            src = f.read()
        mentioned.update(re.findall(r"engine\.[a-z0-9_]+", src))
        findings.extend(lint_source(src, rel))
    # tree-wide inverse knob pass (documented-but-unread keys): per-file
    # rules cannot see the whole read set, so it runs once here, reusing
    # the mention set gathered above instead of re-reading the tree
    findings.extend(run_unread_knob_lint(root, mentioned=mentioned))
    # tree-wide lock-order pass (cycles + golden sync): the acquisition
    # graph spans call edges between files, so it cannot be a per-file rule
    from . import concurrency

    findings.extend(concurrency.run_lock_order_lint(root))
    return findings


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="nds-tpu engine lint (AST rules over nds_tpu/)"
    )
    ap.add_argument(
        "root", nargs="?", default=None,
        help="package root to lint (default: the installed nds_tpu dir)",
    )
    ap.add_argument(
        "--list-rules", action="store_true", help="print the rule table"
    )
    ap.add_argument(
        "--write-lock-order", action="store_true",
        help="regenerate anchors/lock_order.golden from the current tree "
             "(review the diff: every new edge is a new nested acquisition)",
    )
    args = ap.parse_args(argv)
    if args.list_rules:
        for name in sorted(RULES):
            print(name)
        return 0
    if args.write_lock_order:
        from . import concurrency

        print(f"lint: wrote {concurrency.write_golden(args.root)}")
        return 0
    findings = run_lint(args.root)
    for f in findings:
        print(f)
    n = len(findings)
    print(f"lint: {n} finding(s)" if n else "lint: clean")
    return 1 if findings else 0


# registers the concurrency rules (guarded-by / blocking-under-lock /
# thread-leak) into RULES and the cache-lock-discipline alias — imported
# last so the substrate above is fully defined either import order
from . import concurrency as _concurrency  # noqa: E402,F401

if __name__ == "__main__":
    sys.exit(main())
