"""Static plan budgeter: compile-time cardinality and peak-HBM analysis.

The reference harness budgets executor memory *statically in configuration*
(reference: nds/power_run_gpu.template:29-36 pins executor/pinned-pool sizes
before a single task runs) and lets Spark's planner pick the spill/exchange
shape up front. This engine used to discover memory misfits at runtime, one
failed dispatch at a time, via the report ladder's OOM rungs. This module is
the static half of that contract: it walks a bound + rewritten plan and
derives, per node,

  * a cardinality bound (catalog row counts, filter-selectivity heuristics,
    join key-uniqueness from TABLE_PRIMARY_KEYS, blocked-union annotations),
  * a peak-HBM byte model mirroring what exec.py actually materializes
    (power-of-two capacity buckets, gather/pair-table widths, sort key
    words, segment-reduce outputs, union concats, per-window slices),

and folds them into one **verdict** the planner acts on:

  direct            the whole plan's modeled peak fits the budget
  blocked           over budget, but the overage windows away through the
                    plan's blocked-union aggregates: execute those in
                    statically sized row windows (`window_rows` is chosen
                    here, and exec._blocked_union_ctx consumes it ahead of
                    the runtime derivation)
  over              over budget with no (sufficient) windowing seam but
                    under the reject line: admitted, with the prediction
                    stored so the report ladder's first device-OOM rung
                    applies the static recommendation instead of blind
                    halving
  reject            beyond the reject line even windowed: admission control
                    refuses the statement at plan time (PlanBudgetError,
                    classified `planner` -> the report ladder fails fast)
  unknown           some base-table cardinality is unavailable (schema-only
                    entry with no scale factor, csv/lakehouse path): the
                    verdict carries no enforcement

The model is an *upper bound with a documented slack*: capacity bucketing
rounds every row count up to a power of two and child results are assumed
live while a parent executes, so the estimate over-approximates the real
working set; selectivity heuristics may undershoot pathological filters,
which the calibration test bounds at `CALIBRATION_SLACK` (see
tests/test_budget.py). The CI gate (tools/plan_verify_corpus.py --budget)
holds the two load-bearing calibration points: every template admitted at
SF1 (known to fit 103/103), and the round-5 SF10 device-OOM set flagged
over-budget.

Knobs: conf `engine.plan_budget` / env NDS_PLAN_BUDGET = off | warn | on
(default on; warn computes + traces but never rejects), conf
`engine.plan_budget_bytes` / env NDS_PLAN_BUDGET_BYTES (modeled working-set
budget, default DEFAULT_BUDGET_BYTES), conf `engine.plan_budget_sf`
(schema-only sessions: synthesize base-table rows from the TPC-DS scale
model instead of reading data).
"""

from __future__ import annotations

import dataclasses
import math
import os
from dataclasses import dataclass, field
from typing import Optional

from ..engine import expr as E
from ..engine import plan as P
from ..schema import TABLE_PARTITIONING, TABLE_PRIMARY_KEYS

# ---------------------------------------------------------------------------
# TPC-DS row-count model (python port of datagen/native/rowcounts.hpp — the
# generator and the budgeter must agree on what a scale factor means)
# ---------------------------------------------------------------------------

#: spec row counts (TPC-DS v3.2.0 table 3-2) at the defined scale knots
_SCALE_KNOTS = (1, 10, 100, 1000, 3000, 10000, 100000)

_DIM_SCALE_POINTS = {
    "call_center": (6, 24, 30, 42, 48, 54, 60),
    "catalog_page": (11718, 12000, 20400, 30000, 36000, 40000, 50000),
    "customer": (100000, 500000, 2000000, 12000000, 30000000, 65000000,
                 100000000),
    "customer_address": (50000, 250000, 1000000, 6000000, 15000000,
                         32500000, 50000000),
    "item": (18000, 102000, 204000, 300000, 360000, 402000, 502000),
    "promotion": (300, 500, 1000, 1500, 1800, 2000, 2500),
    "reason": (35, 45, 55, 65, 67, 70, 75),
    "store": (12, 102, 402, 1002, 1350, 1500, 1902),
    "warehouse": (5, 10, 15, 20, 22, 25, 30),
    "web_page": (60, 200, 2040, 3000, 3600, 4002, 5004),
    "web_site": (30, 42, 54, 60, 66, 78, 96),
}

_FIXED_ROWS = {
    "customer_demographics": 1920800,
    "household_demographics": 7200,
    "date_dim": 73049,
    "time_dim": 86400,
    "income_band": 20,
    "ship_mode": 20,
}

#: (orders at SF1, average lines per order) per sales channel; returns are
#: ~10% of sales lines (facts.hpp is_returned)
_CHANNELS = {
    "store_sales": (240000, 12.0),
    "catalog_sales": (160000, 9.0),
    "web_sales": (60000, 12.0),
}
_RETURN_FRACTION = 0.10
_INVENTORY_WEEKS = 261


def _interp_rows(points, sf: float) -> int:
    if sf <= 1.0:
        return max(int(math.ceil(points[0] * sf)), min(points[0], 2))
    for i in range(len(_SCALE_KNOTS) - 1):
        if sf <= _SCALE_KNOTS[i + 1]:
            t = (math.log(sf) - math.log(_SCALE_KNOTS[i])) / (
                math.log(_SCALE_KNOTS[i + 1]) - math.log(_SCALE_KNOTS[i])
            )
            lo = math.log(points[i])
            hi = math.log(points[i + 1])
            return int(round(math.exp(lo + t * (hi - lo))))
    return points[-1]


def spec_table_rows(table: str, sf: float) -> Optional[int]:
    """Estimated base-table rows at scale factor `sf` under the generator's
    scaling model (exact at the spec's defined scale points for dims,
    expected value for the line-count-randomized facts). None for a table
    the model doesn't know (synthetic test registrations)."""
    if table in _DIM_SCALE_POINTS:
        return _interp_rows(_DIM_SCALE_POINTS[table], sf)
    if table in _FIXED_ROWS:
        return _FIXED_ROWS[table]
    if table in _CHANNELS:
        orders, lines = _CHANNELS[table]
        return max(int(round(orders * sf * lines)), 1)
    if table.endswith("_returns"):
        sales = table[: -len("_returns")] + "_sales"
        if sales in _CHANNELS:
            orders, lines = _CHANNELS[sales]
            return max(int(round(orders * sf * lines * _RETURN_FRACTION)), 1)
    if table == "inventory":
        item = _interp_rows(_DIM_SCALE_POINTS["item"], sf)
        wh = _interp_rows(_DIM_SCALE_POINTS["warehouse"], sf)
        return _INVENTORY_WEEKS * max(item // 2, 1) * wh
    return None


# ---------------------------------------------------------------------------
# widths / budget resolution
# ---------------------------------------------------------------------------

#: minimum capacity bucket (columnar._MIN_CAP; kept literal so this module
#: never imports jax — the budgeter must run in schema-only CLI contexts)
_MIN_CAP = 1024


def bucket_cap(n: int) -> int:
    cap = _MIN_CAP
    while cap < n:
        cap *= 2
    return cap


def column_row_bytes(dtype) -> int:
    """Device bytes per row of one column: data itemsize + 1 validity byte
    (matches exec._blocked_union_ctx's row_bytes rule). Strings are int32
    dictionary codes on device; decimals are scaled int64."""
    k = dtype.kind
    if k in ("int32", "date", "string", "char", "varchar"):
        return 5
    if k == "bool":
        return 2
    return 9  # int64 / float64 / decimal


def schema_row_bytes(sch: dict) -> int:
    """Bytes per row over a name -> DType schema mapping."""
    return max(sum(column_row_bytes(dt) for dt in sch.values()), 1)


#: default modeled working-set budget. Calibrated against the corpus gate
#: with THIN margins on both sides — treat any change as a calibration
#: event, not a tuning knob: max modeled SF1 peak is 3.75 GiB (q23, 94% of
#: the line; all 103 statements must stay admitted) and the smallest
#: round-5 SF10 device-OOM estimate is 4.74 GiB (q6, must stay flagged).
#: Physically: a 16 GB v5e chip minus the 6 GB catalog residency budget
#: minus allocator/fragmentation headroom.
DEFAULT_BUDGET_BYTES = 4 << 30

#: calibration contract for the model (tests/test_budget.py): the measured
#: per-node materialization (op_span est_bytes high-water) of a query must
#: not exceed CALIBRATION_SLACK x its static peak estimate
CALIBRATION_SLACK = 2.0

#: blocked-union windows get at most this fraction of the budget (the
#: window buffers coexist with cached base tables, the per-window join
#: output and the partial-aggregate merge intermediates) — the derivation
#: Session.union_agg_window_rows used to carry inline
WINDOW_BUDGET_FRACTION = 16

#: out-of-core partition-count cap: past this, per-partition fixed costs
#: (probe re-scan, segment round trips) dominate any HBM relief
SPILL_MAX_PARTITIONS = 256

MODES = ("off", "warn", "on")


def spillable_node(v) -> bool:
    """True when a plan node owns an out-of-core rewrite the executor can
    actually run (exec._spilled_join/_spilled_take/_spilled_distinct):
    inner/left joins and MultiJoins (hash-partitioned build+probe), sorts
    (sorted runs), Distinct and UNION-distinct (partition-hash dedup).
    Everything else — semi/anti/full joins, set ops with whole-input
    semantics, aggregates (the blocked-union seam owns those) — does not
    decompose over hash partitions, and the verifier flags a
    `spill_partitions` annotation landing on one."""
    if isinstance(v, P.Join):
        return v.kind in ("inner", "left")
    if isinstance(v, (P.MultiJoin, P.Sort, P.Distinct)):
        return True
    if isinstance(v, P.SetOp):
        return v.op == "union"
    return False


def choose_spill_partitions(peak_bytes: int, budget_bytes: int) -> int:
    """Statically sized partition count: the smallest power of two that
    models the dominant transient under the budget, clamped to
    [2, SPILL_MAX_PARTITIONS]."""
    ratio = max(
        -(-int(peak_bytes) // max(int(budget_bytes), 1)), 2
    )  # ceil div
    parts = 1 << (ratio - 1).bit_length()
    return int(min(max(parts, 2), SPILL_MAX_PARTITIONS))

#: TPC-DS column-name prefix -> owning table (longest match wins). A
#: column cannot carry more distinct values than its owning table has
#: rows, so this gives the budgeter a sound static NDV bound for group
#: keys (s_store_id groups cap at |store|, not at fact scale) without any
#: runtime statistics.
_COL_PREFIX_TABLE = {
    "ss_": "store_sales", "sr_": "store_returns",
    "cs_": "catalog_sales", "cr_": "catalog_returns",
    "ws_": "web_sales", "wr_": "web_returns", "inv_": "inventory",
    "d_": "date_dim", "t_": "time_dim",
    "c_": "customer", "ca_": "customer_address",
    "cd_": "customer_demographics", "hd_": "household_demographics",
    "ib_": "income_band", "i_": "item", "p_": "promotion",
    "r_": "reason", "s_": "store", "sm_": "ship_mode",
    "w_": "warehouse", "wp_": "web_page", "web_": "web_site",
    "cc_": "call_center", "cp_": "catalog_page",
}


#: foreign-key suffix -> referenced dimension (a FK column's distinct
#: values are bounded by the referenced table's rows — tighter than the
#: owning fact's row count)
_FK_SUFFIX_TABLE = {
    "_item_sk": "item", "_date_sk": "date_dim", "_time_sk": "time_dim",
    "_customer_sk": "customer", "_store_sk": "store",
    "_warehouse_sk": "warehouse", "_promo_sk": "promotion",
    "_cdemo_sk": "customer_demographics",
    "_hdemo_sk": "household_demographics", "_addr_sk": "customer_address",
    "_web_page_sk": "web_page", "_web_site_sk": "web_site",
    "_call_center_sk": "call_center", "_catalog_page_sk": "catalog_page",
    "_ship_mode_sk": "ship_mode", "_reason_sk": "reason",
}


def column_owner_table(col_name: str) -> Optional[str]:
    """The TPC-DS table a column name belongs to by prefix convention
    ("store.s_store_id" -> "store"), or None for derived names."""
    bare = col_name.split(".")[-1]
    best = None
    for pref, table in _COL_PREFIX_TABLE.items():
        if bare.startswith(pref) and (best is None or len(pref) > len(best[0])):
            best = (pref, table)
    return best[1] if best else None


def column_domain_table(col_name: str) -> Optional[str]:
    """The table bounding a column's distinct-value count: the referenced
    dimension for FK-suffixed columns (ss_item_sk -> item), else the
    owning table by prefix."""
    bare = col_name.split(".")[-1]
    for suf, table in _FK_SUFFIX_TABLE.items():
        if bare.endswith(suf):
            return table
    return column_owner_table(col_name)


def resolve_mode(conf: Optional[dict] = None) -> str:
    v = None
    if conf:
        v = conf.get("engine.plan_budget")
    v = v or os.environ.get("NDS_PLAN_BUDGET") or "on"
    v = str(v).lower()
    if v not in MODES:
        raise ValueError(
            f"engine.plan_budget must be one of {MODES}, got {v!r}"
        )
    return v


def resolve_budget_bytes(conf: Optional[dict] = None) -> int:
    v = None
    if conf:
        v = conf.get("engine.plan_budget_bytes")
    v = v or os.environ.get("NDS_PLAN_BUDGET_BYTES")
    return int(v) if v else DEFAULT_BUDGET_BYTES


#: admission-reject line: a plan modeled beyond this is refused outright at
#: plan time (mode `on`). Well above the over-budget line on purpose — a
#: marginally-over plan is still admitted with the ladder pre-armed, only
#: plans that cannot fit the physical device (16 GB v5e HBM minus runtime
#: headroom) are rejected before burning a dispatch on them.
DEFAULT_REJECT_BYTES = 14 << 30


def resolve_reject_bytes(conf: Optional[dict] = None) -> int:
    v = None
    if conf:
        v = conf.get("engine.plan_budget_reject_bytes")
    v = v or os.environ.get("NDS_PLAN_BUDGET_REJECT_BYTES")
    return int(v) if v else DEFAULT_REJECT_BYTES


def default_window_rows(row_bytes: int, budget_bytes: int) -> int:
    """Rows per blocked-union window for `row_bytes`-wide rows under a byte
    budget: ~1/WINDOW_BUDGET_FRACTION of the budget, rounded DOWN to a
    power of two (stable slice shapes), clamped to [64Ki, 16Mi] rows. The
    session-level derivation (`Session.union_agg_window_rows`) delegates
    here; the static verdict path reuses the same clamps so plan-time and
    runtime sizing can never disagree on bounds."""
    budget = budget_bytes // WINDOW_BUDGET_FRACTION
    rows = max(budget // max(row_bytes, 1), 1)
    pow2 = 1 << (rows.bit_length() - 1)
    return int(min(max(pow2, 1 << 16), 1 << 24))


def derive_share_bytes(total_bytes: int, fraction: int,
                       lo: int, hi: int) -> int:
    """A byte budget as 1/`fraction` of a measured resource, rounded DOWN
    to a power of two and clamped to [lo, hi] — the same shape as the
    union-window derivation above (default_window_rows), generalized so
    every `auto` budget in the engine sizes itself the same way: the spill
    pool's host-RAM share (engine.spill_pool_bytes=auto) and the AOT
    executable cache's disk share (engine.aot_cache_bytes unset) both
    delegate here instead of inventing their own formula."""
    share = max(int(total_bytes) // max(int(fraction), 1), 1)
    pow2 = 1 << (share.bit_length() - 1)
    return int(min(max(pow2, lo), hi))


#: serve-mode admission sizing: one concurrently admitted request is
#: assumed to transiently hold up to this much device working set beyond
#: the catalog residency (an admitted-direct statement's modeled peak is
#: bounded by the budget line; slots = budget // this, so full occupancy
#: stays inside the same working-set budget single-stream admission uses)
SERVE_SLOT_BYTES = 1 << 30


def serve_concurrency(conf: Optional[dict] = None) -> int:
    """Admission slots (= worker-pool size) for `nds-tpu-submit serve`.

    `engine.serve_workers` / NDS_SERVE_WORKERS overrides; otherwise the
    count derives from the SAME working-set budget the plan budgeter
    admits statements against (`resolve_budget_bytes`): one slot per
    SERVE_SLOT_BYTES of budget, clamped to [1, 16]. The default 4 GiB
    budget therefore carries 4 concurrent requests — sized so the sum of
    concurrently admitted working sets stays inside what one admitted
    batch statement could have used alone."""
    v = None
    if conf:
        v = conf.get("engine.serve_workers")
    if v is None:
        v = os.environ.get("NDS_SERVE_WORKERS")
    if v:
        try:
            return max(int(v), 1)
        except (TypeError, ValueError):
            pass
    budget = resolve_budget_bytes(conf)
    return int(min(max(budget // SERVE_SLOT_BYTES, 1), 16))


def host_ram_bytes() -> int:
    """Physical host RAM in bytes (sysconf), falling back to a 16 GiB
    assumption on platforms without the counters — the `auto` budget
    derivations must never crash over a missing proc interface."""
    try:
        pages = os.sysconf("SC_PHYS_PAGES")
        page = os.sysconf("SC_PAGE_SIZE")
        if pages > 0 and page > 0:
            return int(pages) * int(page)
    except (ValueError, OSError, AttributeError):
        pass
    return 16 << 30


# ---------------------------------------------------------------------------
# catalog cardinality source
# ---------------------------------------------------------------------------


class CatalogStats:
    """Base-table row counts for the budgeter, best source first:

    1. actual loaded rows (`_Entry.nrows`) or in-memory arrow row counts;
    2. parquet/orc dataset metadata (`count_rows`, footer-only; memoized
       per entry so a session pays it once);
    3. the TPC-DS scale model when a scale factor is declared
       (conf `engine.plan_budget_sf`, schema-only sessions);
    4. None — cardinality unknown, the verdict degrades to `unknown`.
    """

    def __init__(self, catalog, scale_factor: Optional[float] = None):
        self.catalog = catalog
        self.scale_factor = scale_factor

    def table_rows(self, name: str) -> Optional[int]:
        e = self.catalog.entries.get(name) if self.catalog else None
        if e is not None:
            if e.nrows is not None:
                return int(e.nrows)
            if e.arrow is not None:
                return int(e.arrow.num_rows)
            if e.fmt in ("parquet", "orc", "lakehouse"):
                # memoized metadata count; a FAILED probe is memoized as
                # -1 but must still fall through to the scale model below
                # (a transient IO error must not pin the table to
                # `unknown` for the session's lifetime). Lakehouse tables
                # answer from the manifest (pinned snapshot when one
                # exists, else the current head) — a COLD lakehouse
                # warehouse must still produce enforceable verdicts, or a
                # serving fleet's admission edge degrades to `unknown`
                # until every table has been touched once.
                cached = getattr(e, "budget_est_rows", None)
                if cached is None:
                    try:
                        if e.fmt == "lakehouse":
                            snap = e.pinned_snapshot
                            if snap is None:
                                from ..lakehouse.table import LakehouseTable

                                snap = LakehouseTable(e.path).snapshot()
                            cached = int(snap.num_rows())
                        else:
                            cached = int(
                                self.catalog._dataset(e).count_rows()
                            )
                    except Exception:
                        cached = -1
                    e.budget_est_rows = cached
                if cached >= 0:
                    return cached
        if self.scale_factor is not None:
            return spec_table_rows(name, self.scale_factor)
        return None

    def schema(self, name: str):
        return self.catalog.schema(name) if self.catalog else None


# ---------------------------------------------------------------------------
# selectivity heuristics
# ---------------------------------------------------------------------------

_SEL_EQ = 0.1
_SEL_RANGE = 0.4
_SEL_BETWEEN = 0.3
_SEL_LIKE = 0.25
_SEL_NULL = 0.1
_SEL_FLOOR = 0.02  # conjunction floor: heuristics must not promise miracles


def selectivity(e) -> float:
    """Heuristic fraction of rows a predicate keeps, in [_SEL_FLOOR, 1].
    Deliberately coarse and floor-clamped: the budgeter needs an upper
    bound, not a cost-based optimum, so deep conjunctions stop shrinking at
    _SEL_FLOOR instead of promising near-zero cardinalities the data may
    not deliver (FK distributions are not uniform over PK domains)."""
    return max(_SEL_FLOOR, min(_raw_sel(e), 1.0))


def _raw_sel(e) -> float:
    if isinstance(e, E.BinOp):
        if e.op == "and":
            return max(_raw_sel(e.left) * _raw_sel(e.right), _SEL_FLOOR)
        if e.op == "or":
            return min(_raw_sel(e.left) + _raw_sel(e.right), 1.0)
        if e.op == "=":
            return _SEL_EQ
        if e.op in ("<", "<=", ">", ">="):
            return _SEL_RANGE
        if e.op in ("<>", "!="):
            return 0.9
        return 1.0
    if isinstance(e, E.Between):
        return _SEL_BETWEEN
    if isinstance(e, E.InList):
        return min(_SEL_EQ * max(len(e.values), 1), 0.6)
    if isinstance(e, E.Like):
        return _SEL_LIKE
    if isinstance(e, E.UnaryOp):
        if e.op == "not":
            return max(1.0 - _raw_sel(e.operand), _SEL_FLOOR)
        if e.op == "isnull":
            return _SEL_NULL
        if e.op == "isnotnull":
            return 1.0
    return 1.0


# ---------------------------------------------------------------------------
# the analyzer
# ---------------------------------------------------------------------------


class PlanBudgetError(Exception):
    """Admission control: the plan's modeled peak exceeds the budget even
    under windowed execution. Deterministic for a given catalog, so
    faults.classify maps it to the `planner` kind and the report ladder
    fails fast instead of walking OOM rungs."""

    def __init__(self, peak_bytes: int, budget_bytes: int, detail: str = ""):
        self.peak_bytes = peak_bytes
        self.budget_bytes = budget_bytes
        super().__init__(
            f"plan rejected by admission control: modeled peak "
            f"{peak_bytes / (1 << 30):.2f} GiB exceeds the "
            f"{budget_bytes / (1 << 30):.2f} GiB plan budget"
            + (f" ({detail})" if detail else "")
        )


@dataclass
class NodeEstimate:
    """Per-node static estimate. `alloc_bytes` is what executing THIS node
    materializes (output buffers + transient work: key words, pair gathers,
    sort scratch); `live_bytes` is what the node's result pins for its
    parent; `peak_bytes` is the modeled high-water of the whole subtree
    (children retained while later siblings/parent work runs). In mesh
    mode every byte figure is PER DEVICE: a `sharded` node's buffers
    divide by the mesh width, a replicated node's are charged in full on
    every chip (the layout Catalog._to_device actually places)."""

    node: object
    desc: str
    rows: int
    width: int
    cap: int
    alloc_bytes: int
    live_bytes: int
    peak_bytes: int
    blocked: bool = False
    sharded: bool = False
    children: list = field(default_factory=list)


@dataclass
class PlanBudget:
    """The analyzer's statement-level result."""

    nodes: list  # post-order NodeEstimate list
    peak_bytes: int  # modeled peak, blocked-union aggregates DIRECT
    peak_blocked_bytes: int  # modeled peak with blocked aggs windowed
    budget_bytes: int
    verdict: str  # direct | blocked | spill | over | reject | unknown
    window_rows: Optional[int] = None  # set when verdict == blocked
    #: mesh width the model divided sharded node bytes by (None = the
    #: single-device model); the verdict is then PER DEVICE — what each
    #: chip's working set must fit, with replicated relations charged on
    #: every chip
    mesh_devices: Optional[int] = None
    unknown_tables: list = field(default_factory=list)
    #: the plan carries >= 1 out-of-core seam (spillable_node) — recorded
    #: for EVERY verdict so the report ladder's spill_retry rung knows an
    #: unpredicted device OOM can retry through the spill pool
    spillable: bool = False
    spill_partitions: Optional[int] = None  # set when verdict == spill
    #: nodes whose static row estimate a recorded actual replaced
    #: (engine.plan_feedback=on; 0 = the pure static model)
    feedback_overrides: int = 0

    def table(self, limit: int = 0) -> str:
        """Human-readable per-node estimate table (explain --budget)."""
        rows = self.nodes if not limit else self.nodes[-limit:]
        out = [
            f"{'rows':>12}  {'width':>6}  {'cap':>12}  {'alloc':>10}  "
            f"{'peak':>10}  node"
        ]
        for n in rows:
            out.append(
                f"{n.rows:>12}  {n.width:>6}  {n.cap:>12}  "
                f"{_fmt_bytes(n.alloc_bytes):>10}  "
                f"{_fmt_bytes(n.peak_bytes):>10}  "
                f"{'[blocked] ' if n.blocked else ''}"
                f"{'[sharded] ' if n.sharded else ''}{n.desc[:72]}"
            )
        out.append(
            (
                f"verdict ({self.mesh_devices}-device mesh, per device): "
                if self.mesh_devices
                else "verdict: "
            )
            + f"{self.verdict}  peak={_fmt_bytes(self.peak_bytes)}"
            f" (windowed={_fmt_bytes(self.peak_blocked_bytes)})"
            f" budget={_fmt_bytes(self.budget_bytes)}"
            + (f" window_rows={self.window_rows}" if self.window_rows else "")
            + (
                f" spill_partitions={self.spill_partitions}"
                if self.spill_partitions
                else ""
            )
            + (
                f" unknown_tables={sorted(set(self.unknown_tables))}"
                if self.unknown_tables
                else ""
            )
        )
        return "\n".join(out)


def _fmt_bytes(b: int) -> str:
    if b >= 1 << 30:
        return f"{b / (1 << 30):.2f}G"
    if b >= 1 << 20:
        return f"{b / (1 << 20):.1f}M"
    if b >= 1 << 10:
        return f"{b / (1 << 10):.1f}K"
    return str(int(b))


class PlanBudgeter:
    """Walks a bound + rewritten plan bottom-up, producing NodeEstimates.

    Schema resolution is delegated to the PlanVerifier's memoized static
    dtype inference so the byte model and the verifier can never disagree
    about a node's output schema. Estimates memoize by node id: shared
    subtrees (CTE diamonds) cost one walk, and when two parents consume
    one shared result its live bytes count at each consumer — which is
    what the executor's _cte_cache really does to memory."""

    def __init__(self, catalog=None, stats: Optional[CatalogStats] = None,
                 budget_bytes: Optional[int] = None, windowed: bool = False,
                 mesh_devices: Optional[int] = None, feedback=None):
        from .verifier import PlanVerifier, _count_plan_refs

        self.stats = stats or CatalogStats(catalog)
        self.budget_bytes = (
            budget_bytes if budget_bytes is not None else DEFAULT_BUDGET_BYTES
        )
        #: mesh width: sharded node bytes divide by this (per-device
        #: verdict), replicated relations stay charged in full per device.
        #: 1 = the single-device model, byte-identical to pre-mesh output.
        self.n_dev = max(int(mesh_devices or 1), 1)
        #: windowed=True models blocked-union aggregates on the windowed
        #: executor path (branches materialized, concat/join/aggregate per
        #: bounded window) instead of the direct full-concat path
        self.windowed = windowed
        self._ver = PlanVerifier(catalog)
        self._count_refs = _count_plan_refs
        self._memo: dict = {}
        self._post: list = []
        self.unknown_tables: list = []
        #: statically derived window rows per blocked aggregate modeled in
        #: windowed mode (plan window = min over these)
        self.blocked_windows: list = []
        #: measured-cardinality overrides (engine.plan_feedback=on):
        #: {id(node): recorded actual rows} from the FeedbackStore. None
        #: (or empty) keeps the static model byte-identical; applied
        #: overrides are collected for the plan_feedback event
        self.feedback = feedback or None
        self.feedback_applied: list = []

    # -- entry ----------------------------------------------------------
    def run(self, root: P.PlanNode) -> int:
        """Walk the plan; return the modeled peak bytes. Scalar subquery
        plans execute as separate statements before the main plan, so
        their peaks are independent candidates."""
        self._ver._refs = self._count_refs(root)
        peak = self._est(root).peak_bytes
        for sub in self._subquery_plans(root):
            peak = max(peak, self._est(sub).peak_bytes)
        return peak

    def _subquery_plans(self, root):
        return [
            v.plan
            for v in P.walk_plan(root)
            if isinstance(v, E.ScalarSubquery) and v.plan is not None
        ]

    # -- helpers --------------------------------------------------------
    def _schema(self, node) -> dict:
        sch = self._ver._schema_of(node)
        return sch if sch is not None else {}

    def _width(self, node) -> int:
        return schema_row_bytes(self._schema(node))

    def _div(self, nbytes, sharded: bool) -> int:
        """Per-device share of a byte figure: sharded buffers split over
        the mesh width, everything else is charged in full on each chip
        (the replicated-dim placement). Identity on a 1-wide mesh."""
        if sharded and self.n_dev > 1:
            return int(nbytes) // self.n_dev
        return int(nbytes)

    def _finish(self, node, rows, width, alloc, children,
                live=None, blocked=False, sharded=False) -> NodeEstimate:
        rows = max(int(rows), 0)
        fb = self.feedback.get(id(node)) if self.feedback else None
        if fb is not None:
            # measured actual overrides the static estimate (clamped:
            # the recorded value is the observed MAXIMUM, so the new
            # estimate is never below anything this node has produced).
            # Allocation scales with the capacity bucket ratio — the
            # per-rule alloc terms are cap-proportional, and children's
            # own overrides were already applied bottom-up
            fb = max(int(fb), 0)
            if fb != rows:
                old_cap = bucket_cap(max(rows, 1))
                new_cap = bucket_cap(max(fb, 1))
                if new_cap != old_cap:
                    alloc = int(alloc * (new_cap / old_cap))
                    if live is not None:
                        live = int(live * (new_cap / old_cap))
                rows = fb
                self.feedback_applied.append(node)
        cap = bucket_cap(max(rows, 1))
        live_b = (
            live if live is not None else self._div(cap * width, sharded)
        )
        # executor retention model: children run left-to-right, each
        # earlier child's result stays live while later siblings execute,
        # and all children stay live while this node materializes
        peak = 0
        acc = 0
        for c in children:
            peak = max(peak, acc + c.peak_bytes)
            acc += c.live_bytes
        peak = max(peak, acc + alloc)
        est = NodeEstimate(
            node=node,
            desc=P.node_desc(node),
            rows=rows,
            width=width,
            cap=cap,
            alloc_bytes=int(alloc),
            live_bytes=int(live_b),
            peak_bytes=int(peak),
            blocked=blocked,
            sharded=bool(sharded),
        )
        self._post.append(est)
        return est

    def _est(self, node) -> NodeEstimate:
        if node is None:
            return NodeEstimate(None, "missing", 0, 1, _MIN_CAP, 0, 0, 0)
        key = id(node)
        hit = self._memo.get(key)
        if hit is not None:
            return hit
        m = getattr(self, f"_est_{type(node).__name__.lower()}", None)
        if m is None:
            est = self._finish(node, 1, self._width(node), 0, [])
        else:
            est = m(node)
        self._memo[key] = est
        return est

    # -- per-node rules (mirror exec.py materialization; sharded-ness
    # mirrors the verifier's PartitionSpec propagation so the byte model
    # and the sharding rules can never disagree about layout) ------------
    def _scan_sharded(self, table: str, cap: int) -> bool:
        """True when Catalog._to_device would row-shard this base table
        over the mesh: a registered fact (TABLE_PARTITIONING — the same
        registry table_partition_spec derives from) whose capacity bucket
        divides the mesh width (else the loud replication fallback)."""
        return (
            self.n_dev > 1
            and table in TABLE_PARTITIONING
            and cap % self.n_dev == 0
        )

    def _est_scan(self, node: P.Scan) -> NodeEstimate:
        rows = self.stats.table_rows(node.table)
        # zone-map surviving-row bound (Session._prune_lake_scans): a HARD
        # upper bound from the pinned manifest's per-file stats — tighter
        # than any table-level estimate whenever pruning fired, and a
        # usable size even for tables the stats layer knows nothing about
        prune_rows = getattr(node, "prune_rows", None)
        if prune_rows is not None:
            rows = prune_rows if rows is None else min(rows, prune_rows)
        if rows is None:
            self.unknown_tables.append(node.table)
            rows = 0
        width = self._width(node)
        cap = bucket_cap(max(rows, 1))
        sharded = self._scan_sharded(node.table, cap)
        return self._finish(
            node, rows, width, self._div(cap * width, sharded), [],
            sharded=sharded,
        )

    def _est_materializedscan(self, node: P.MaterializedScan) -> NodeEstimate:
        rows = 1
        if node.table is not None:
            known = node.table.nrows_known
            rows = known if known is not None else int(node.table.cap)
        width = self._width(node)
        # already materialized: no new allocation, but it is live input
        return self._finish(node, rows, width, 0, [])

    def _est_project(self, node: P.Project) -> NodeEstimate:
        child = self._est(node.child)
        sch = self._schema(node)
        width = schema_row_bytes(sch)
        computed = sum(
            column_row_bytes(dt)
            for (e, _name), dt in zip(node.items, sch.values())
            if not isinstance(e, E.Col)
        )
        return self._finish(
            node, child.rows, width,
            self._div(child.cap * computed, child.sharded), [child],
            sharded=child.sharded,
        )

    def _est_filter(self, node: P.Filter) -> NodeEstimate:
        child = self._est(node.child)
        rows = int(math.ceil(child.rows * selectivity(node.predicate)))
        # deferred compaction: the live mask is the only new buffer; data
        # buffers are shared with the child (capacity stays the child's)
        return self._finish(
            node, rows, child.width,
            self._div(child.cap, child.sharded), [child],
            live=self._div(child.cap * child.width, child.sharded),
            sharded=child.sharded,
        )

    def _est_pipeline(self, node: P.Pipeline) -> NodeEstimate:
        child = self._est(node.child)
        rows = child.rows
        for s in node.stages:
            if isinstance(s, P.Filter):
                rows = int(math.ceil(rows * selectivity(s.predicate)))
        if node.agg is not None:
            # the fused body runs the chain AND the partial-aggregate
            # scatter in ONE dispatch over the chain INPUT (masks deferred
            # to the boundary), so the key/sort-word working set scales
            # with the child's capacity, not the post-filter estimate
            return self._agg_estimate(node, node.agg, [child], rows,
                                      child.cap, in_sharded=child.sharded)
        width = self._width(node)
        # the fused body materializes the full output column set at the
        # input capacity in one dispatch (masks deferred to the boundary)
        return self._finish(
            node, rows, width, self._div(child.cap * width, child.sharded),
            [child], sharded=child.sharded,
        )

    def _keys_unique(self, side, keys) -> bool:
        """True when `keys` cover a declared primary key of the side's
        base table (a Scan reached through Filter/Project/Pipeline
        wrappers) — the static stand-in for the runtime unique-key probe."""
        _, base = P._peel_wrappers(side)
        if not isinstance(base, P.Scan):
            return False
        pk = TABLE_PRIMARY_KEYS.get(base.table)
        if pk is None:
            return False
        names = set()
        for k in keys:
            for c in E.walk(k):
                if isinstance(c, E.Col):
                    names.add(c.name.split(".")[-1])
        return set(pk) <= names

    def _est_join(self, node: P.Join) -> NodeEstimate:
        left = self._est(node.left)
        right = self._est(node.right)
        if node.kind == "cross":
            rows = max(left.rows, 1) * max(right.rows, 1)
        elif node.kind in ("semi", "anti", "mark"):
            rows = left.rows
        elif self._keys_unique(node.right, node.right_keys):
            rows = left.rows
        elif self._keys_unique(node.left, node.left_keys):
            rows = right.rows
        else:
            rows = max(left.rows, right.rows)
        width = self._width(node)
        cap = bucket_cap(max(rows, 1))
        sharded = left.sharded or right.sharded
        # key words (8B per side) + compaction of both inputs + the pair
        # table gathered at the output width — per side's own layout: a
        # sharded fact's words/compaction split over the mesh (exchange /
        # local probe), a replicated dim pays full on every chip
        alloc = (
            self._div(8 * left.cap + left.cap * left.width, left.sharded)
            + self._div(8 * right.cap + right.cap * right.width,
                        right.sharded)
            + self._div(cap * width, sharded)
        )
        return self._finish(node, rows, width, alloc, [left, right],
                            sharded=sharded)

    def _est_multijoin(self, node: P.MultiJoin) -> NodeEstimate:
        rels = [self._est(r) for r in node.relations]
        width = self._width(node)
        # greedy pairwise joins: output rows bounded by the largest
        # non-unique (fact-like) relation — a relation whose edges
        # collectively cover its base table's primary key (single-column
        # dims; inventory probed on date+item+warehouse, q72) matches at
        # most one row per probe combination and never expands the join;
        # the last two pair tables carry ~the full accumulated width
        edge_cols = [set() for _ in node.relations]
        for i, j, le, re_ in node.edges:
            for idx, e in ((i, le), (j, re_)):
                if 0 <= idx < len(edge_cols):
                    for c in E.walk(e):
                        if isinstance(c, E.Col):
                            edge_cols[idx].add(c.name.split(".")[-1])
        non_unique = []
        for i, r in enumerate(node.relations):
            _, base = P._peel_wrappers(r)
            pk = (
                TABLE_PRIMARY_KEYS.get(base.table)
                if isinstance(base, P.Scan)
                else None
            )
            if pk is None or not set(pk) <= edge_cols[i]:
                non_unique.append(rels[i].rows)
        rows = max(non_unique or [r.rows for r in rels] or [1])
        cap = bucket_cap(max(rows, 1))
        sharded = any(r.sharded for r in rels)
        alloc = self._div(2 * cap * width, sharded) + sum(
            self._div(8 * r.cap, r.sharded) for r in rels
        )
        return self._finish(node, rows, width, alloc, rels, sharded=sharded)

    def _agg_groups(self, agg, in_rows: int) -> int:
        """Group-count bound. Each key column's distinct values are bounded
        by its domain table's rows (FK suffix -> referenced dim, else
        owning table by prefix), and keys sharing one domain table count
        that table ONCE (all item-attribute keys together cannot exceed
        |item| combinations). Any derived key falls back to the input-rows
        bound — the executor cannot produce more groups than input rows."""
        if not agg.keys:
            return 1
        in_rows = max(in_rows, 1)
        domains = {}
        for e, _name in agg.keys:
            owner = (
                column_domain_table(e.name) if isinstance(e, E.Col) else None
            )
            rows_t = self.stats.table_rows(owner) if owner else None
            if rows_t is None:
                return in_rows
            domains[owner] = max(rows_t, 1)
        prod = 1
        for rows_t in domains.values():
            prod *= rows_t
            if prod >= in_rows:
                return in_rows
        return max(min(prod, in_rows), 1)

    def _agg_estimate(self, node, agg, children, in_rows, in_cap,
                      blocked=False, in_sharded=False) -> NodeEstimate:
        sch = self._schema(node)
        width = schema_row_bytes(sch)
        groups = self._agg_groups(agg, in_rows)
        levels = min(len(agg.grouping_sets), 3) if agg.grouping_sets else 1
        rows = groups * (2 if agg.grouping_sets else 1)
        cap = bucket_cap(max(rows, 1))
        # segment-reduce path: 2 x 8B key/sort words over the input (per
        # shard under a mesh — the scatter-add lowers to per-chip partials)
        # + the group output (x cascade levels' incremental concat), which
        # MERGES replicated (psum) and is charged in full per device
        alloc = self._div(16 * in_cap, in_sharded) + levels * cap * width
        return self._finish(node, rows, width, alloc, children,
                            blocked=blocked)

    def _est_aggregate(self, node: P.Aggregate) -> NodeEstimate:
        if node.blocked_union and self.windowed:
            shape = P.union_agg_shape(node)
            if shape is not None:
                return self._est_blocked_agg(node, shape)
        child = self._est(node.child)
        return self._agg_estimate(
            node, node, [child], child.rows, child.cap,
            blocked=bool(node.blocked_union), in_sharded=child.sharded,
        )

    def _est_blocked_agg(self, node: P.Aggregate, shape) -> NodeEstimate:
        """The windowed executor path (exec._blocked_union_ctx): union
        branches execute and stay fully materialized, but the concat never
        happens — alignment, the dimension joins and the partial aggregate
        run per bounded window, and partials merge into group-sized
        tables. Peak = branches + dims + O(window x joined width) +
        O(3 x groups x output width)."""
        outer, join, inner, branch_plans = shape
        children = [self._est(b) for b in branch_plans]
        joined_width = self._width(node.child)
        branch_width = max((c.width for c in children), default=9)
        if join is not None:
            mj, uidx = join
            children += [
                self._est(r) for i, r in enumerate(mj.relations) if i != uidx
            ]
        in_rows = sum(
            c.rows for c in children[: len(branch_plans)]
        )
        row_bytes = max(branch_width, joined_width)
        wrows = default_window_rows(row_bytes, self.budget_bytes)
        self.blocked_windows.append(wrows)
        wcap = bucket_cap(wrows)
        groups = self._agg_groups(node, in_rows)
        out_width = self._width(node)
        gcap = bucket_cap(max(groups, 1))
        # aligned window slice + per-window join pair/wrapped output +
        # key words, plus merged/part/concat group tables
        alloc = wcap * (branch_width + joined_width + 16) + 3 * gcap * out_width
        levels = min(len(node.grouping_sets), 3) if node.grouping_sets else 1
        rows = groups * (2 if node.grouping_sets else 1)
        return self._finish(node, rows, out_width, alloc * min(levels, 2),
                            children, blocked=True)

    def _est_window(self, node: P.Window) -> NodeEstimate:
        child = self._est(node.child)
        width = self._width(node)
        # NOT divided under a mesh: the generic window sort all-gathers,
        # so each device pays the full working set (the conservative
        # bound; a future dist-window rewrite can claim the division)
        alloc = 16 * child.cap + 8 * child.cap * max(len(node.fns), 1)
        return self._finish(node, child.rows, width, alloc, [child],
                            sharded=child.sharded)

    def _est_sort(self, node: P.Sort) -> NodeEstimate:
        child = self._est(node.child)
        width = child.width
        # sharded input: the samplesort exchange range-partitions, so no
        # device ever materializes the whole table (exec._try_dist_sort)
        alloc = self._div(16 * child.cap + child.cap * width, child.sharded)
        return self._finish(node, child.rows, width, alloc, [child],
                            sharded=child.sharded)

    def _est_limit(self, node: P.Limit) -> NodeEstimate:
        child = self._est(node.child)
        rows = min(child.rows, max(int(node.n), 0))
        return self._finish(node, rows, child.width, 0, [child],
                            sharded=child.sharded)

    def _est_distinct(self, node: P.Distinct) -> NodeEstimate:
        child = self._est(node.child)
        # input-side dedup work splits over shards; the deduped output
        # merges replicated (like Aggregate), so live bytes stay full
        alloc = self._div(
            16 * child.cap + child.cap * child.width, child.sharded
        )
        return self._finish(node, child.rows, child.width, alloc, [child])

    def _est_setop(self, node: P.SetOp) -> NodeEstimate:
        left = self._est(node.left)
        right = self._est(node.right)
        width = self._width(node)
        rows = left.rows + right.rows
        if node.op in ("intersect", "except"):
            rows = left.rows
        cap = bucket_cap(max(rows, 1))
        # the concat materializes both sides into one capacity bucket;
        # distinct set ops add a sort-words pass. Sharded only when BOTH
        # sides are (the verifier's sharding-axis rule forbids mixing)
        sharded = left.sharded and right.sharded
        alloc = self._div(
            cap * width + (16 * cap if node.op != "union_all" else 0),
            sharded,
        )
        if node.op == "union":
            rows = max(rows // 2, 1)
        return self._finish(node, rows, width, alloc, [left, right],
                            sharded=sharded)


# ---------------------------------------------------------------------------
# statement-level entry points
# ---------------------------------------------------------------------------


def analyze_plan(
    plan: P.PlanNode,
    catalog=None,
    scale_factor: Optional[float] = None,
    budget_bytes: Optional[int] = None,
    reject_bytes: Optional[int] = None,
    mesh_devices: Optional[int] = None,
    feedback=None,
) -> PlanBudget:
    """Analyze one bound + rewritten plan against a catalog (or the TPC-DS
    scale model when `scale_factor` is given): a direct-path pass, a
    windowed pass when the plan carries blocked-union aggregates, and the
    verdict folding both against the two budget lines:

      direct   modeled peak fits the budget
      blocked  over budget, fits once blocked-union aggregates run in
               statically sized windows (`window_rows`)
      over     over budget with no (sufficient) windowing seam, but under
               the reject line: admitted, prediction armed for the ladder
      reject   beyond the reject line even windowed — admission refuses it
      unknown  some base-table cardinality unavailable; no enforcement

    With `mesh_devices` > 1 the model is PER DEVICE: sharded node bytes
    divide by the mesh width, replicated relations are charged on every
    chip, and the verdict answers "does each chip's share fit its HBM
    budget" — the admission question a mesh session (and serve mode on
    one) actually has.

    `feedback` ({id(node): recorded actual rows}, engine.plan_feedback=on)
    replaces static per-node row estimates with measured cardinalities
    before verdict folding; None (the default, and every pre-feedback
    caller) is byte-identical to the static model."""
    stats = CatalogStats(catalog, scale_factor)
    direct = PlanBudgeter(catalog, stats, budget_bytes, windowed=False,
                          mesh_devices=mesh_devices, feedback=feedback)
    peak = direct.run(plan)
    budget = direct.budget_bytes
    reject_line = (
        reject_bytes if reject_bytes is not None else DEFAULT_REJECT_BYTES
    )
    has_blocked = any(e.blocked for e in direct._post)
    peak_blocked = peak
    window_rows = None
    if has_blocked:
        win = PlanBudgeter(catalog, stats, budget_bytes, windowed=True,
                           mesh_devices=mesh_devices, feedback=feedback)
        peak_blocked = min(win.run(plan), peak)
        if win.blocked_windows:
            window_rows = min(win.blocked_windows)
    spillable = any(
        spillable_node(v)
        for v in P.walk_plan(plan)
        if isinstance(v, P.PlanNode)
    )
    spill_partitions = None
    if direct.unknown_tables:
        verdict = "unknown"
        window_rows = None
    elif peak <= budget:
        verdict = "direct"
        window_rows = None
    elif has_blocked and peak_blocked <= budget:
        verdict = "blocked"
    elif min(peak_blocked, peak) <= reject_line:
        # admitted over budget. With an out-of-core seam the verdict is
        # `spill` (between `over` and `reject`): the overage partitions
        # away through the executor's spilled join/sort/distinct paths,
        # with the partition count chosen statically here so the first
        # attempt already runs out-of-core instead of discovering the
        # misfit as a device OOM. Seamless plans stay `over` — admitted
        # with the ladder's prediction armed, exactly as before.
        if spillable:
            verdict = "spill"
            spill_partitions = choose_spill_partitions(
                min(peak_blocked, peak), budget
            )
        else:
            verdict = "over"
        window_rows = window_rows if has_blocked else None
    else:
        verdict = "reject"
        window_rows = None
    return PlanBudget(
        nodes=list(direct._post),
        peak_bytes=peak,
        peak_blocked_bytes=peak_blocked,
        budget_bytes=budget,
        verdict=verdict,
        window_rows=window_rows,
        mesh_devices=(
            int(mesh_devices) if mesh_devices and mesh_devices > 1 else None
        ),
        unknown_tables=list(direct.unknown_tables),
        spillable=spillable,
        spill_partitions=spill_partitions,
        feedback_overrides=len(direct.feedback_applied),
    )


def emit_budget_event(tracer, pb: PlanBudget) -> None:
    """The one `plan_budget` event payload (EVENT_SCHEMA contract) —
    shared by the plan-time hook and the explain CLI so the two emission
    sites can never drift. No-op without a tracer."""
    if tracer is None:
        return
    tracer.emit(
        "plan_budget",
        verdict=pb.verdict,
        peak_bytes=pb.peak_bytes,
        budget_bytes=pb.budget_bytes,
        peak_blocked_bytes=pb.peak_blocked_bytes,
        window_rows=pb.window_rows,
        spill_partitions=pb.spill_partitions,
        mesh_devices=pb.mesh_devices,
        nodes=len(pb.nodes),
    )


def session_mesh_devices(session) -> Optional[int]:
    """The mesh width a session's plans execute over: the live
    jax.sharding.Mesh when the session carries one, else the declared
    `engine.mesh_devices` conf — but the conf fallback applies ONLY to
    schema-only sessions (explain/corpus: catalog entries carry a schema
    and no data, so nothing will ever execute). A session with real data
    but no mesh executes single-device, and a stray conf key must not
    buy it per-device admission verdicts for plans that will run on one
    chip (q14@SF10 modeled 'direct'/8-wide would admit straight into the
    device OOM the budgeter exists to prevent). None/1 = the
    single-device model."""
    mesh = getattr(session, "mesh", None)
    if mesh is not None:
        try:
            n = int(mesh.devices.size)
        except AttributeError:
            n = int(getattr(mesh, "size", 0) or 0)
        if n > 1:
            return n
        return None  # a real 1-wide mesh: single-device, conf ignored
    entries = getattr(getattr(session, "catalog", None), "entries", {})
    if any(
        getattr(e, "arrow", None) is not None
        or getattr(e, "path", None) is not None
        for e in entries.values()
    ):
        return None  # live data, no mesh: plans execute single-device
    try:
        n = int(session.conf.get("engine.mesh_devices") or 0)
    except (TypeError, ValueError):
        n = 0
    return n if n > 1 else None


def budget_plan(plan: P.PlanNode, session) -> Optional[PlanBudget]:
    """The Session._finish_plan hook: analyze, annotate, enforce.

    * emits a `plan_budget` trace event when the session is traced;
    * verdict `blocked`: annotates every blocked-union Aggregate with the
      statically chosen `budget_window_rows` (exec consumes it ahead of
      the runtime derivation; conf/env still win);
    * verdict `reject` in mode `on`: raises PlanBudgetError;
    * stores the result on `session.last_plan_budget` so the report
      ladder's first device-OOM rung can consume the prediction.

    Returns None (and does nothing) when the budgeter is off. Analysis
    failures downgrade to a `verdict="error"` marker instead of failing
    the statement (set NDS_PLAN_BUDGET_STRICT=1 to re-raise — the corpus
    CI gate does), because a budgeting bug must not take down a query the
    runtime ladder could have carried."""
    mode = resolve_mode(session.conf)
    if mode == "off":
        session.last_plan_budget = None
        return None
    sf = session.conf.get("engine.plan_budget_sf")
    # cardinality feedback (analysis/feedback.py): compute this plan's
    # per-node store keys once, consume recorded actuals as estimate
    # overrides in mode `on`, and (below) annotate node_fp/est_rows onto
    # the nodes so the executor can record what actually happened. Store
    # absent or mode off: fb_fps stays None and NOTHING changes
    from . import feedback as _feedback

    fb_store = getattr(session, "feedback_store", None)
    fb_mode = "off"
    fb_fps = None
    fb_overrides = None
    if fb_store is not None:
        fb_mode = _feedback.resolve_feedback_mode(session.conf)
    if fb_mode != "off":
        try:
            fb_fps = _feedback.plan_node_fps(plan, session)
        except Exception:
            if os.environ.get("NDS_PLAN_BUDGET_STRICT"):
                raise
            fb_fps = None
        if fb_fps and fb_mode == "on":
            fb_overrides = {}
            with session.cache_lock:
                for nid, fp in fb_fps.items():
                    rec = fb_store.lookup(fp)
                    rows = (rec or {}).get("rows") or {}
                    if rows.get("max") is not None:
                        fb_overrides[nid] = int(rows["max"])
    try:
        pb = analyze_plan(
            plan,
            session.catalog,
            scale_factor=float(sf) if sf else None,
            budget_bytes=resolve_budget_bytes(session.conf),
            reject_bytes=resolve_reject_bytes(session.conf),
            mesh_devices=session_mesh_devices(session),
            feedback=fb_overrides,
        )
    except Exception as exc:
        if os.environ.get("NDS_PLAN_BUDGET_STRICT"):
            raise
        session.last_plan_budget = {"verdict": "error", "error": str(exc)}
        session.notify_failure(f"plan budgeter failed: {str(exc)[:200]}")
        return None
    emit_budget_event(getattr(session, "tracer", None), pb)
    if fb_fps:
        # annotate estimate accounting onto the plan (the same dynamic-
        # annotation family as budget_window_rows: deliberately NOT
        # dataclass fields, so structural fingerprints and the plan cache
        # stay feedback-agnostic). The executor reads these to emit
        # op_span est-vs-actual fields and to record into the store
        for est in pb.nodes:
            fp = fb_fps.get(id(est.node))
            if fp is None:
                continue
            est.node.node_fp = fp
            est.node.est_rows = est.rows
            est.node.est_live_bytes = est.live_bytes
        tracer = getattr(session, "tracer", None)
        if tracer is not None:
            tracer.emit(
                "plan_feedback",
                op="consume" if fb_mode == "on" else "annotate",
                result="applied" if pb.feedback_overrides else "static",
                mode=fb_mode,
                lookups=len(fb_fps) if fb_mode == "on" else 0,
                hits=len(fb_overrides or {}),
                overrides=pb.feedback_overrides,
                verdict=pb.verdict,
            )
    # `warn` is observe-only: record + trace + arm the ladder, but never
    # change execution (no window annotation, no rejection) — the mode
    # the README points scale-out runs at precisely to escape enforcement
    annotate = (
        mode == "on"
        and pb.window_rows is not None
        # `spill` included: a plan whose blocked seam is insufficient on
        # its own still runs its blocked aggregates with the static
        # window (the spill annotations below handle the rest) — exactly
        # the window an `over` verdict would have armed pre-spill
        and pb.verdict in ("blocked", "spill", "over")
    )
    # an explicit conf/env window eclipses the annotation at execution
    # time (Session.union_agg_window_rows resolution order), so the
    # static window is only IN EFFECT when nothing explicit is set — the
    # ladder's budget_shrink rung keys off this to know whether the
    # failed attempt actually ran the recommendation
    explicit = session.conf.get(
        "engine.union_agg_window_rows"
    ) or os.environ.get("NDS_UNION_AGG_WINDOW_ROWS")
    session.last_plan_budget = {
        "verdict": pb.verdict,
        "peak_bytes": pb.peak_bytes,
        "budget_bytes": pb.budget_bytes,
        "window_rows": pb.window_rows,
        # mesh width the per-device model divided sharded bytes by (None
        # for the single-device model) — serve-mode admission echoes it
        "mesh_devices": pb.mesh_devices,
        "annotated": annotate and not explicit,
        # spill_retry arming: recorded for EVERY verdict — an unpredicted
        # device OOM on a direct/over-verdict plan with an out-of-core
        # seam still retries through the pool (report._next_rung)
        "spillable": pb.spillable,
        "spill_partitions": pb.spill_partitions,
        # estimate-vs-actual accounting: the feedback mode this statement
        # planned under, how many store hits were consulted and how many
        # static estimates a recorded actual replaced (serve's /statusz
        # and `profile --accuracy` read the downstream surfaces)
        "feedback_mode": fb_mode,
        "feedback_hits": len(fb_overrides or {}),
        "feedback_overrides": pb.feedback_overrides,
    }
    if annotate:
        _annotate_blocked_windows(plan, pb.window_rows)
    if mode == "on" and pb.verdict == "spill" and pb.spill_partitions:
        # statically planned degradation: the executor's auto mode spills
        # exactly these nodes (warn stays observe-only, like the window
        # annotation above)
        _annotate_spill(plan, pb.spill_partitions)
    if pb.verdict == "reject" and mode == "on":
        raise PlanBudgetError(
            pb.peak_bytes, pb.budget_bytes,
            detail="no blocked-union seam can window the overage",
        )
    return pb


def _annotate_blocked_windows(plan: P.PlanNode, window_rows: int):
    """Set `budget_window_rows` (a dynamic physical annotation, like
    `_topk_safe` — deliberately NOT a dataclass field, so structural
    fingerprints and the plan cache stay window-agnostic) on every
    blocked-union Aggregate in the tree."""
    for v in P.walk_plan(plan):
        if isinstance(v, P.Aggregate) and v.blocked_union:
            v.budget_window_rows = int(window_rows)


def _annotate_spill(plan: P.PlanNode, partitions: int):
    """Set `spill_partitions` (same dynamic-annotation family as
    `budget_window_rows`: fingerprint/plan-cache-agnostic) on every
    out-of-core-capable node — the executor's `auto` spill mode consumes
    it (exec._spill_parts_for), and the verifier's annotation-coverage
    rule checks its placement and sanity."""
    for v in P.walk_plan(plan):
        if isinstance(v, P.PlanNode) and spillable_node(v):
            v.spill_partitions = int(partitions)
