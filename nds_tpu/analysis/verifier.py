"""Plan-IR verifier: structural invariant checks over bound logical plans.

The binder and the rewrite stack (prune_columns -> mark_blocked_union_aggs
-> mark_pipelines) each carry invariants the executor silently relies on,
and every recent bug in that stack was a *statically checkable* violation:
a LEFT JOIN promoted to INNER from a non-null-rejecting predicate would
drop rows, a Pipeline absorbing a shared wrapper would defeat by-identity
result reuse, a blocked-union annotation on a non-decomposable aggregate
would invite a windowed path that cannot merge partials. Spark's Catalyst
re-runs its analyzer after every rule for exactly this reason; this module
is the TPU engine's equivalent.

`PlanVerifier.verify` walks the whole plan (subquery plans riding inside
expressions included) and checks:

* every node's output schema is resolvable with stable dtypes and no
  duplicate column names (full static expression-dtype inference mirroring
  `engine.expr.Evaluator`'s promotion rules);
* `Pipeline` nodes wrap only detached, fusible, single-consumer
  Filter/Project stages (no shared wrappers, no attached stage children,
  no pipeline-of-pipeline non-maximality);
* `blocked_union` annotations sit only on Aggregates whose shape AND
  aggregate set actually decompose over row windows
  (`plan.union_agg_shape` + `plan.aggs_decomposable`);
* join conditions reference only bound child columns (Join keys against
  their own side, MultiJoin edges against their endpoint relations);
* the binder's LEFT->INNER promotions are each backed by a re-derived
  null-rejecting conjunct shape (`binder._null_rejecting_shape`);
* ORDER BY .. LIMIT top-k nodes preserve the sort-key schema (every sort
  key resolves over the Sort input, which the top-k gather reads);
* SetOp sides agree on arity and aligned output names;
* physical-choice annotations sit only where their consumer reads them
  (`_topk_safe` on Sorts, `donate_ok` on Pipelines, `budget_window_rows`
  on blocked-union Aggregates — the physical-annotation family);
* with a mesh: the sharding invariant family (PartitionSpec axis
  consistency across node boundaries, all_to_all exchange arity,
  replicated-dim legality) against the canonical layout registry
  (`table_partition_spec`), registered ahead of the mesh rewrite pass
  per the PR-5 contract (ROADMAP item 1).

Gating: conf `engine.verify_plans` / env `NDS_VERIFY_PLANS` = off (default)
| final (verify the finished plan once) | all (verify after binding and
after EACH rewrite pass). Violations raise `PlanVerifyError`, which
`faults.classify` maps to the `planner` failure kind (deterministic: the
report ladder fails fast, no retry), and each verification emits a
`plan_verify` trace event (obs/trace.py:EVENT_SCHEMA).

Cost: pure host-side tree walking + dict lookups — no device work, no
compilation. `tools/plan_verify_corpus.py` runs all 99 TPC-DS templates
through `all` strictness in seconds on CPU.
"""

from __future__ import annotations

import dataclasses
import os

from ..dtypes import BOOL, DATE, DType, FLOAT64, INT32, INT64, STRING
from ..engine import expr as E
from ..engine import plan as P
from ..engine.binder import _null_rejecting_shape
from ..engine.expr import _lit_dtype, _promote
from ..schema import TABLE_PARTITIONING
from .budget import (
    SPILL_MAX_PARTITIONS,
    bucket_cap as _bucket_cap,
    schema_row_bytes,
    spillable_node,
)

# ---------------------------------------------------------------------------
# PartitionSpec layout registry (ROADMAP item 1: sharding invariants are
# registered here BEFORE the mesh rewrite pass lands — the PR-5 contract).
# The engine's canonical layout (session.Catalog._to_device): fact tables
# row-shard over the mesh's `data` axis, everything else replicates.
# ---------------------------------------------------------------------------

#: the canonical row-sharding mesh axis (parallel/dist.py builds meshes
#: with this axis; PartitionSpec("data") shards rows across it)
PARTITION_AXIS = "data"

#: a replicated relation above this many device bytes is a layout bug — a
#: fact-scale table copied to every chip defeats sharding entirely (the
#: replicated-dim legality rule)
REPLICATED_BYTES_CAP = 2 << 30


def table_partition_spec(table: str) -> tuple:
    """The canonical PartitionSpec axes for a base table: ("data",) row
    sharding for the partitioned fact tables, () (replicated) for
    dimensions — derived from the same TABLE_PARTITIONING registry
    Catalog._to_device places from, so the verifier's sharding rules and
    the actual device layout cannot disagree."""
    return (PARTITION_AXIS,) if table in TABLE_PARTITIONING else ()


class PlanVerifyError(Exception):
    """A plan failed structural verification. Deterministic (the same plan
    re-verifies to the same violations), so faults.classify maps this to
    the `planner` kind and the report ladder fails fast instead of
    retrying."""

    def __init__(self, stage: str, violations):
        self.stage = stage
        self.violations = list(violations)
        head = "; ".join(self.violations[:3])
        more = (
            f" (+{len(self.violations) - 3} more)"
            if len(self.violations) > 3
            else ""
        )
        super().__init__(
            f"plan verification failed after {stage!r}: "
            f"{len(self.violations)} violation(s): {head}{more}"
        )


LEVELS = ("off", "final", "all")


def resolve_level(conf: dict | None = None) -> str:
    """Verification strictness: conf `engine.verify_plans` wins over the
    NDS_VERIFY_PLANS env knob; default off (zero cost)."""
    v = None
    if conf:
        v = conf.get("engine.verify_plans")
    v = v or os.environ.get("NDS_VERIFY_PLANS") or "off"
    v = str(v).lower()
    if v not in LEVELS:
        raise ValueError(
            f"engine.verify_plans must be one of {LEVELS}, got {v!r}"
        )
    return v


class _Unres(Exception):
    """Internal: expression dtype resolution failed (becomes a violation)."""


#: scalar functions the evaluator implements, mapped to a result-dtype rule
#: (arg dtypes list -> DType). Kept in lockstep with Evaluator._eval_func.
_STRING_FUNCS = ("substr", "substring", "upper", "lower", "trim")


def _count_plan_refs(root) -> dict:
    """Reference count per plan node id over the whole tree (stage lists
    and subquery plans included) — mirrors fuse._count_refs. A Pipeline
    stage with more than one reference is a shared wrapper absorbed by
    mistake."""
    refs = {}
    seen = set()

    def visit(v):
        if isinstance(v, (P.PlanNode, E.Expr)):
            if isinstance(v, P.PlanNode):
                refs[id(v)] = refs.get(id(v), 0) + 1
            if id(v) in seen:
                return
            seen.add(id(v))
            for f in dataclasses.fields(v):
                visit(getattr(v, f.name))
        elif isinstance(v, (list, tuple)):
            for x in v:
                visit(x)

    visit(root)
    return refs


class PlanVerifier:
    """Walks a bound plan and collects invariant violations (strings).

    One instance per verification: schema resolution is memoized per plan
    node id, so shared subtrees (CTE plans, cached scalar subqueries)
    resolve once and the walk stays linear in plan size."""

    def __init__(self, catalog=None):
        self.catalog = catalog  # object with .schema(name) -> Schema | None
        self.violations: list[str] = []
        self._schemas: dict[int, dict | None] = {}
        self._refs: dict[int, int] = {}

    # ------------------------------------------------------------------
    def verify(self, root: P.PlanNode, promotions=(), mesh=None) -> list[str]:
        self._refs = _count_plan_refs(root)
        self._schema_of(root)
        self._check_promotions(promotions)
        self._check_annotations(root)
        if mesh is not None:
            self._check_sharding(root, mesh)
        return list(self.violations)

    # ------------------------------------------------------------------
    # physical-annotation coverage: dynamic annotations (`_topk_safe`,
    # `donate_ok`, `budget_window_rows`) are load-bearing across passes —
    # one landing on the wrong node class silently changes execution, so
    # placement itself is verified, not just the annotated nodes' shape
    # ------------------------------------------------------------------
    def _check_annotations(self, root: P.PlanNode):
        nodes = [v for v in P.walk_plan(root) if isinstance(v, P.PlanNode)]
        for n in nodes:
            if getattr(n, "_topk_safe", False) and not isinstance(n, P.Sort):
                # fuse annotates every single-consumer Sort (the Limit
                # executor is the only reader); the annotation on any
                # other node class means a rewrite copied it somewhere a
                # future top-k check could mis-trust
                self._viol(
                    "physical-annotation", n,
                    "_topk_safe set on a non-Sort node (only ORDER BY "
                    "sorts own the top-k single-consumer contract)",
                )
            if getattr(n, "donate_ok", False) and not isinstance(
                n, P.Pipeline
            ):
                self._viol(
                    "physical-annotation", n,
                    "donate_ok set on a non-Pipeline node (only fused "
                    "pipelines own the donation contract)",
                )
            if getattr(n, "budget_window_rows", None) is not None:
                if not (
                    isinstance(n, P.Aggregate) and n.blocked_union
                ):
                    self._viol(
                        "physical-annotation", n,
                        "budget_window_rows set on a node that is not a "
                        "blocked-union Aggregate (the windowed executor "
                        "is the only consumer of static window sizing)",
                    )
            sp = getattr(n, "spill_partitions", None)
            if sp is not None:
                # out-of-core annotation coverage (registered ahead of the
                # spilled-executor rewrite per the PR-5 contract): the
                # annotation may only land on operators whose rewrite
                # DECOMPOSES over hash partitions / sorted runs, and the
                # statically chosen partition count must be sane
                if not spillable_node(n):
                    self._viol(
                        "spill", n,
                        "spill_partitions set on a node whose operator "
                        "does not decompose over hash partitions/sorted "
                        "runs (only inner/left joins, MultiJoins, Sorts, "
                        "Distinct and UNION own the out-of-core rewrite)",
                    )
                elif not (
                    isinstance(sp, int)
                    and 2 <= sp <= SPILL_MAX_PARTITIONS
                    and sp & (sp - 1) == 0
                ):
                    self._viol(
                        "spill", n,
                        f"spill_partitions={sp!r} is not a power of two "
                        f"in [2, {SPILL_MAX_PARTITIONS}] (hash "
                        f"partitioning and capacity buckets both need "
                        f"pow2 alignment)",
                    )

    # ------------------------------------------------------------------
    # sharding invariants (registered ahead of the mesh rewrite pass —
    # ROADMAP item 1 / the PR-5 contract): PartitionSpec axis consistency
    # across node boundaries, exchange arity, replicated-dim legality
    # ------------------------------------------------------------------
    def _check_sharding(self, root: P.PlanNode, mesh):
        try:
            n_dev = int(mesh.devices.size)
        except AttributeError:
            n_dev = int(getattr(mesh, "size", 0)) or 1
        if n_dev & (n_dev - 1):
            self._viol(
                "exchange-arity", None,
                f"mesh has {n_dev} devices: capacity buckets are powers "
                f"of two, so row-sharded caps and all_to_all exchange "
                f"routing (cap % n_dev == 0) can never align on a "
                f"non-power-of-two mesh",
            )
        specs: dict[int, tuple] = {}

        def spec_of(n) -> tuple:
            if n is None:
                return ()
            key = id(n)
            if key in specs:
                return specs[key]
            specs[key] = s = _spec(n)
            return s

        def _spec(n) -> tuple:
            if isinstance(n, P.Scan):
                s = table_partition_spec(n.table)
                rows = self._table_rows(n.table)
                if s and rows is not None and n_dev > 0:
                    cap = _bucket_cap(rows)
                    if cap % n_dev:
                        self._viol(
                            "replicated-dim", n,
                            f"fact table {n.table!r} (cap {cap}) is not "
                            f"divisible by the {n_dev}-device mesh; the "
                            f"catalog would silently replicate it instead "
                            f"of row-sharding",
                        )
                if not s and rows is not None:
                    width = self._scan_width(n)
                    if rows * width > REPLICATED_BYTES_CAP:
                        self._viol(
                            "replicated-dim", n,
                            f"replicated relation {n.table!r} is "
                            f"~{rows * width >> 20} MiB per device; "
                            f"replicating past "
                            f"{REPLICATED_BYTES_CAP >> 30} GiB defeats "
                            f"sharding (partition it or shrink it)",
                        )
                # the catalog RECORDED a replication fallback for this
                # fact table (Catalog._to_device couldn't row-shard it):
                # every later plan scanning it is flagged, so the one-line
                # mesh_fallback event can never stay the only evidence of
                # a fact-scale table copied to every chip
                e = (
                    getattr(self.catalog, "entries", {}).get(n.table)
                    if self.catalog is not None
                    else None
                )
                if s and e is not None and getattr(e, "mesh_fallback", False):
                    width = self._scan_width(n)
                    sized = (
                        f" (~{(rows or 0) * width >> 20} MiB per device)"
                        if rows is not None
                        else ""
                    )
                    self._viol(
                        "replicated-dim", n,
                        f"fact table {n.table!r} was silently replicated "
                        f"by the catalog mesh fallback{sized}; a "
                        f"row-shardable layout (pow2 mesh, cap divisible "
                        f"by the device count) is required to scale out",
                    )
                return s
            if isinstance(n, (P.Aggregate, P.Distinct)):
                spec_of(n.child)
                return ()  # partial results merge (psum): output replicated
            if isinstance(n, P.SetOp):
                ls, rs = spec_of(n.left), spec_of(n.right)
                if ls != rs:
                    self._viol(
                        "sharding-axis", n,
                        f"{n.op} sides carry different partition specs "
                        f"({ls or 'replicated'} vs {rs or 'replicated'}): "
                        f"a concat across mixed layouts mixes per-device "
                        f"row subsets with full copies",
                    )
                return ls
            if isinstance(n, (P.Join, P.MultiJoin)):
                child_specs = [spec_of(c) for c in n.children() if c is not None]
                sharded = [s for s in child_specs if s]
                axes = {s for s in sharded}
                if len(axes) > 1:
                    self._viol(
                        "sharding-axis", n,
                        f"join inputs are sharded over different axes "
                        f"{sorted(axes)}; an exchange can only route "
                        f"within one axis",
                    )
                return sharded[0] if sharded else ()
            out = ()
            for c in n.children():
                if c is not None:
                    s = spec_of(c)
                    if s:
                        out = s
            return out

        for v in P.walk_plan(root):
            if isinstance(v, P.PlanNode):
                spec_of(v)

    def _table_rows(self, table: str):
        if self.catalog is None:
            return None
        e = getattr(self.catalog, "entries", {}).get(table)
        if e is None:
            return None
        if getattr(e, "nrows", None) is not None:
            return int(e.nrows)
        arrow = getattr(e, "arrow", None)
        if arrow is not None:
            return int(arrow.num_rows)
        return None

    def _scan_width(self, node: P.Scan) -> int:
        sch = self._schema_of(node)
        return schema_row_bytes(sch) if sch else 9

    def _viol(self, rule: str, node, msg: str):
        where = f" [{type(node).__name__}]" if node is not None else ""
        self.violations.append(f"{rule}: {msg}{where}")

    # ------------------------------------------------------------------
    # schema resolution (memoized; None == this subtree already violated)
    # ------------------------------------------------------------------
    def _schema_of(self, node) -> dict | None:
        if node is None:
            self._viol("schema", None, "missing child plan node")
            return None
        key = id(node)
        if key in self._schemas:
            return self._schemas[key]
        # pre-insert None: a (never-expected) cycle terminates as a failure
        # instead of recursing forever
        self._schemas[key] = None
        m = getattr(self, f"_schema_{type(node).__name__.lower()}", None)
        if m is None:
            self._viol(
                "schema", node, f"unknown plan node {type(node).__name__}"
            )
            return None
        sch = m(node)
        self._schemas[key] = sch
        return sch

    def _schema_scan(self, node: P.Scan):
        if self.catalog is None:
            self._viol("schema", node, "no catalog to resolve Scan against")
            return None
        sch = self.catalog.schema(node.table)
        if sch is None:
            self._viol("schema", node, f"unknown table {node.table!r}")
            return None
        by_name = {f.name: f.dtype for f in sch}
        cols = node.columns if node.columns is not None else list(by_name)
        out = {}
        for c in cols:
            if c not in by_name:
                self._viol(
                    "schema", node,
                    f"scan of {node.table!r} selects unknown column {c!r}",
                )
                return None
            out[f"{node.alias}.{c}"] = by_name[c]
        return out

    def _schema_materializedscan(self, node: P.MaterializedScan):
        if node.name == "__dual__":
            return {}
        if node.table is None:
            self._viol(
                "schema", node,
                f"materialized scan {node.name!r} is not populated",
            )
            return None
        return {n: c.dtype for n, c in node.table.columns.items()}

    def _schema_project(self, node: P.Project):
        child = self._schema_of(node.child)
        if child is None:
            return None
        return self._project_over(node, node.items, child)

    def _project_over(self, node, items, child):
        out = {}
        for e, name in items:
            dt = self._try_expr(e, child, node, f"projection item {name!r}")
            if dt is None:
                return None
            if name in out:
                self._viol(
                    "schema", node, f"duplicate output column {name!r}"
                )
                return None
            out[name] = dt
        return out

    def _schema_filter(self, node: P.Filter):
        child = self._schema_of(node.child)
        if child is None:
            return None
        dt = self._try_expr(node.predicate, child, node, "filter predicate")
        if dt is None:
            return None
        if dt.is_string:
            self._viol(
                "schema", node,
                f"filter predicate has string dtype {dt} (not boolean)",
            )
            return None
        return child

    def _schema_join(self, node: P.Join):
        left = self._schema_of(node.left)
        right = self._schema_of(node.right)
        if left is None or right is None:
            return None
        if len(node.left_keys) != len(node.right_keys):
            self._viol(
                "join-keys", node,
                f"{len(node.left_keys)} left keys vs "
                f"{len(node.right_keys)} right keys",
            )
            return None
        ok = True
        for lk in node.left_keys:
            if self._try_expr(
                lk, left, node, "left join key (must bind to left child)"
            ) is None:
                ok = False
        for rk in node.right_keys:
            if self._try_expr(
                rk, right, node, "right join key (must bind to right child)"
            ) is None:
                ok = False
        if not ok:
            return None
        merged = dict(left)
        for n, dt in right.items():
            if n in merged:
                self._viol(
                    "schema", node,
                    f"join output has duplicate column {n!r}",
                )
                return None
            merged[n] = dt
        if node.residual is not None:
            # residuals evaluate over the pair table where both sides'
            # columns coexist (exec._apply_residual) — semi/anti included
            if self._try_expr(
                node.residual, merged, node, "join residual"
            ) is None:
                return None
        if node.kind in ("semi", "anti"):
            return dict(left)
        if node.kind == "mark":
            if not node.mark_name:
                self._viol("schema", node, "mark join without mark_name")
                return None
            if node.mark_name in left:
                self._viol(
                    "schema", node,
                    f"mark column {node.mark_name!r} collides with an "
                    f"existing left column",
                )
                return None
            out = dict(left)
            out[node.mark_name] = BOOL
            return out
        return merged

    def _schema_multijoin(self, node: P.MultiJoin):
        rels = [self._schema_of(r) for r in node.relations]
        if any(r is None for r in rels):
            return None
        merged = {}
        for sch in rels:
            for n, dt in sch.items():
                if n in merged:
                    self._viol(
                        "schema", node,
                        f"multijoin output has duplicate column {n!r}",
                    )
                    return None
                merged[n] = dt
        ok = True
        for i, j, le, re_ in node.edges:
            if not (0 <= i < len(rels) and 0 <= j < len(rels)):
                self._viol(
                    "join-keys", node,
                    f"edge endpoints ({i}, {j}) outside the "
                    f"{len(rels)}-relation list",
                )
                ok = False
                continue
            if self._try_expr(
                le, rels[i], node,
                f"multijoin edge left expr (must bind to relation {i})",
            ) is None:
                ok = False
            if self._try_expr(
                re_, rels[j], node,
                f"multijoin edge right expr (must bind to relation {j})",
            ) is None:
                ok = False
        if node.residual is not None:
            if self._try_expr(
                node.residual, merged, node, "multijoin residual"
            ) is None:
                ok = False
        return merged if ok else None

    def _schema_aggregate(self, node: P.Aggregate):
        child = self._schema_of(node.child)
        if child is None:
            return None
        self._check_blocked_union(node)
        out = {}
        for g, name in node.keys:
            dt = self._try_expr(g, child, node, f"group key {name!r}")
            if dt is None:
                return None
            if name in out:
                self._viol(
                    "schema", node, f"duplicate output column {name!r}"
                )
                return None
            out[name] = dt
        for a, name in node.aggs:
            dt = self._agg_dtype(a, child, node)
            if dt is None:
                return None
            if name in out:
                self._viol(
                    "schema", node, f"duplicate output column {name!r}"
                )
                return None
            out[name] = dt
        if node.grouping_sets is not None:
            nkeys = len(node.keys)
            for s in node.grouping_sets:
                if any(not (0 <= i < nkeys) for i in s):
                    self._viol(
                        "schema", node,
                        f"grouping set {s} indexes outside the "
                        f"{nkeys}-key list",
                    )
                    return None
        return out

    def _check_blocked_union(self, node: P.Aggregate):
        if not node.blocked_union:
            return
        if P.union_agg_shape(node) is None:
            self._viol(
                "blocked-union", node,
                "blocked_union annotation on an Aggregate whose input is "
                "not a union_all chain",
            )
        if not P.aggs_decomposable(node.aggs):
            self._viol(
                "blocked-union", node,
                "blocked_union annotation on a non-decomposable aggregate "
                "set (distinct/stddev/grouping do not merge over row "
                "windows)",
            )

    def _schema_window(self, node: P.Window):
        child = self._schema_of(node.child)
        if child is None:
            return None
        out = dict(child)
        for wf, name in node.fns:
            dt = self._window_dtype(wf, child, node)
            if dt is None:
                return None
            if name in out:
                self._viol(
                    "schema", node, f"duplicate output column {name!r}"
                )
                return None
            out[name] = dt
        return out

    def _schema_sort(self, node: P.Sort):
        child = self._schema_of(node.child)
        if child is None:
            return None
        for e, _asc, _nf in node.keys:
            if self._try_expr(e, child, node, "sort key") is None:
                return None
        return child

    def _schema_limit(self, node: P.Limit):
        child = self._schema_of(node.child)
        if child is None:
            return None
        if not isinstance(node.n, int) or node.n < 0:
            self._viol(
                "schema", node, f"LIMIT count must be a non-negative int, "
                f"got {node.n!r}"
            )
            return None
        if isinstance(node.child, P.Sort):
            # sort-key resolution over the Sort input (which the top-k
            # gather reads) was already checked by _schema_sort; the
            # cross-pass invariant left to verify is the single-consumer
            # annotation: a SHARED Sort marked _topk_safe would execute
            # top-k for one parent and starve the other (fuse's rewrite
            # must only set it when the Sort has exactly one reference)
            if (
                getattr(node.child, "_topk_safe", False)
                and self._refs.get(id(node.child), 1) > 1
            ):
                self._viol(
                    "topk", node,
                    "Sort under LIMIT is marked _topk_safe but has "
                    "multiple consumers; the top-k gather would truncate "
                    "the other parent's input",
                )
        return child

    def _schema_distinct(self, node: P.Distinct):
        return self._schema_of(node.child)

    def _schema_setop(self, node: P.SetOp):
        left = self._schema_of(node.left)
        right = self._schema_of(node.right)
        if left is None or right is None:
            return None
        if len(left) != len(right):
            self._viol(
                "setop", node,
                f"{node.op} sides have {len(left)} vs {len(right)} columns",
            )
            return None
        if list(left) != list(right):
            # the binder aligns rhs output names to the lhs via a Project;
            # a mismatch means a rewrite re-ordered or renamed one side
            self._viol(
                "setop", node,
                f"{node.op} sides have misaligned column names: "
                f"{list(left)[:4]} vs {list(right)[:4]}",
            )
            return None
        out = {}
        for (n, lt), rt in zip(left.items(), right.values()):
            if lt.is_string != rt.is_string:
                self._viol(
                    "setop", node,
                    f"{node.op} column {n!r} mixes string and non-string "
                    f"({lt} vs {rt})",
                )
                return None
            out[n] = _promote(lt, rt)
        return out

    def _schema_pipeline(self, node: P.Pipeline):
        from ..engine.fuse import _expr_fusible

        child = self._schema_of(node.child)
        self._check_donate_ok(node)
        if not node.stages and node.agg is None:
            # an agg-tail Pipeline may have an empty chain (the Aggregate
            # sat directly on its input); a plain one must not
            self._viol("pipeline", node, "Pipeline with no stages")
            return child
        if isinstance(node.child, P.Pipeline) and node.child.agg is None:
            # an agg-tail Pipeline child is legitimate (a HAVING chain's
            # pipeline sits over the fused aggregate it filters; the
            # aggregate terminates the lower chain, so the two can never
            # merge) — only plain-over-plain means a non-maximal chain
            self._viol(
                "pipeline", node,
                "Pipeline child is itself a Pipeline (chain not maximal)",
            )
        cur = child
        for s in node.stages:
            if not isinstance(s, (P.Filter, P.Project)):
                self._viol(
                    "pipeline", node,
                    f"stage {type(s).__name__} is not Filter/Project",
                )
                return None
            if s.child is not None:
                self._viol(
                    "pipeline", node,
                    f"stage {type(s).__name__} still has an attached child "
                    f"(stages must be detached copies)",
                )
                return None
            if self._refs.get(id(s), 1) > 1:
                self._viol(
                    "pipeline", node,
                    f"stage {type(s).__name__} is referenced elsewhere in "
                    f"the plan (Pipeline wraps a shared node, defeating "
                    f"by-identity result reuse)",
                )
                return None
            exprs = (
                [s.predicate]
                if isinstance(s, P.Filter)
                else [e for e, _ in s.items]
            )
            for e in exprs:
                if not _expr_fusible(e):
                    self._viol(
                        "pipeline", node,
                        f"stage expression {e} is not fusible (subquery/"
                        f"aggregate/window must never enter a Pipeline)",
                    )
            if cur is None:
                continue
            if isinstance(s, P.Filter):
                dt = self._try_expr(
                    s.predicate, cur, node, "pipeline filter predicate"
                )
                if dt is None:
                    cur = None
            else:
                cur = self._project_over(node, s.items, cur)
        if node.agg is not None:
            return self._check_pipeline_agg(node, cur)
        return cur

    def _check_donate_ok(self, node: P.Pipeline):
        """`donate_ok` is fuse's clearance to hand the child's buffers to a
        donating executable — provably wrong whenever another plan node (or
        a cross-statement cache) can still observe them. Mirrors
        fuse._donate_ok_child; a rewrite that sets the flag outside these
        bounds corrupts live memory, so the verifier re-derives it."""
        if not node.donate_ok:
            return
        from ..engine.fuse import _NO_DONATE_CHILD

        if self._refs.get(id(node.child), 1) > 1:
            self._viol(
                "donate", node,
                "donate_ok set but the pipeline child has multiple "
                "consumers; donating its buffers would invalidate the "
                "other consumer's input",
            )
        elif isinstance(node.child, _NO_DONATE_CHILD) or (
            isinstance(node.child, P.Pipeline)
            and node.child.agg is not None
        ):
            self._viol(
                "donate", node,
                f"donate_ok set on a {type(node.child).__name__} child "
                f"whose result a cache or base table retains beyond this "
                f"call",
            )

    def _check_pipeline_agg(self, node: P.Pipeline, cur):
        """The fused aggregate tail: detached, unshared, plain-shaped,
        fully decomposable — the exact eligibility fuse._agg_fusible
        proved at rewrite time, re-derived here so a later pass that
        mutates the plan cannot leave a stale (now-wrong) fusion."""
        from ..engine.fuse import _expr_fusible

        agg = node.agg
        if agg.child is not None:
            self._viol(
                "pipeline-agg", node,
                "aggregate tail still has an attached child (must be a "
                "detached copy)",
            )
            return None
        if self._refs.get(id(agg), 1) > 1:
            self._viol(
                "pipeline-agg", node,
                "aggregate tail is referenced elsewhere in the plan "
                "(Pipeline wraps a shared Aggregate)",
            )
            return None
        if agg.grouping_sets is not None or agg.blocked_union:
            self._viol(
                "pipeline-agg", node,
                "aggregate tail must be plain-shaped (no grouping sets — "
                "the rollup cascade re-aggregates across levels; no "
                "blocked_union — the windowed executor owns those)",
            )
            return None
        if not P.aggs_decomposable(agg.aggs):
            self._viol(
                "pipeline-agg", node,
                "non-decomposable aggregate set fused into a Pipeline "
                "tail (distinct/stddev/grouping cannot run as a direct "
                "partial-aggregate scatter)",
            )
            return None
        for e, name in agg.keys:
            if not _expr_fusible(e):
                self._viol(
                    "pipeline-agg", node,
                    f"group key {name!r} is not traceable inside one "
                    f"jitted dispatch",
                )
                return None
        for a, name in agg.aggs:
            if a.arg is not None and not _expr_fusible(a.arg):
                self._viol(
                    "pipeline-agg", node,
                    f"aggregate argument of {name!r} is not traceable "
                    f"inside one jitted dispatch",
                )
                return None
        if cur is None:
            return None
        out = {}
        for g, name in agg.keys:
            dt = self._try_expr(g, cur, node, f"group key {name!r}")
            if dt is None:
                return None
            if name in out:
                self._viol(
                    "schema", node, f"duplicate output column {name!r}"
                )
                return None
            out[name] = dt
        for a, name in agg.aggs:
            dt = self._agg_dtype(a, cur, agg)
            if dt is None:
                return None
            if name in out:
                self._viol(
                    "schema", node, f"duplicate output column {name!r}"
                )
                return None
            out[name] = dt
        return out

    # ------------------------------------------------------------------
    # aggregate / window dtype rules (mirror exec._eval_agg/_eval_window)
    # ------------------------------------------------------------------
    def _agg_dtype(self, a: E.Agg, child, node):
        fn = a.fn
        if fn == "grouping":
            # the arg is the raw key expr or the key's output Col (the
            # executor matches either form against the node's key items)
            if a.arg is not None:
                key_cols = {E.Col(kn) for _, kn in node.keys}
                key_exprs = [ke for ke, _ in node.keys]
                if a.arg not in key_cols and not any(
                    a.arg == ke for ke in key_exprs
                ):
                    if self._try_expr(
                        a.arg, child, node, "grouping() argument"
                    ) is None:
                        return None
            return INT32
        if fn == "count":
            if a.arg is not None:
                if self._try_expr(a.arg, child, node, "count() arg") is None:
                    return None
            return INT64
        if a.arg is None:
            self._viol("schema", node, f"aggregate {fn} needs an argument")
            return None
        d = self._try_expr(a.arg, child, node, f"{fn}() argument")
        if d is None:
            return None
        if fn == "sum":
            if d.is_string:
                self._viol("schema", node, "sum over a string column")
                return None
            return INT64 if d.kind in ("int32", "bool") else d
        if fn in ("min", "max"):
            return d
        if fn == "avg":
            if d.is_string:
                self._viol("schema", node, "avg over a string column")
                return None
            return FLOAT64
        if fn in ("stddev_samp", "var_samp"):
            if d.is_string:
                self._viol("schema", node, f"{fn} over a string column")
                return None
            return FLOAT64
        self._viol("schema", node, f"unknown aggregate function {fn!r}")
        return None

    def _window_dtype(self, wf: E.WindowFn, child, node):
        for pe in wf.partition_by:
            if self._try_expr(pe, child, node, "window partition key") is None:
                return None
        for oe, _asc in wf.order_by:
            if self._try_expr(oe, child, node, "window order key") is None:
                return None
        fn = wf.fn
        if fn in ("rank", "dense_rank", "row_number"):
            return INT64
        if fn == "count":
            if wf.arg is not None:
                if self._try_expr(wf.arg, child, node, "window arg") is None:
                    return None
            return INT64
        if fn in ("sum", "avg", "min", "max"):
            if wf.arg is None:
                self._viol(
                    "schema", node, f"window {fn} needs an argument"
                )
                return None
            d = self._try_expr(wf.arg, child, node, f"window {fn} arg")
            if d is None:
                return None
            if fn == "avg":
                return FLOAT64
            if fn == "sum":
                return INT64 if d.kind in ("int32", "bool") else d
            return d
        self._viol("schema", node, f"unknown window function {fn!r}")
        return None

    # ------------------------------------------------------------------
    # scalar expression dtype inference
    # ------------------------------------------------------------------
    def _try_expr(self, e, sch, node, what) -> DType | None:
        try:
            return self._expr_dtype(e, sch)
        except _Unres as exc:
            self._viol("schema", node, f"{what}: {exc}")
            return None

    def _expr_dtype(self, e, sch) -> DType:
        if isinstance(e, E.Col):
            key = f"{e.table}.{e.name}" if e.table else e.name
            if key in sch:
                return sch[key]
            if e.name in sch:  # bare-name fallback, mirrors _eval_col
                return sch[e.name]
            have = list(sch)[:6]
            raise _Unres(f"unresolved column {key!r} (have {have}...)")
        if isinstance(e, E.Lit):
            return e.dtype or _lit_dtype(e.value)
        if isinstance(e, E.Interval):
            return INT32
        if isinstance(e, E.BinOp):
            return self._binop_dtype(e, sch)
        if isinstance(e, E.UnaryOp):
            d = self._expr_dtype(e.operand, sch)
            if e.op == "neg":
                return d
            if e.op in ("not", "isnull", "isnotnull"):
                return BOOL
            raise _Unres(f"unknown unary op {e.op!r}")
        if isinstance(e, E.Between):
            for c in (e.operand, e.low, e.high):
                self._expr_dtype(c, sch)
            return BOOL
        if isinstance(e, E.InList):
            self._expr_dtype(e.operand, sch)
            return BOOL
        if isinstance(e, E.Like):
            d = self._expr_dtype(e.operand, sch)
            if not d.is_string:
                raise _Unres(f"LIKE over non-string dtype {d}")
            return BOOL
        if isinstance(e, E.Case):
            vals = []
            for c, v in e.branches:
                self._expr_dtype(c, sch)
                vals.append(self._expr_dtype(v, sch))
            if e.default is not None:
                vals.append(self._expr_dtype(e.default, sch))
            out = vals[0]
            for d in vals[1:]:
                out = _promote(out, d)
            return out
        if isinstance(e, E.Cast):
            self._expr_dtype(e.operand, sch)
            return e.target
        if isinstance(e, E.Func):
            return self._func_dtype(e, sch)
        if isinstance(e, E.ScalarSubquery):
            sub = self._schema_of(e.plan)
            if sub is None:
                raise _Unres("scalar subquery plan failed to resolve")
            if e.out_name not in sub:
                raise _Unres(
                    f"scalar subquery output {e.out_name!r} missing from "
                    f"its plan's schema {list(sub)[:4]}"
                )
            return sub[e.out_name]
        if isinstance(e, E.SubqueryExpr):
            raise _Unres(
                "unplanned SubqueryExpr survived binding (must be lowered "
                "to a join or ScalarSubquery)"
            )
        if isinstance(e, E.Agg):
            raise _Unres(
                f"aggregate {e.fn} in scalar context (must be rewritten to "
                f"an Aggregate output column)"
            )
        if isinstance(e, E.WindowFn):
            raise _Unres(
                f"window function {e.fn} in scalar context (must be "
                f"extracted to a Window node)"
            )
        raise _Unres(f"unknown expression {type(e).__name__}")

    def _binop_dtype(self, e: E.BinOp, sch) -> DType:
        op = e.op
        a = self._expr_dtype(e.left, sch)
        b = self._expr_dtype(e.right, sch)
        if op in ("and", "or"):
            return BOOL
        if op in ("=", "<>", "!=", "<", "<=", ">", ">="):
            return BOOL
        if op == "||":
            if not (a.is_string and b.is_string):
                raise _Unres(f"|| over non-string dtypes {a}, {b}")
            return STRING
        if op in ("+", "-", "*", "/"):
            if a.is_string or b.is_string:
                raise _Unres(f"arithmetic {op} over string dtype")
            if op in ("+", "-") and a.kind == "date" and b.is_integer:
                return DATE
            if op in ("+", "-") and b.kind == "date" and a.is_integer:
                return DATE
            if op == "-" and a.kind == "date" and b.kind == "date":
                return INT32
            if op == "/":
                return FLOAT64
            if op == "*" and (a.is_decimal or b.is_decimal):
                if a.kind == "float64" or b.kind == "float64":
                    return FLOAT64
                s1 = a.scale if a.is_decimal else 0
                s2 = b.scale if b.is_decimal else 0
                return DType("decimal", 38, s1 + s2)
            # +/-/* promotion, mirrors Evaluator._numeric_pair
            if a.is_decimal and b.is_decimal:
                return DType("decimal", 38, max(a.scale, b.scale))
            if a.is_decimal:
                return FLOAT64 if b.kind == "float64" else a
            if b.is_decimal:
                return FLOAT64 if a.kind == "float64" else b
            if a.kind == "float64" or b.kind == "float64":
                return FLOAT64
            if a.kind == "int64" or b.kind == "int64":
                return INT64
            return INT32
        raise _Unres(f"unknown binary op {op!r}")

    def _func_dtype(self, e: E.Func, sch) -> DType:
        name = e.name.lower()
        args = [self._expr_dtype(a, sch) for a in e.args]
        if name == "coalesce":
            # ifnull/nvl deliberately NOT accepted: the evaluator does not
            # implement them (Evaluator._eval_func), and a plan that
            # verifies clean must not crash at execution
            out = args[0]
            for d in args[1:]:
                out = _promote(out, d)
            return out
        if name == "abs":
            return args[0]
        if name == "round":
            return args[0] if args[0].is_decimal else FLOAT64
        if name in _STRING_FUNCS:
            if not args[0].is_string:
                raise _Unres(f"{name} over non-string dtype {args[0]}")
            return STRING
        if name in ("year", "month", "day"):
            return INT32
        if name in ("date_add", "date_sub"):
            return DATE
        if name == "nullif":
            return args[0]
        if name == "concat":
            return STRING
        raise _Unres(f"unknown scalar function {e.name!r}")

    # ------------------------------------------------------------------
    # binder LEFT->INNER promotion cross-check
    # ------------------------------------------------------------------
    def _check_promotions(self, promotions):
        for rec in promotions or ():
            conj = rec.get("conjunct")
            refs = rec.get("refs")
            if conj is None or not _null_rejecting_shape(conj):
                self._viol(
                    "left-inner-promotion", None,
                    f"LEFT JOIN promoted to INNER from a conjunct that is "
                    f"NOT null-rejecting: {conj} (would drop the outer "
                    f"join's null-extended rows incorrectly)",
                )
            if not refs:
                self._viol(
                    "left-inner-promotion", None,
                    f"LEFT JOIN promotion recorded without any reference "
                    f"into the promoted relation: {conj}",
                )


def verify_plan(
    plan: P.PlanNode,
    catalog=None,
    stage: str = "final",
    promotions=(),
    tracer=None,
    mesh=None,
) -> None:
    """Run the PlanVerifier; emit a `plan_verify` trace event; raise
    PlanVerifyError (classified `planner` by faults.classify) on any
    violation. With a `mesh`, the sharding invariant family (axis
    consistency, exchange arity, replicated-dim legality) runs too."""
    violations = PlanVerifier(catalog).verify(plan, promotions, mesh=mesh)
    if tracer is not None:
        ev = {"stage": stage, "ok": not violations}
        if violations:
            ev["violations"] = len(violations)
            ev["first"] = violations[0][:200]
        tracer.emit("plan_verify", **ev)
    if violations:
        raise PlanVerifyError(stage, violations)
