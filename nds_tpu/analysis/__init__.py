"""Static analysis: plan-IR verification, plan budgeting + engine lint.

Three complementary gates over the engine's correctness surface:

* `verifier` — a PlanVerifier that re-checks structural invariants of the
  logical plan after binding and after each rewrite pass (schema
  resolvability with stable dtypes, Pipeline chain shape, blocked-union
  annotation soundness, join-key scoping, LEFT->INNER promotion evidence,
  physical-annotation placement, and — with a mesh — the sharding
  invariant family), the engine's counterpart of Catalyst's re-run
  analyzer. Gated by conf `engine.verify_plans` / env NDS_VERIFY_PLANS
  (off | final | all).
* `budget` — a static cost/memory analyzer that derives per-node
  cardinality bounds and a peak-HBM byte model mirroring the executor's
  materialization, and folds them into a load-bearing plan-time verdict:
  direct | blocked(window_rows) | over | reject(admission). Gated by conf
  `engine.plan_budget` / env NDS_PLAN_BUDGET (off | warn | on).
* `lint` — an AST lint over nds_tpu/ codifying the repo's historical bug
  classes as rules (cross-stream module globals, epoch durations, torn
  report writes, host syncs in traced regions, hot-path imports, trace
  event schema drift, undocumented/unread conf knobs, unguarded session-
  cache mutations).

All run in CI (ci/tier1-check): `tools/plan_verify_corpus.py` statically
checks ALL 99 TPC-DS query templates through the verifier AND calibrates
the budgeter at the SF1/SF10 catalogs, and the lint must be clean over
the package.
"""
