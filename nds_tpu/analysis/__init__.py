"""Static analysis: plan-IR verification + engine lint.

Two complementary gates over the engine's correctness surface:

* `verifier` — a PlanVerifier that re-checks structural invariants of the
  logical plan after binding and after each rewrite pass (schema
  resolvability with stable dtypes, Pipeline chain shape, blocked-union
  annotation soundness, join-key scoping, LEFT->INNER promotion evidence),
  the engine's counterpart of Catalyst's re-run analyzer. Gated by conf
  `engine.verify_plans` / env NDS_VERIFY_PLANS (off | final | all).
* `lint` — an AST lint over nds_tpu/ codifying the repo's historical bug
  classes as rules (cross-stream module globals, epoch durations, torn
  report writes, host syncs in traced regions, hot-path imports, trace
  event schema drift).

Both run in CI (ci/tier1-check): `tools/plan_verify_corpus.py` statically
checks ALL 99 TPC-DS query templates through the verifier, and the lint
must be clean over the package.
"""
