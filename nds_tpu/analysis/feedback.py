"""Estimate-vs-actual cardinality feedback (the measure->record->consume
loop that makes budgeter error a measured, shrinking number).

The budgeter (analysis/budget.py) plans from static heuristics: table
stats, FK shapes, a conjunction selectivity floor. The executor measures
everything the model guessed — op_span actual rows/bytes, per-device
exchange skew — and until now threw the measurements away. This module
is the persistent middle: a `FeedbackStore` (the PromotionStore/aotcache
persistence pattern — atomic pid-staged writes, checksum-verified loads,
corrupt entries quarantine as misses, an LRU byte budget derived via
`budget.derive_share_bytes`, dead-pid temp sweeps) keyed by
`(node_fp, scale_tag)`:

  node_fp    sha256 of the node's structural fingerprint
             (engine/plan.py:fingerprint — operator shape, input
             relations, pushed predicates) — stable across processes
  scale_tag  the data the fingerprint ran against: the declared budget
             SF plus each scanned table's lake snapshot version (or
             registered row count). A lake-version advance changes the
             tag, so stale cardinalities invalidate into clean misses.

Modes (`engine.plan_feedback` / NDS_PLAN_FEEDBACK, default `record`):

  off      no annotations, no recording, no lookups — the static model,
           byte-identical to the pre-feedback engine
  record   plan nodes are annotated (`node_fp`, `est_rows`,
           `est_live_bytes`), the executor records actuals + exchange
           skew into the store; estimates stay static
  on       record, PLUS a recorded actual overrides the static per-node
           row estimate (clamped: never below the observed maximum) so
           verdicts/windows/spill-partition counts re-derive from
           measurements, and the exchange layer seeds hot-key capacity
           from recorded skew instead of rediscovering it via
           overflow-retry doubling

The store directory rides the AOT cache dir by default
(`<aot_cache_dir>/feedback`), so `cache warm --fleet`-style shared-dir
wiring shares learned cardinalities across processes and serve replicas
exactly like compiled executables; `engine.feedback_dir` /
NDS_FEEDBACK_DIR override, ""/"0" disables.
"""

import hashlib
import json
import math
import os
import threading
import time

from ..engine import plan as P
from ..engine.lockdebug import make_lock

#: plan_feedback modes (parallel to budget.MODES)
FEEDBACK_MODES = ("off", "record", "on")

#: store entry format version: bump on layout change so old entries read
#: as clean key mismatches (misses), never as corrupt data
FORMAT_VERSION = 1

_ENTRY_PREFIX = "fb-"
_ENTRY_SUFFIX = ".json"

#: auto byte budget for the store dir: 1/64 of the filesystem's free
#: bytes, clamped to [4 MiB, 1 GiB] — entries are ~300 B JSON documents,
#: so even the floor holds ~10k learned plan nodes
_BUDGET_FRACTION = 64
_BUDGET_LO = 4 << 20
_BUDGET_HI = 1 << 30

#: bounded in-process |log(est/actual)| sample reservoir (bench/statusz
#: medians); oldest samples age out ring-style
_ERR_SAMPLES_CAP = 4096

#: log2-bucketed actual-row histogram width kept per entry
_HIST_CAP = 24


def resolve_feedback_mode(conf=None) -> str:
    v = None
    if conf:
        v = conf.get("engine.plan_feedback")
    v = v or os.environ.get("NDS_PLAN_FEEDBACK") or "record"
    v = str(v).lower()
    if v not in FEEDBACK_MODES:
        raise ValueError(
            f"engine.plan_feedback must be one of {FEEDBACK_MODES}, "
            f"got {v!r}"
        )
    return v


def resolve_feedback_dir(conf=None):
    """The feedback store directory, or None when disabled: explicit conf
    / env win (""/"0" disables); otherwise a `feedback/` namespace under
    the resolved AOT cache dir — one shared dir therefore shares BOTH
    compiled executables and learned cardinalities across processes and
    serve replicas (the `--aot_cache_dir` fleet wiring), and disabling
    the AOT dir disables feedback with it."""
    v = None
    if conf:
        v = conf.get("engine.feedback_dir")
    if v is None:
        v = os.environ.get("NDS_FEEDBACK_DIR")
    if v is not None:
        v = str(v)
        if v in ("", "0"):
            return None
        return os.path.expanduser(v)
    from ..engine.aotcache import resolve_aot_cache_dir

    base = resolve_aot_cache_dir(conf)
    if not base:
        return None
    return os.path.join(base, "feedback")


def resolve_feedback_bytes(conf=None, dirpath=None) -> int:
    """Store byte budget: explicit conf/env, else an `auto` share of the
    store filesystem's free bytes through the one derivation every auto
    budget in the engine uses (budget.derive_share_bytes)."""
    v = None
    if conf:
        v = conf.get("engine.feedback_bytes")
    if v is None:
        v = os.environ.get("NDS_FEEDBACK_BYTES")
    if v is not None and str(v).lower() not in ("", "auto"):
        return int(v)
    from .budget import derive_share_bytes

    total = 0
    probe = dirpath or "."
    while probe:
        try:
            import shutil

            total = shutil.disk_usage(probe).free
            break
        except OSError:
            parent = os.path.dirname(probe)
            if parent == probe:
                break
            probe = parent
    if not total:
        return _BUDGET_LO
    return derive_share_bytes(total, _BUDGET_FRACTION, _BUDGET_LO,
                              _BUDGET_HI)


# ---------------------------------------------------------------------------
# keys: structural fingerprint x data scale
# ---------------------------------------------------------------------------


def plan_scale_tag(plan, session) -> str:
    """The data-scale half of a feedback key: the declared budget SF plus
    one `table@version` component per scanned relation — the lake
    snapshot version when the scan is pinned to one, else the registered
    row count when the catalog knows it cheaply. Advancing a lake version
    (or re-registering a table with different data) changes the tag, so
    every learned cardinality under the old tag becomes a clean miss
    instead of a stale override."""
    sf = None
    entries = {}
    if session is not None:
        sf = session.conf.get("engine.plan_budget_sf")
        entries = getattr(getattr(session, "catalog", None), "entries", {})
    parts = [f"sf={sf}" if sf else "sf=?"]
    seen = set()
    for v in P.walk_plan(plan):
        if not isinstance(v, P.Scan) or v.table in seen:
            continue
        seen.add(v.table)
        ver = getattr(v, "lake_version", None)
        if ver is None:
            e = entries.get(v.table)
            arrow = getattr(e, "arrow", None)
            ver = arrow.num_rows if arrow is not None else "?"
        parts.append(f"{v.table}@{ver}")
    parts.sort()
    return ";".join(parts)


def node_fp(structural_fp: str, scale_tag: str) -> str:
    """One store key: content fingerprint of (operator subtree, data
    scale) — 40 hex chars, the same truncation the aot cache uses."""
    h = hashlib.sha256()
    h.update(str(structural_fp).encode("utf-8"))
    h.update(b"|")
    h.update(str(scale_tag).encode("utf-8"))
    return h.hexdigest()[:40]


def _mscan_tainted(plan) -> set:
    """Ids of nodes whose subtree contains a MaterializedScan: those
    fingerprints embed a per-process serial (deliberately — the scanned
    table is not reconstructible), so they can never hit across
    processes and would only pollute the store with unique keys. Plans
    without one (the overwhelmingly common case) pay a single walk."""
    if not any(
        isinstance(v, P.MaterializedScan) for v in P.walk_plan(plan)
    ):
        return set()
    out = set()
    for v in P.walk_plan(plan):
        if isinstance(v, P.PlanNode) and any(
            isinstance(w, P.MaterializedScan) for w in P.walk_plan(v)
        ):
            out.add(id(v))
    return out


def plan_node_fps(plan, session, scale_tag=None) -> dict:
    """{id(node): store key} for every feedback-eligible plan node (plus
    scalar-subquery plans — the budgeter models them too). Computed once
    per statement at plan time; budget_plan annotates the winners onto
    the nodes so the executor never recomputes a fingerprint."""
    if scale_tag is None:
        scale_tag = plan_scale_tag(plan, session)
    tainted = _mscan_tainted(plan)
    out = {}
    for v in P.walk_plan(plan):
        if isinstance(v, P.PlanNode) and id(v) not in tainted:
            out[id(v)] = node_fp(P.fingerprint(v), scale_tag)
    return out


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------


def _entry_name(fp: str) -> str:
    return f"{_ENTRY_PREFIX}{fp}{_ENTRY_SUFFIX}"


def _merge_component(dst: dict, rows) -> dict:
    """Fold one observation into a {n,last,min,max,hist} component."""
    rows = int(rows)
    dst["n"] = int(dst.get("n", 0)) + 1
    dst["last"] = rows
    dst["min"] = rows if dst.get("min") is None else min(dst["min"], rows)
    dst["max"] = rows if dst.get("max") is None else max(dst["max"], rows)
    hist = dst.setdefault("hist", {})
    bucket = str(min(rows.bit_length(), _HIST_CAP))
    hist[bucket] = int(hist.get(bucket, 0)) + 1
    return dst


class FeedbackStore:
    """Persistent (node_fp, scale)-keyed actual-cardinality records.

    One tiny JSON document per key under `dirpath`, written with the
    aot-cache discipline: stage to a `.tmp-<pid>-<rand>` sibling, fsync,
    `os.replace` into place (readers see whole documents or nothing),
    re-verify the FULL embedded key and a payload checksum on load — a
    filename-hash collision is a clean miss, a corrupt document is
    quarantined (renamed aside, once) and treated as a miss. Mutations
    buffer in `_pending` and land on `flush()` (one merge+write per
    touched key per statement, not per recorded node), after which the
    LRU byte budget is re-enforced by mtime — lookups refresh an entry's
    mtime so hot plan nodes survive eviction.

    In-process state is guarded by an internal lock; session-level call
    sites additionally hold `Session.cache_lock` (the cache-lock-
    discipline lint enforces it for `feedback_store`, as for every other
    session cache)."""

    def __init__(self, dirpath: str, budget_bytes: int):
        self.dir = dirpath
        self.budget = int(budget_bytes)
        self._lock = make_lock("FeedbackStore._lock")
        self._mem = {}  # fp -> record dict (None = known miss)  # nds-guarded-by: _lock
        self._pending = {}  # fp -> record delta awaiting flush  # nds-guarded-by: _lock
        self._disabled = False  # first write error disables stores  # nds-guarded-by: _lock
        self._err_samples = []  # |log(est/actual)| ring  # nds-guarded-by: _lock
        self.stats = {  # nds-guarded-by: _lock
            "lookups": 0, "hits": 0, "misses": 0, "records": 0,
            "skew_records": 0, "flushes": 0, "stores": 0, "evictions": 0,
            "quarantined": 0, "overrides": 0,
        }

    # -- reads ----------------------------------------------------------
    def lookup(self, fp: str):
        """The record for one key, or None. First disk read per key is
        cached (hits AND misses) for the life of the session; a hit
        refreshes the entry's mtime (LRU recency)."""
        with self._lock:
            self.stats["lookups"] += 1
            if fp in self._mem:
                rec = self._mem[fp]
                self.stats["hits" if rec is not None else "misses"] += 1
                return dict(rec) if rec is not None else None
            rec = self._load_locked(fp)
            self._mem[fp] = rec
            self.stats["hits" if rec is not None else "misses"] += 1
            return dict(rec) if rec is not None else None

    def _load_locked(self, fp: str):
        path = os.path.join(self.dir, _entry_name(fp))
        try:
            with open(path, "rb") as f:
                doc = json.loads(f.read().decode("utf-8"))
        except FileNotFoundError:
            return None
        except (OSError, ValueError, UnicodeDecodeError):
            self._quarantine_locked(path)
            return None
        body = doc.get("body") if isinstance(doc, dict) else None
        key = doc.get("key") if isinstance(doc, dict) else None
        if not isinstance(body, dict) or not isinstance(key, dict):
            self._quarantine_locked(path)
            return None
        want = hashlib.sha256(
            json.dumps(body, sort_keys=True).encode("utf-8")
        ).hexdigest()
        if doc.get("sha256") != want:
            self._quarantine_locked(path)
            return None
        if key != self._key(fp):
            # full-key mismatch after a filename-hash collision or a
            # format-version bump: valid foreign data, a clean miss
            return None
        try:
            os.utime(path, None)
        except OSError:
            pass
        return body

    def _key(self, fp: str) -> dict:
        return {"node_fp": fp, "v": FORMAT_VERSION}

    # -- buffered writes ------------------------------------------------
    def record(self, fp: str, rows=None, nbytes=None, est_rows=None):
        """Fold one executed node's actuals into the pending delta for
        `fp`. Returns the |log(est/actual)| error sample when the static
        estimate was annotated (the caller's plan_feedback event carries
        it), else None."""
        err = None
        if est_rows is not None and rows is not None:
            err = abs(math.log(max(int(est_rows), 1))
                      - math.log(max(int(rows), 1)))
        with self._lock:
            self.stats["records"] += 1
            rec = self._pending.setdefault(fp, {})
            if rows is not None:
                _merge_component(rec.setdefault("rows", {}), rows)
            if nbytes is not None:
                _merge_component(rec.setdefault("bytes", {}), nbytes)
            if err is not None:
                self._err_samples.append(err)
                if len(self._err_samples) > _ERR_SAMPLES_CAP:
                    del self._err_samples[: _ERR_SAMPLES_CAP // 4]
        return err

    def record_skew(self, fp: str, skew: float, retries: int = 0):
        """Fold one exchange's measured received-row skew (max/mean) and
        its overflow-retry count into the pending delta for `fp` — the
        seed the next execution's capacity guess consumes."""
        with self._lock:
            self.stats["skew_records"] += 1
            rec = self._pending.setdefault(fp, {})
            sk = rec.setdefault("skew", {})
            sk["n"] = int(sk.get("n", 0)) + 1
            sk["last"] = round(float(skew), 3)
            sk["max"] = round(max(float(sk.get("max", 0.0)), float(skew)), 3)
            sk["retries"] = max(int(sk.get("retries", 0)), int(retries))

    def flush(self) -> int:
        """Merge every pending delta with its on-disk record and commit
        (tempfile + rename per key), then re-enforce the byte budget.
        Returns the number of keys written; write errors disable further
        stores for this process (the cache must never take down a
        query)."""
        with self._lock:
            pending, self._pending = self._pending, {}
            if not pending or self._disabled:
                return 0
            self.stats["flushes"] += 1
            written = []
            for fp, delta in pending.items():
                base = self._mem.get(fp)
                if base is None:
                    base = self._load_locked(fp) or {}
                merged = self._merge(dict(base), delta)
                merged["updated"] = int(time.time())
                if self._write_locked(fp, merged):
                    self._mem[fp] = merged
                    written.append(_entry_name(fp))
                    self.stats["stores"] += 1
                if self._disabled:
                    break
            if written:
                self._enforce_budget_locked(keep=set(written))
            return len(written)

    @staticmethod
    def _merge(base: dict, delta: dict) -> dict:
        for comp in ("rows", "bytes"):
            d = delta.get(comp)
            if not d:
                continue
            b = base.setdefault(comp, {})
            b["n"] = int(b.get("n", 0)) + int(d.get("n", 0))
            b["last"] = d.get("last", b.get("last"))
            for agg, fold in (("min", min), ("max", max)):
                vals = [x for x in (b.get(agg), d.get(agg)) if x is not None]
                if vals:
                    b[agg] = fold(vals)
            hist = b.setdefault("hist", {})
            for k, n in (d.get("hist") or {}).items():
                hist[k] = int(hist.get(k, 0)) + int(n)
        d = delta.get("skew")
        if d:
            b = base.setdefault("skew", {})
            b["n"] = int(b.get("n", 0)) + int(d.get("n", 0))
            b["last"] = d.get("last", b.get("last"))
            b["max"] = max(float(b.get("max", 0.0)), float(d.get("max", 0.0)))
            b["retries"] = max(int(b.get("retries", 0)),
                               int(d.get("retries", 0)))
        return base

    def _write_locked(self, fp: str, body: dict) -> bool:
        doc = {
            "key": self._key(fp),
            "body": body,
            "sha256": hashlib.sha256(
                json.dumps(body, sort_keys=True).encode("utf-8")
            ).hexdigest(),
        }
        dest = os.path.join(self.dir, _entry_name(fp))
        tmp = (f"{dest}.tmp-{os.getpid()}-"
               f"{hashlib.sha256(os.urandom(8)).hexdigest()[:6]}")
        try:
            os.makedirs(self.dir, exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(json.dumps(doc, sort_keys=True).encode("utf-8"))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, dest)
            return True
        except OSError as exc:
            self._disabled = True
            import warnings

            warnings.warn(
                f"feedback store disabled: cannot write {dest!r}: {exc}"
            )
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return False

    def _quarantine_locked(self, path: str):
        self.stats["quarantined"] += 1
        dest = os.path.join(
            os.path.dirname(path),
            f"quarantine-{os.path.basename(path)}.{os.getpid()}",
        )
        try:
            os.replace(path, dest)
        except OSError:
            pass

    # -- maintenance ----------------------------------------------------
    def _entries(self):
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        out = []
        for n in names:
            if not (n.startswith(_ENTRY_PREFIX)
                    and n.endswith(_ENTRY_SUFFIX)):
                continue
            path = os.path.join(self.dir, n)
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append((st.st_mtime, st.st_size, n, path))
        return out

    def _enforce_budget_locked(self, keep=frozenset()):
        entries = self._entries()
        total = sum(e[1] for e in entries)
        if total <= self.budget:
            return
        for mtime, size, name, path in sorted(entries):
            if total <= self.budget:
                break
            if name in keep:
                continue
            try:
                os.unlink(path)
            except OSError:
                continue
            total -= size
            self.stats["evictions"] += 1

    def usage(self):
        entries = self._entries()
        return len(entries), sum(e[1] for e in entries)

    def vacuum(self, drop_all: bool = False) -> int:
        """Sweep dead-pid temps + quarantined entries and re-enforce the
        budget; `drop_all` also forgets every learned cardinality (the
        operator reset after a data regeneration). Returns files
        removed."""
        # aotcache.sweep_orphans filters on ITS entry prefixes, so the
        # fb-* temps need their own dead-pid sweep (same liveness rule:
        # a temp whose owning pid is alive is an in-flight store)
        from ..engine.aotcache import _pid_alive

        removed = 0
        try:
            names = os.listdir(self.dir)
        except OSError:
            names = []
        for n in list(names):
            if not (n.startswith(_ENTRY_PREFIX) and ".tmp-" in n):
                continue
            pid_s = n.split(".tmp-", 1)[1].split("-", 1)[0]
            try:
                pid = int(pid_s)
            except ValueError:
                continue
            if pid == os.getpid() or _pid_alive(pid):
                continue
            try:
                os.unlink(os.path.join(self.dir, n))
                removed += 1
                names.remove(n)
            except OSError:
                pass
        for n in names:
            drop = n.startswith("quarantine-") or (
                drop_all
                and n.startswith(_ENTRY_PREFIX)
                and n.endswith(_ENTRY_SUFFIX)
            )
            if not drop:
                continue
            try:
                os.unlink(os.path.join(self.dir, n))
                removed += 1
            except OSError:
                continue
        with self._lock:
            if drop_all:
                self._mem.clear()
                self._pending.clear()
            before = self.stats["evictions"]
            self._enforce_budget_locked()
            removed += self.stats["evictions"] - before
        return removed

    # -- in-process accuracy accounting (bench/statusz) -----------------
    def err_stats(self):
        """(median, max, n) over the bounded |log(est/actual)| sample
        reservoir — the bench OUT line's `budget_err_median` and the
        statusz accuracy block read this without touching disk."""
        with self._lock:
            s = sorted(self._err_samples)
        if not s:
            return None, None, 0
        mid = len(s) // 2
        med = s[mid] if len(s) % 2 else (s[mid - 1] + s[mid]) / 2.0
        return med, s[-1], len(s)

    def hit_rate(self):
        """lookup hit fraction, or None before any lookup."""
        n = self.stats["lookups"]
        return (self.stats["hits"] / n) if n else None
