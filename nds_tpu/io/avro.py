"""Minimal Avro Object Container File writer/reader for Arrow tables.

The reference's transcode offers avro output through an external Spark
plugin jar (reference: nds/nds_transcode.py:241-249 `--output_format avro`,
README note that it needs `spark-avro`). This environment has no avro
library, so the subset of the 1.11 spec the NDS schemas need is implemented
directly:

  * container layout: magic `Obj\\x01`, metadata map (schema JSON + codec
    null), 16-byte sync marker, then blocks of (record count, byte size,
    records, sync)
  * encodings: zigzag-varint longs/ints, IEEE-754 LE doubles, length-prefixed
    utf8 strings/bytes, union index for nullable fields
  * logical types: date as int (days since epoch), decimal as big-endian
    two's-complement bytes with precision/scale in the schema

Reader included so round-trips are testable without external tooling.
"""

from __future__ import annotations

import io
import json
import os
import struct

import pyarrow as pa

MAGIC = b"Obj\x01"
SYNC = bytes(range(16))  # deterministic marker: files are reproducible


# ---------------------------------------------------------------------------
# primitive encoders / decoders
# ---------------------------------------------------------------------------


def _zigzag(n: int) -> bytes:
    u = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_zigzag(buf: io.BytesIO) -> int:
    shift = 0
    u = 0
    while True:
        b = buf.read(1)[0]
        u |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
    return (u >> 1) ^ -(u & 1)


def _enc_bytes(b: bytes) -> bytes:
    return _zigzag(len(b)) + b


def _read_bytes(buf: io.BytesIO) -> bytes:
    return buf.read(_read_zigzag(buf))


def _decimal_bytes(unscaled: int) -> bytes:
    length = max(1, (unscaled.bit_length() + 8) // 8)
    return unscaled.to_bytes(length, "big", signed=True)


# ---------------------------------------------------------------------------
# schema mapping
# ---------------------------------------------------------------------------


def _avro_field_type(f: pa.Field):
    t = f.type
    if pa.types.is_int64(t):
        base = "long"
    elif pa.types.is_int32(t):
        # spark-avro maps IntegerType to avro "int"; keep schema parity so
        # downstream consumers see 32-bit fields (reference: transcode avro
        # output consumed by nds_validate)
        base = "int"
    elif pa.types.is_floating(t):
        base = "double"
    elif pa.types.is_boolean(t):
        base = "boolean"
    elif pa.types.is_date32(t):
        base = {"type": "int", "logicalType": "date"}
    elif pa.types.is_decimal(t):
        base = {
            "type": "bytes",
            "logicalType": "decimal",
            "precision": t.precision,
            "scale": t.scale,
        }
    elif pa.types.is_string(t) or pa.types.is_large_string(t):
        base = "string"
    else:
        raise ValueError(f"unsupported arrow type for avro: {t}")
    if f.nullable:
        return ["null", base]
    return base


def arrow_to_avro_schema(schema: pa.Schema, name: str) -> dict:
    return {
        "type": "record",
        "name": name,
        "fields": [
            {"name": f.name, "type": _avro_field_type(f)} for f in schema
        ],
    }


# ---------------------------------------------------------------------------
# writer
# ---------------------------------------------------------------------------


def _encode_value(out: bytearray, t: pa.DataType, v):
    if pa.types.is_int64(t) or pa.types.is_int32(t):
        out += _zigzag(int(v))
    elif pa.types.is_floating(t):
        out += struct.pack("<d", float(v))
    elif pa.types.is_boolean(t):
        out.append(1 if v else 0)
    elif pa.types.is_date32(t):
        out += _zigzag(
            v.toordinal() - 719163 if hasattr(v, "toordinal") else int(v)
        )
    elif pa.types.is_decimal(t):
        unscaled = int(v.scaleb(t.scale).to_integral_value())
        out += _enc_bytes(_decimal_bytes(unscaled))
    else:  # string
        out += _enc_bytes(str(v).encode("utf-8"))


def write_avro(batches, path: str, schema: pa.Schema = None,
               record_name: str = "row", rows_per_block: int = 4096):
    """Write a pa.Table or an iterable of record batches. Batch iterables
    stream block-by-block (one container block per slice), keeping memory
    bounded by a single batch — the same morsel contract as the other
    transcode formats."""
    if isinstance(batches, pa.Table):
        schema = batches.schema
        batches = batches.to_batches(max_chunksize=rows_per_block)
    elif schema is None:
        raise ValueError("schema is required when streaming batches")
    schema_json = json.dumps(arrow_to_avro_schema(schema, record_name))
    fields = list(schema)
    with open(path, "wb") as f:
        f.write(MAGIC)
        meta = {
            "avro.schema": schema_json.encode("utf-8"),
            "avro.codec": b"null",
        }
        f.write(_zigzag(len(meta)))
        for k, v in meta.items():
            f.write(_enc_bytes(k.encode("utf-8")))
            f.write(_enc_bytes(v))
        f.write(_zigzag(0))  # end of metadata map
        f.write(SYNC)
        for batch in batches:
            for start in range(0, batch.num_rows, rows_per_block):
                rows = batch.slice(start, rows_per_block).to_pylist()
                if not rows:
                    continue
                out = bytearray()
                for row in rows:
                    for fld in fields:
                        v = row[fld.name]
                        if fld.nullable:
                            if v is None:
                                out += _zigzag(0)  # union branch: null
                                continue
                            out += _zigzag(1)
                        _encode_value(out, fld.type, v)
                f.write(_zigzag(len(rows)))
                f.write(_zigzag(len(out)))
                f.write(out)
                f.write(SYNC)


# ---------------------------------------------------------------------------
# reader (round-trip verification)
# ---------------------------------------------------------------------------


def _decode_value(buf: io.BytesIO, ftype):
    if isinstance(ftype, dict):
        lt = ftype.get("logicalType")
        if lt == "date":
            import datetime

            return datetime.date.fromordinal(_read_zigzag(buf) + 719163)
        if lt == "decimal":
            import decimal

            raw = _read_bytes(buf)
            unscaled = int.from_bytes(raw, "big", signed=True)
            return decimal.Decimal(unscaled).scaleb(-ftype["scale"])
        ftype = ftype["type"]
    if ftype == "long" or ftype == "int":
        return _read_zigzag(buf)
    if ftype == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if ftype == "boolean":
        return buf.read(1)[0] == 1
    if ftype == "string":
        return _read_bytes(buf).decode("utf-8")
    raise ValueError(f"unsupported avro type {ftype}")


def read_avro(path: str):
    """Read an avro container file written by write_avro -> list of dicts."""
    with open(path, "rb") as f:
        data = f.read()
    buf = io.BytesIO(data)
    assert buf.read(4) == MAGIC, "not an avro container file"
    meta = {}
    while True:
        n = _read_zigzag(buf)
        if n == 0:
            break
        if n < 0:
            # spec: a negative map block count is followed by the block's
            # byte size, then |n| entries
            _read_zigzag(buf)
            n = -n
        for _ in range(n):
            k = _read_bytes(buf).decode("utf-8")
            meta[k] = _read_bytes(buf)
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    sync = buf.read(16)
    rows = []
    while buf.tell() < len(data):
        count = _read_zigzag(buf)
        _size = _read_zigzag(buf)
        for _ in range(count):
            row = {}
            for fld in schema["fields"]:
                ftype = fld["type"]
                if isinstance(ftype, list):  # nullable union
                    if _read_zigzag(buf) == 0:
                        row[fld["name"]] = None
                        continue
                    ftype = ftype[1]
                row[fld["name"]] = _decode_value(buf, ftype)
            rows.append(row)
        assert buf.read(16) == sync, "sync marker mismatch"
    return rows
