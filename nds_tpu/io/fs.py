"""Filesystem seam: warehouse, report, and stream IO routes through fsspec.

The reference reaches HDFS/S3/GS in every phase (reference:
nds/nds_gen_data.py:130-180 hadoop targets; nds/nds_power.py:296-299 writes
the extra time log *via Spark* precisely so it can land on cloud storage).
This module is the equivalent seam: any `scheme://` path is handled by the
matching fsspec filesystem (memory:// in tests, s3://gs://abfs:// in real
deployments), plain paths stay on the fast local-POSIX code paths.
"""

from __future__ import annotations

import os
import posixpath


def is_remote(path) -> bool:
    """True for scheme-qualified paths (file:// included: it must route
    through get_fs for scheme stripping — raw os.* calls on the literal
    URL string would create a relative './file:/...' directory)."""
    return "://" in str(path)


def get_fs(path):
    """(filesystem, normalized path) for any local path or URL."""
    import fsspec

    fs, _, paths = fsspec.get_fs_token_paths(str(path))
    return fs, paths[0]


def fs_open(path, mode: str = "r", newline=None, encoding=None):
    """open() for local paths and URLs alike (caller closes). `newline`
    and `encoding` apply to local text mode (csv writers need
    newline=''); fsspec text mode already uses newline=''."""
    if not is_remote(path):
        if "w" in mode or "a" in mode:
            parent = os.path.dirname(str(path))
            if parent:
                os.makedirs(parent, exist_ok=True)
        return open(path, mode, newline=newline, encoding=encoding)
    fs, p = get_fs(path)
    if "w" in mode or "a" in mode:
        parent = posixpath.dirname(p)
        if parent:
            fs.makedirs(parent, exist_ok=True)
    return fs.open(p, mode)


def join(base, *parts) -> str:
    """Path join that keeps URL schemes intact."""
    if is_remote(base):
        return posixpath.join(str(base), *parts)
    return os.path.join(str(base), *parts)


def put_if_absent(fs, tmp: str, dest: str) -> bool:
    """Move tmp to dest only if dest does not exist; True on success.

    Local filesystems get a genuinely atomic os.link (two concurrent
    committers cannot both win). Remote stores without an atomic
    create-exclusive primitive fall back to exists+move — the same
    best-effort window Iceberg closes with a catalog service; single-writer
    benchmark phases never race it."""
    proto = fs.protocol if isinstance(fs.protocol, str) else fs.protocol[0]
    if proto in ("file", "local"):
        try:
            os.link(tmp, dest)
        except FileExistsError:
            os.unlink(tmp)
            return False
        os.unlink(tmp)
        return True
    if fs.exists(dest):
        fs.rm_file(tmp)
        return False
    fs.mv(tmp, dest)
    return True
