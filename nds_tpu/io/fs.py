"""Filesystem seam: warehouse, report, and stream IO routes through fsspec.

The reference reaches HDFS/S3/GS in every phase (reference:
nds/nds_gen_data.py:130-180 hadoop targets; nds/nds_power.py:296-299 writes
the extra time log *via Spark* precisely so it can land on cloud storage).
This module is the equivalent seam: any `scheme://` path is handled by the
matching fsspec filesystem (memory:// in tests, s3://gs://abfs:// in real
deployments), plain paths stay on the fast local-POSIX code paths.

Failure domain: remote opens retry transient errors with exponential
backoff + jitter (NDS_IO_RETRIES / NDS_IO_BACKOFF — object stores throttle
and reset connections routinely, and one 503 must not kill a benchmark
phase), `fs_open_atomic` writes via a temp name + rename so a crash mid-write
can never leave a torn report/manifest behind, and every open is a fault
injection point (faults.maybe_fire_path) so those paths are testable.
"""

from __future__ import annotations

import os
import posixpath
import time
import uuid

from .. import faults

#: default transient-IO retry budget for remote opens (attempts = retries+1)
IO_RETRIES_ENV = "NDS_IO_RETRIES"
IO_BACKOFF_ENV = "NDS_IO_BACKOFF"


def io_retry_budget():
    """(retries, backoff_base_seconds) for transient remote-IO failures."""
    return (
        int(os.environ.get(IO_RETRIES_ENV, "3")),
        float(os.environ.get(IO_BACKOFF_ENV, "0.5")),
    )


def is_remote(path) -> bool:
    """True for scheme-qualified paths (file:// included: it must route
    through get_fs for scheme stripping — raw os.* calls on the literal
    URL string would create a relative './file:/...' directory)."""
    return "://" in str(path)


def get_fs(path):
    """(filesystem, normalized path) for any local path or URL."""
    import fsspec

    fs, _, paths = fsspec.get_fs_token_paths(str(path))
    return fs, paths[0]


def _open_remote_with_retries(path, mode):
    """Open a remote path, retrying transient failures with exponential
    backoff + full jitter. Deterministic errors raise immediately."""
    retries, base = io_retry_budget()
    delays = faults.backoff_delays(retries, base)
    while True:
        try:
            faults.maybe_fire_path(path)
            fs, p = get_fs(path)
            if "w" in mode or "a" in mode:
                parent = posixpath.dirname(p)
                if parent:
                    fs.makedirs(parent, exist_ok=True)
            return fs.open(p, mode)
        except Exception as exc:
            if faults.classify(exc) != faults.IO_TRANSIENT:
                raise
            delay = next(delays, None)
            if delay is None:
                raise
            print(
                f"fs: transient io failure opening {path} ({exc}); "
                f"retrying in {delay:.2f}s"
            )
            # observability: record the retry in the bound stream's event
            # log (lazy import, and only on the already-slow retry path)
            from ..obs import trace as _obs_trace

            tracer = _obs_trace.current()
            if tracer is not None:
                tracer.emit(
                    "io_retry", path=str(path), error=str(exc)[:200],
                    delay_s=round(delay, 3),
                )
            time.sleep(delay)


def fs_open(path, mode: str = "r", newline=None, encoding=None):
    """open() for local paths and URLs alike (caller closes). `newline`
    and `encoding` apply to local text mode (csv writers need
    newline=''); fsspec text mode already uses newline=''."""
    if not is_remote(path):
        faults.maybe_fire_path(path)
        if "w" in mode or "a" in mode:
            parent = os.path.dirname(str(path))
            if parent:
                os.makedirs(parent, exist_ok=True)
        return open(path, mode, newline=newline, encoding=encoding)
    return _open_remote_with_retries(path, mode)


class _AtomicFile:
    """File-like wrapper that writes to a temp sibling and renames into
    place on a clean close; close-after-error (or interpreter teardown mid-
    write) leaves the destination untouched — readers see the old complete
    file or the new complete file, never a torn one."""

    def __init__(self, path, mode, newline=None, encoding=None):
        self._dest = str(path)
        self._remote = is_remote(path)
        suffix = f".tmp-{uuid.uuid4().hex[:8]}"
        if self._remote:
            self._tmp = self._dest + suffix
            self._fh = fs_open(self._tmp, mode)
        else:
            parent = os.path.dirname(self._dest)
            if parent:
                os.makedirs(parent, exist_ok=True)
            self._tmp = self._dest + suffix
            faults.maybe_fire_path(self._dest)
            self._fh = open(self._tmp, mode, newline=newline, encoding=encoding)
        self._committed = False

    def __getattr__(self, name):
        return getattr(self._fh, name)

    def __iter__(self):
        return iter(self._fh)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close(commit=exc_type is None)
        return False

    def close(self, commit: bool = True):
        if self._committed:
            return
        self._fh.close()
        if not commit:
            self._discard()
            return
        self._committed = True
        if self._remote:
            fs, tmp = get_fs(self._tmp)
            _, dest = get_fs(self._dest)
            fs.mv(tmp, dest)
        else:
            os.replace(self._tmp, self._dest)

    def _discard(self):
        self._committed = True
        try:
            if self._remote:
                fs, tmp = get_fs(self._tmp)
                fs.rm_file(tmp)
            else:
                os.unlink(self._tmp)
        except OSError:
            pass


def fs_open_atomic(path, mode: str = "w", newline=None, encoding=None):
    """Crash-safe fs_open for whole-file writes (reports, time logs, state
    files): content lands under a temp name and renames into place on close.
    Use as a context manager; an exception inside the block discards the
    temp file instead of publishing it."""
    if "w" not in mode:
        raise ValueError(f"fs_open_atomic is write-only, got mode {mode!r}")
    return _AtomicFile(path, mode, newline=newline, encoding=encoding)


def join(base, *parts) -> str:
    """Path join that keeps URL schemes intact."""
    if is_remote(base):
        return posixpath.join(str(base), *parts)
    return os.path.join(str(base), *parts)


def put_if_absent(fs, tmp: str, dest: str) -> bool:
    """Move tmp to dest only if dest does not exist; True on success.

    Local filesystems get a genuinely atomic os.link (two concurrent
    committers cannot both win). Remote stores without an atomic
    create-exclusive primitive fall back to exists+move — the same
    best-effort window Iceberg closes with a catalog service; single-writer
    benchmark phases never race it."""
    proto = fs.protocol if isinstance(fs.protocol, str) else fs.protocol[0]
    if proto in ("file", "local"):
        try:
            os.link(tmp, dest)
        except FileExistsError:
            os.unlink(tmp)
            return False
        os.unlink(tmp)
        return True
    if fs.exists(dest):
        fs.rm_file(tmp)
        return False
    fs.mv(tmp, dest)
    return True
