"""Raw data ingestion: pipe-delimited .dat files -> Arrow.

Counterpart of the reference's CSV scan (reference: nds/nds_transcode.py:56-58
`session.read.option(delimiter='|').option('header','false').csv(path, schema)`).
Generator rows end with a trailing '|' so a phantom empty column is appended
during parse and dropped here; empty strings are nulls.
"""

from __future__ import annotations

import glob
import os

import pyarrow as pa
import pyarrow.csv as pacsv


def _read_options(schema):
    names = [f.name for f in schema] + ["_trailing"]
    return pacsv.ReadOptions(column_names=names)


def _parse_options():
    return pacsv.ParseOptions(delimiter="|")


def _convert_options(schema, use_decimal):
    types = {f.name: f.dtype.to_arrow(use_decimal) for f in schema}
    types["_trailing"] = pa.string()
    return pacsv.ConvertOptions(
        column_types=types,
        strings_can_be_null=True,
        quoted_strings_can_be_null=True,
    )


def _empty_table(schema, use_decimal):
    return pa.table(
        {f.name: pa.array([], type=f.dtype.to_arrow(use_decimal)) for f in schema}
    )


def read_dat_file(path, schema, use_decimal=True) -> pa.Table:
    if os.path.getsize(path) == 0:
        # small scale factors legitimately produce empty refresh chunks
        return _empty_table(schema, use_decimal)
    t = pacsv.read_csv(
        path,
        read_options=_read_options(schema),
        parse_options=_parse_options(),
        convert_options=_convert_options(schema, use_decimal),
    )
    return t.drop_columns(["_trailing"])


def read_dat_dir(path, schema, use_decimal=True) -> pa.Table:
    """Read a per-table directory of chunk files (or a single file)."""
    if os.path.isfile(path):
        return read_dat_file(path, schema, use_decimal)
    files = sorted(glob.glob(os.path.join(path, "*.dat")))
    if not files:
        raise FileNotFoundError(f"no .dat files under {path}")
    parts = [read_dat_file(f, schema, use_decimal) for f in files]
    return pa.concat_tables(parts)


def iter_dat_chunk_tables(path, schema, use_decimal=True):
    """Yield one whole Arrow table per generator chunk file (or the single
    file). Host memory is bounded by the chunk size, which generation
    parallelism keeps roughly constant across scale factors; the
    partitioned transcode writer sorts each chunk by its partition key, so
    it needs chunk granularity rather than fixed-byte morsels."""
    files = (
        [path]
        if os.path.isfile(path)
        else sorted(glob.glob(os.path.join(path, "*.dat")))
    )
    if not files:
        raise FileNotFoundError(f"no .dat files under {path}")
    for f in files:
        yield read_dat_file(f, schema, use_decimal)


def iter_dat_batches(path, schema, use_decimal=True, block_size=64 << 20):
    """Stream a .dat file or chunk directory as Arrow record batches.

    Bounded-memory ingestion for the transcode/load phase: tables are read in
    `block_size`-byte morsels instead of one whole-table materialization, so
    SF100+ fact tables stream through a fixed host-memory footprint
    (reference analogue: Spark's partitioned CSV scan, nds/nds_transcode.py:56-58).
    """
    if os.path.isfile(path):
        files = [path]
    else:
        files = sorted(glob.glob(os.path.join(path, "*.dat")))
        if not files:
            raise FileNotFoundError(f"no .dat files under {path}")
    ropts = _read_options(schema)
    ropts.block_size = block_size
    for f in files:
        if os.path.getsize(f) == 0:
            continue
        with pacsv.open_csv(
            f,
            read_options=ropts,
            parse_options=_parse_options(),
            convert_options=_convert_options(schema, use_decimal),
        ) as reader:
            for batch in reader:
                yield batch.drop_columns(["_trailing"])
