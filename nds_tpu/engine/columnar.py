"""Columnar data model for the TPU execution engine.

The device-resident unit of work is a `Table`: a set of named `Column`s whose
buffers are dense JAX arrays padded to a shared *capacity* (a power-of-two
bucket >= the live row count). Padding + bucketing keeps the set of shapes the
compiler sees small, so per-op `jit` caches stay warm across the 99-query
stream even though every intermediate result has a different live row count
(the TPU answer to dynamic result shapes of joins/filters — SURVEY.md §7
"hard parts" #2).

Representation choices (TPU-first, see nds_tpu/dtypes.py):
  - integers / dates        -> int32 / int64 device buffers
  - decimal(p,s)            -> scaled int64 (value * 10^s), exact add/sub/cmp
  - char/varchar/string     -> int32 dictionary codes on device, the distinct
                               values live host-side in a pyarrow array; all
                               string functions are O(|dict|) host transforms
                               plus an O(n) device gather
  - NULLs                   -> separate bool validity buffer (None == all valid)

Counterpart of the columnar-batch layer the reference delegates to cuDF device
buffers via the rapids plugin (reference: nds/power_run_gpu.template:20-41
configures it; the batches themselves live in the external engine).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..dtypes import DType, parse_dtype, INT64, FLOAT64

jax.config.update("jax_enable_x64", True)

# Minimum capacity bucket. 8*128 = one float32 VMEM tile's worth of lanes.
_MIN_CAP = 1024


def bucket_cap(n: int) -> int:
    """Smallest power-of-two capacity >= n (>= _MIN_CAP)."""
    cap = _MIN_CAP
    while cap < n:
        cap *= 2
    return cap


def _pad_to(arr: jnp.ndarray, cap: int, fill=0) -> jnp.ndarray:
    n = arr.shape[0]
    if n == cap:
        return arr
    if n > cap:
        raise ValueError(f"array longer ({n}) than capacity ({cap})")
    return jnp.pad(arr, (0, cap - n), constant_values=fill)


@dataclass(frozen=True)
class ColStats:
    """Host-side column statistics captured once at catalog load.

    Bounds are over the column's *base-table* non-null values, so they stay
    conservatively valid through any row subset (filter/compact/sort) and any
    gather (join output). `unique` means the base column's non-null values
    are pairwise distinct — preserved by subsetting, destroyed by joins.
    The executor's fast-path plan choices (dense star-join, direct
    aggregation) read these instead of issuing device round-trips, so picking
    a physical strategy costs zero host syncs on the query hot path (the
    round-2 regression: per-join masked_min_max + counts.max() syncs).
    """

    vmin: int
    vmax: int
    unique: bool
    base_rows: int  # live rows of the base table the bounds came from


@dataclass(frozen=True)
class Column:
    """One column: device buffer + optional validity + optional dictionary.

    `data` and `valid` are padded to the owning Table's capacity; entries at
    index >= nrows are garbage and must never influence results (kernels mask
    them with an iota < nrows predicate where it matters).
    """

    data: jnp.ndarray
    dtype: DType
    valid: Optional[jnp.ndarray] = None  # bool; None == all valid
    dictionary: Optional[pa.Array] = None  # for string dtypes: distinct values
    stats: Optional[ColStats] = None  # base-table stats (see ColStats)
    # buffer OWNERSHIP: True iff this column's data/valid buffers were
    # freshly minted for this one table by its producer (join pair gathers,
    # compaction takes) and alias nothing another live table references.
    # Consumed by fused-pipeline full-column donation (engine/fuse.py):
    # only owned, single-consumer, non-passthrough buffers may be donated
    # to an executable. Conservatively False everywhere else — a False on
    # a fresh buffer only costs a missed donation, a True on an aliased
    # buffer would invalidate memory another table still reads.
    owned: bool = False

    @property
    def is_string(self) -> bool:
        return self.dtype.is_string

    def with_valid(self, valid: Optional[jnp.ndarray]) -> "Column":
        return replace(self, valid=valid)

    def disowned(self) -> "Column":
        """This column shared by reference into ANOTHER table (join/filter/
        project passthrough): two tables now reference the buffer, and the
        sharing site cannot prove the source table is transient — e.g. a
        CTE or plan-cache entry retains it across reads — so neither side
        may treat the buffer as exclusively owned. Every executor path that
        copies Column objects across a plan-node boundary must route
        through this (a stale True would let fused-pipeline donation free
        memory the retained table still reads)."""
        return replace(self, owned=False) if self.owned else self

    def subset_stats(self) -> Optional[ColStats]:
        """Stats valid for any row-subset/permutation of this column."""
        return self.stats

    def gather_stats(self) -> Optional[ColStats]:
        """Stats valid after a gather with possible repeats (join output):
        bounds survive, uniqueness does not."""
        if self.stats is None:
            return None
        return replace(self.stats, unique=False)


class Table:
    """A named collection of equal-capacity columns with a live row count.

    Deferred compaction: `live` (when set) is an explicit per-row liveness
    mask — filtered/joined rows stay in place instead of being packed to
    the front, and `nrows` may be a 0-d device scalar that only crosses to
    the host on first access. Device->host syncs cost ~90 ms each on the
    bench tunnel, so producers queue the count asynchronously and most
    consumers (masks, group-by, joins, sorts) never force it."""

    __slots__ = ("columns", "_nrows", "live", "_packed", "unique_key")

    def __init__(self, columns: dict, nrows, live=None, unique_key=None):
        self.columns = columns  # name -> Column (insertion-ordered)
        self._nrows = nrows  # host int or 0-d device array (lazy)
        self.live = live  # None (first nrows rows live) or bool[cap]
        self._packed = None  # memoized compacted() result
        # frozenset of column names whose combined values are pairwise
        # distinct over live rows (group-by keys, DISTINCT output). Survives
        # row subsetting/renaming; destroyed by row-expanding gathers.
        # Probe-style joins read it to skip runtime uniqueness checks.
        self.unique_key = unique_key

    @property
    def nrows(self) -> int:
        if not isinstance(self._nrows, int):
            self._nrows = int(self._nrows)  # device sync on first need
        return self._nrows

    @property
    def nrows_known(self):
        """The live row count if already on the host, else None."""
        return self._nrows if isinstance(self._nrows, int) else None

    @property
    def nrows_lazy(self):
        """The live row count without forcing a device sync (host int or
        0-d device array); pass through when constructing derived tables."""
        return self._nrows

    @property
    def cap(self) -> int:
        for c in self.columns.values():
            return int(c.data.shape[0])
        # column-less table (e.g. the __dual__ relation for FROM-less
        # selects): capacity must still cover the live rows
        return bucket_cap(self.nrows) if self.nrows > 0 else 0

    @property
    def names(self):
        return list(self.columns.keys())

    def column(self, name: str) -> Column:
        return self.columns[name]

    def select(self, names) -> "Table":
        uk = self.unique_key
        if uk is not None and not uk <= set(names):
            uk = None
        return Table(
            {n: self.columns[n] for n in names}, self._nrows, self.live,
            unique_key=uk,
        )

    def rename(self, mapping: dict) -> "Table":
        uk = self.unique_key
        if uk is not None:
            uk = frozenset(mapping.get(n, n) for n in uk)
        return Table(
            {mapping.get(n, n): c for n, c in self.columns.items()},
            self._nrows,
            self.live,
            unique_key=uk,
        )

    def row_mask(self) -> jnp.ndarray:
        """Bool mask of live rows."""
        if self.live is not None:
            return self.live
        return jnp.arange(self.cap, dtype=jnp.int32) < self._nrows

    def compacted(self) -> "Table":
        """Pack live rows to the front (drops the mask). Reuses the count
        already queued in _nrows (no extra reduce/sync) and memoizes, so a
        masked table shared by several consumers compacts once."""
        if self.live is None:
            return self
        if self._packed is not None:
            return self._packed
        from ..ops import kernels as K

        count = self.nrows
        cap = bucket_cap(max(count, 1))
        idx = K.compact_indices(self.live, cap)
        cols = {}
        for name, c in self.columns.items():
            cols[name] = Column(
                c.data[idx],
                c.dtype,
                None if c.valid is None else c.valid[idx],
                c.dictionary,
                c.subset_stats(),
            )
        self._packed = Table(cols, count, unique_key=self.unique_key)
        return self._packed


def table_device_bytes(table: Table) -> int:
    """Device bytes held by a table's buffers (data + validity masks;
    capacity-padded shapes are static, so this never syncs the device).
    THE byte-estimation rule: the session plan-cache budget and the obs
    op_span `est_bytes` field both read it, so they cannot drift."""
    total = 0
    for c in table.columns.values():
        total += int(c.data.nbytes)
        if c.valid is not None:
            total += int(c.valid.nbytes)
    return total


# ---------------------------------------------------------------------------
# Bounded row windows (blocked union-aggregation)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("cap",))
def _dyn_slice(arr: jnp.ndarray, start, cap: int) -> jnp.ndarray:
    return jax.lax.dynamic_slice_in_dim(arr, start, cap)


def window_slice(table: Table, start: int, cap: int) -> Table:
    """Rows [start, start+cap) of a compacted table as a Table of capacity
    `cap`, via per-column dynamic slices — never a full-capacity gather.

    `cap` must be a power-of-two bucket <= table.cap and `start` a multiple
    of `cap`, so the slice can never clamp (both caps are power-of-two
    buckets, hence table.cap is a multiple of cap). The start index stays a
    traced scalar, so every window of a given (shape, cap) pair shares one
    compiled slice kernel."""
    if table.live is not None:
        raise ValueError("window_slice requires a compacted table")
    if cap >= table.cap:
        return table
    if start % cap:
        raise ValueError(f"window start {start} not aligned to cap {cap}")
    nrows = min(max(table.nrows - start, 0), cap)
    cols = {}
    for name, c in table.columns.items():
        cols[name] = Column(
            _dyn_slice(c.data, start, cap),
            c.dtype,
            None if c.valid is None else _dyn_slice(c.valid, start, cap),
            c.dictionary,
            c.subset_stats(),
        )
    return Table(cols, nrows, unique_key=table.unique_key)


# ---------------------------------------------------------------------------
# Host <-> device conversion (Arrow is the host-side interchange format)
# ---------------------------------------------------------------------------


def _np_valid(arr: pa.Array) -> Optional[np.ndarray]:
    if arr.null_count == 0:
        return None
    return pc.is_valid(arr).to_numpy(zero_copy_only=False)


def column_from_arrow(arr: pa.ChunkedArray | pa.Array, dtype: DType, cap: int) -> Column:
    """Decode one Arrow column into the device representation."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    valid_np = _np_valid(arr)
    if dtype.is_string:
        # Dictionary-encode on host; codes ride to HBM, values stay host-side.
        if not pa.types.is_dictionary(arr.type):
            arr = pc.dictionary_encode(arr)
        codes = np.asarray(
            arr.indices.fill_null(0).to_numpy(zero_copy_only=False), dtype=np.int32
        )
        dictionary = arr.dictionary
        data = jnp.asarray(np.ascontiguousarray(codes))
    else:
        dictionary = None
        if dtype.is_decimal:
            if pa.types.is_decimal(arr.type):
                # decimal128 -> scaled int64: multiply by 10^s as decimal
                # (keeps exactness; p <= 18 covers all of TPC-DS), then the
                # rescale-free cast to int64 is lossless.
                import decimal

                shift = pa.scalar(decimal.Decimal(10**dtype.scale))
                scaled = pc.multiply(arr.cast(pa.decimal128(18, arr.type.scale)), shift)
                np_vals = scaled.fill_null(0).cast(pa.int64()).to_numpy(
                    zero_copy_only=False
                )
            else:
                scale = 10 ** dtype.scale
                f = arr.cast(pa.float64()).fill_null(0.0).to_numpy(zero_copy_only=False)
                np_vals = np.round(f * scale).astype(np.int64)
            np_vals = np.asarray(np_vals, dtype=np.int64)
        elif dtype.kind == "date":
            np_vals = arr.cast(pa.int32()).fill_null(0).to_numpy(zero_copy_only=False)
        else:
            npdt = dtype.device_np_dtype()
            filled = arr.fill_null(0) if arr.null_count else arr
            np_vals = np.asarray(
                filled.to_numpy(zero_copy_only=False), dtype=npdt
            )
        data = jnp.asarray(np.ascontiguousarray(np_vals))
    data = _pad_to(data, cap)
    valid = None
    if valid_np is not None:
        valid = _pad_to(jnp.asarray(valid_np), cap, fill=False)
    return Column(data, dtype, valid, dictionary)


# Above this many rows, per-column uniqueness (count_distinct) is skipped at
# load: only dimension-sized build sides benefit, and larger tables are
# rejected by the dense-join domain cap anyway.
_UNIQUE_STATS_MAX_ROWS = 1 << 22


def arrow_column_stats(arr, dtype: DType, nrows: int) -> Optional[ColStats]:
    """Host-side min/max/uniqueness of an integer-like Arrow column.

    One vectorized Arrow pass per column at catalog-load time buys sync-free
    physical plan choice for every query that later touches the column."""
    if dtype.kind not in ("int32", "int64", "date"):
        return None
    if nrows == 0:
        return None
    if isinstance(arr, pa.ChunkedArray) and arr.num_chunks == 0:
        return None
    if dtype.kind == "date":
        # date32 scalars don't cast to int; min/max over the day numbers
        arr = arr.cast(pa.int32())
    mm = pc.min_max(arr)
    vmin, vmax = mm["min"], mm["max"]
    if not vmin.is_valid:  # all-null column
        return None
    vmin = vmin.cast(pa.int64()).as_py()
    vmax = vmax.cast(pa.int64()).as_py()
    unique = False
    if nrows <= _UNIQUE_STATS_MAX_ROWS:
        n_valid = nrows - arr.null_count
        unique = pc.count_distinct(arr, mode="only_valid").as_py() == n_valid
    return ColStats(vmin, vmax, unique, nrows)


def table_from_arrow(
    batch: pa.Table | pa.RecordBatch, schema=None, with_stats: bool = False
) -> Table:
    """Build a device Table from an Arrow table.

    `schema` (nds_tpu.schema.Schema) supplies logical types; if omitted they
    are inferred from the Arrow types. `with_stats` captures per-column
    ColStats (catalog loads set it; ad-hoc intermediates skip the pass).
    """
    nrows = batch.num_rows
    cap = bucket_cap(nrows)
    cols = {}
    if isinstance(batch, pa.RecordBatch):
        batch = pa.Table.from_batches([batch])
    for i, name in enumerate(batch.column_names):
        if schema is not None and name in schema:
            dtype = schema.field(name).dtype
        else:
            dtype = _infer_dtype(batch.schema.field(i).type)
        col = column_from_arrow(batch.column(i), dtype, cap)
        if with_stats and col.stats is None:
            stats = arrow_column_stats(batch.column(i), dtype, nrows)
            if stats is not None:
                col = replace(col, stats=stats)
        cols[name] = col
    return Table(cols, nrows)


def _infer_dtype(t: pa.DataType) -> DType:
    if pa.types.is_int32(t) or pa.types.is_int16(t) or pa.types.is_int8(t):
        return parse_dtype("int32")
    if pa.types.is_int64(t):
        return parse_dtype("int64")
    if pa.types.is_floating(t):
        return parse_dtype("float64")
    if pa.types.is_decimal(t):
        return DType("decimal", t.precision, t.scale)
    if pa.types.is_date(t):
        return parse_dtype("date")
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        return parse_dtype("string")
    if pa.types.is_dictionary(t):
        return parse_dtype("string")
    if pa.types.is_boolean(t):
        return parse_dtype("int32")
    raise ValueError(f"unsupported arrow type {t}")


def column_to_arrow(col: Column, nrows: int, host=None) -> pa.Array:
    """Materialize a device column back into Arrow (collect/write path).
    `host`: optional pre-fetched (data, valid) numpy pair so callers can batch
    the device->host transfers of many columns into one round trip."""
    if host is not None:
        data, valid = host
        data = data[:nrows]
        valid = None if valid is None else valid[:nrows]
    else:
        data = np.asarray(col.data[:nrows])
        valid = None if col.valid is None else np.asarray(col.valid[:nrows])
    mask = None if valid is None else ~valid
    dt = col.dtype
    if dt.is_string:
        codes = pa.array(data.astype(np.int32), mask=mask)
        return pa.DictionaryArray.from_arrays(codes, col.dictionary).cast(pa.string())
    if dt.is_decimal:
        # Our int64s are *unscaled* decimal values; Arrow's int->decimal cast
        # is value-preserving, so build the decimal128 buffer directly
        # (low word = value, high word = sign extension).
        ints = data.astype("<i8")
        buf = np.empty((len(ints), 2), dtype="<i8")
        buf[:, 0] = ints
        buf[:, 1] = ints >> 63
        validity = None
        if mask is not None:
            validity = pa.array(~mask).buffers()[1]
        return pa.Array.from_buffers(
            pa.decimal128(dt.precision, dt.scale),
            len(ints),
            [validity, pa.py_buffer(buf.tobytes())],
        )
    if dt.kind == "date":
        return pa.array(data.astype(np.int32), mask=mask).cast(pa.date32())
    if dt.kind == "bool":
        return pa.array(data.astype(bool), mask=mask)
    return pa.array(data, mask=mask)


def table_to_arrow(table: Table) -> pa.Table:
    table = table.compacted()  # deferred-compaction tables pack here
    # one batched device->host round trip for every buffer (each blocking
    # np.asarray would otherwise pay a full tunnel round trip per column)
    flat = []
    for c in table.columns.values():
        flat.append(c.data)
        if c.valid is not None:
            flat.append(c.valid)
    if any(
        hasattr(x, "is_fully_addressable") and not x.is_fully_addressable
        for x in flat
    ):
        # multi-process mesh: shards live on other hosts' devices, which
        # device_get cannot read — all-gather each buffer to every process
        # first (DCN-tier result collection)
        from jax.experimental import multihost_utils

        flat = [
            multihost_utils.process_allgather(x, tiled=True)
            if hasattr(x, "is_fully_addressable") and not x.is_fully_addressable
            else x
            for x in flat
        ]
    fetched = iter(jax.device_get(flat))
    arrays = []
    for c in table.columns.values():
        data = next(fetched)
        valid = next(fetched) if c.valid is not None else None
        arrays.append(column_to_arrow(c, table.nrows, host=(data, valid)))
    return pa.table(arrays, names=table.names)


# ---------------------------------------------------------------------------
# Dictionary utilities (string kernels run on the host over distinct values)
# ---------------------------------------------------------------------------


def unify_dictionaries(a: Column, b: Column):
    """Remap two string columns onto one shared dictionary.

    Needed before any cross-table comparison/join of string columns, because
    codes are only meaningful within their own dictionary. Returns
    (codes_a, codes_b, unified_dictionary); the remap is O(|dict|) on host +
    O(n) gathers on device.
    """
    if a.dictionary is not None and a.dictionary is b.dictionary:
        # already share one dictionary (common after unions/CTE reuse over
        # the same base column): codes are directly comparable — skip the
        # host-side unique/index_in work, which costs real milliseconds
        # per join on 100k-entry dictionaries
        return a.data, b.data, a.dictionary
    da = a.dictionary if a.dictionary is not None else pa.array([], type=pa.string())
    db = b.dictionary if b.dictionary is not None else pa.array([], type=pa.string())
    unified = pc.unique(pa.concat_arrays([da.cast(pa.string()), db.cast(pa.string())]))
    remap_a = pc.index_in(da.cast(pa.string()), unified).to_numpy(zero_copy_only=False)
    remap_b = pc.index_in(db.cast(pa.string()), unified).to_numpy(zero_copy_only=False)
    ra = jnp.asarray(remap_a.astype(np.int32))
    rb = jnp.asarray(remap_b.astype(np.int32))
    codes_a = ra[jnp.clip(a.data, 0, max(len(da) - 1, 0))] if len(da) else a.data
    codes_b = rb[jnp.clip(b.data, 0, max(len(db) - 1, 0))] if len(db) else b.data
    return codes_a, codes_b, unified


def sort_dictionary(col: Column):
    """Return codes remapped so that code order == lexicographic value order.

    Lets ORDER BY / min / max on strings run entirely on device: comparing
    rank codes is comparing strings.
    """
    d = col.dictionary
    if d is None or len(d) == 0:
        # all-null string column (e.g. c_login): nothing to rank
        return col.data, d
    d = d.cast(pa.string())
    order = pc.array_sort_indices(d)  # indices of values in sorted order
    rank = np.empty(len(d), dtype=np.int32)
    rank[order.to_numpy(zero_copy_only=False)] = np.arange(len(d), dtype=np.int32)
    sorted_dict = d.take(order)
    ranks = jnp.asarray(rank)[jnp.clip(col.data, 0, len(d) - 1)]
    return ranks, sorted_dict
