"""Session: the user-facing entry point of the TPU SQL engine.

Plays the role SparkSession plays for the reference harness (reference:
nds/nds_power.py:184-233 builds the session, registers temp views, runs
`spark.sql(q)` then collect()/write). A Session owns a catalog of named
datasets (Arrow-backed files or in-memory tables), parses + binds + executes
SQL, and returns Arrow tables.
"""

from __future__ import annotations

import os
import threading
from time import monotonic as _monotonic, perf_counter as _perf
from typing import Optional

import pyarrow as pa
import pyarrow.dataset as pads

from ..schema import get_schemas, get_maintenance_schemas
from . import expr as E
from . import plan as P
from .binder import Binder
from .columnar import (
    Table,
    table_device_bytes,
    table_from_arrow,
    table_to_arrow,
)
from .exec import Executor
from .sql import ast as A
from .sql.parser import parse_sql, parse_script
from .lockdebug import make_lock


_PERSISTENT_CACHE_SET = False


def _enable_persistent_compile_cache():
    """Point XLA's persistent compilation cache at a shared directory so the
    99-query compile footprint is paid once per machine, not once per process
    (cold query compiles dominate wall clock ~50x over steady-state
    execution). Opt out with NDS_XLA_CACHE_DIR=0."""
    # process-wide once-latch, not per-stream state: worst case under a
    # race is a second, idempotent jax.config.update with the same values
    # nds-lint: disable=mutable-module-global
    global _PERSISTENT_CACHE_SET
    if _PERSISTENT_CACHE_SET:
        return
    _PERSISTENT_CACHE_SET = True
    # user-owned default (XDG): a /tmp default could be pre-created by any
    # other local user (/tmp squatting), putting cache entries in an
    # attacker-owned directory
    default_dir = os.path.join(
        os.environ.get("XDG_CACHE_HOME")
        or os.path.join(os.path.expanduser("~"), ".cache"),
        "nds_xla",
    )
    cache_dir = os.environ.get("NDS_XLA_CACHE_DIR", default_dir)
    if not cache_dir or cache_dir == "0":
        return
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        # NDS_XLA_CACHE_MIN_COMPILE_S=0 persists even sub-100ms kernel
        # compiles — the cold-start gate (tools/fuse_microbench.py) and
        # fleets whose cold cost is MANY small kernels want everything on
        # disk; the 0.1 s default keeps steady-state dev runs from
        # churning the cache with trivial entries
        min_s = os.environ.get("NDS_XLA_CACHE_MIN_COMPILE_S")
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs",
            float(min_s) if min_s else 0.1,
        )
    except Exception:
        pass  # older jax without the knobs: in-memory cache only


class _PlanResultCache:
    """Byte-budgeted LRU of executed plan subtrees, keyed by structural
    fingerprint (plan.fingerprint). Lets repeated CTE/subquery text reuse
    materialized device tables ACROSS statements — e.g. query14_part1 and
    _part2 share their cross_items/avg_sales CTEs, and a run_script's
    statements share repeated subtrees. Cleared whenever the catalog
    changes (any registration, drop, or invalidation)."""

    def __init__(self, budget_bytes: int):
        from collections import OrderedDict

        self.budget = budget_bytes
        self.map = OrderedDict()  # fp -> (table, nbytes)
        self.nbytes = 0
        self.scalars = {}  # fp -> (value, dtype, dictionary)

    # the one byte-estimation rule, shared with the obs op_span est_bytes
    # field (engine/columnar.py:table_device_bytes)
    _table_bytes = staticmethod(table_device_bytes)

    def get(self, fp):
        hit = self.map.get(fp)
        if hit is None:
            return None
        self.map.move_to_end(fp)
        return hit[0]

    def put(self, fp, table):
        if fp in self.map:
            self.map.move_to_end(fp)
            return
        nb = self._table_bytes(table)
        if nb > self.budget:
            return
        self.map[fp] = (table, nb)
        self.nbytes += nb
        while self.nbytes > self.budget and len(self.map) > 1:
            _, (_, old_nb) = self.map.popitem(last=False)
            self.nbytes -= old_nb

    def clear(self):
        self.map.clear()
        self.scalars.clear()
        self.nbytes = 0


class _Entry:
    def __init__(self, schema=None, arrow=None, path=None, fmt=None):
        self.schema = schema  # nds_tpu Schema or None (infer)
        self.arrow = arrow  # pa.Table (in-memory)
        self.path = path  # file/dir path
        self.fmt = fmt  # parquet | csv | orc | lakehouse
        self.device_cols = {}  # per-column device cache: name -> Column
        self.nrows = None
        # lakehouse snapshot pin (fmt == "lakehouse" only): the manifest
        # version this entry's reads resolve against, the TableSnapshot
        # handle itself, and the reader lease registered for it
        # (lakehouse/leases.py) so vacuum never deletes pinned files
        self.pinned_version = None
        self.pinned_snapshot = None
        self.lease_id = None
        # declared-PK verification memo: None = not checked yet, else bool.
        # The TABLE_PRIMARY_KEYS claim is about the DATA, and a table
        # registered under a TPC-DS name may hold anything (synthetic test
        # tables) — so the claim is checked against the actual rows once
        # before any join relies on it.
        self.pk_verified = None
        # a fact table that could not row-shard over the session mesh and
        # fell back to full replication (Catalog._to_device); the
        # verifier's replicated-dim rule flags scans of such tables so the
        # fallback can never stay a log line
        self.mesh_fallback = False


class Catalog:
    # device-column cache budget: stay well under the 16 GB v5e HBM so
    # query intermediates (which can transiently need several GB) never
    # collide with table residency; least-recently-used tables evict first
    DEVICE_BUDGET_BYTES = int(
        os.environ.get("NDS_CATALOG_BUDGET_BYTES", 6 << 30)
    )

    def __init__(self, session):
        self.session = session
        self.entries = {}  # name -> _Entry
        # recency tick for catalog-entry LRU: a lost increment under a
        # concurrent bump only perturbs eviction recency, never
        # correctness — unguarded by design
        self._use_tick = 0  # nds-guarded-by: none
        # lakehouse pin holds (thread-local): table names whose snapshot
        # pin a DML statement froze for its own nested reads — auto-pin
        # must not re-resolve them mid-transaction (lakehouse/dml.py)
        self._pin_holds = threading.local()

    def _cached_bytes(self, e) -> int:
        total = 0
        for c in e.device_cols.values():
            total += int(c.data.nbytes)
            if c.valid is not None:
                total += int(c.valid.nbytes)
        return total

    def _evict_to_budget(self, keep_name):
        total = sum(self._cached_bytes(e) for e in self.entries.values())
        if total <= self.DEVICE_BUDGET_BYTES:
            return
        victims = sorted(
            (
                (name, e)
                for name, e in self.entries.items()
                if name != keep_name and e.device_cols
            ),
            key=lambda kv: getattr(kv[1], "last_use", 0),
        )
        for name, e in victims:
            total -= self._cached_bytes(e)
            e.device_cols = {}
            # routine budget management, NOT a task failure: reporting it
            # through the listener channel would flip successful queries
            # to CompletedWithTaskFailures
            print(f"catalog: evicted device columns of {name!r} (budget)")
            if total <= self.DEVICE_BUDGET_BYTES:
                return

    def schema(self, name):
        e = self.entries.get(name)
        if e is None:
            return None
        if e.schema is not None:
            return e.schema
        # infer a Schema facade from arrow metadata
        at = self._arrow_schema(e)
        from ..schema import Schema, Field
        from .columnar import _infer_dtype

        return Schema(
            tuple(Field(f.name, _infer_dtype(f.type)) for f in at)
        )

    def _dataset(self, e: _Entry, snapshot=None, files=None):
        # hive partitioning discovery: the transcode phase writes fact tables
        # as <date_sk>=<value>/ directories; declare the partition field type
        # from the table schema so keys round-trip with the right dtype
        if e.fmt == "lakehouse":
            from ..lakehouse.table import LakehouseTable

            # snapshot-isolated read: a pinned entry resolves against its
            # plan-time manifest version — a racing replace()/append()
            # cannot change what this query sees. Unpinned (direct/legacy)
            # access still resolves the head once per dataset build.
            # `snapshot` (when the caller captured one) wins outright:
            # load() passes its plan's handle so a concurrent re-pin of
            # the entry cannot swap the manifest mid-read.
            snap = snapshot if snapshot is not None else e.pinned_snapshot
            if snap is None:
                snap = LakehouseTable(e.path).snapshot()
            # `files`: a zone-map pruned subset of the snapshot's file
            # list (Scan.lake_files) — the point where pruning becomes
            # skipped IO rather than a plan annotation
            return snap.dataset(files=files)
        part = "hive"
        fmt = e.fmt
        if e.schema is not None:
            from ..schema import TABLE_PARTITIONING

            use_decimal = self.session.use_decimal
            names = {f.name for f in e.schema}
            pcols = [c for c in TABLE_PARTITIONING.values() if c in names]
            if pcols:
                part = pads.partitioning(
                    pa.schema(
                        [
                            (c, e.schema.field(c).dtype.to_arrow(use_decimal))
                            for c in pcols
                        ]
                    ),
                    flavor="hive",
                )
            if e.fmt == "csv":
                # transcoded csv warehouse (comma-delimited, with header):
                # parse columns to the declared schema types
                import pyarrow.csv as pacsv

                fmt = pads.CsvFileFormat(
                    convert_options=pacsv.ConvertOptions(
                        column_types={
                            f.name: f.dtype.to_arrow(use_decimal)
                            for f in e.schema
                            if f.name not in pcols
                        },
                        strings_can_be_null=True,
                    )
                )
        return pads.dataset(e.path, format=fmt, partitioning=part)

    def _arrow_schema(self, e: _Entry):
        if e.arrow is not None:
            return e.arrow.schema
        return self._dataset(e).schema

    # ---- lakehouse snapshot pins ----------------------------------------
    def pin_lakehouse(self, name, version=None):
        """Resolve (or restore) a lakehouse entry's snapshot pin.

        `version=None` resolves the current head ONCE and pins it — unless
        the name is held (a DML transaction froze it for its nested reads).
        When the pin moves (the table advanced under us, or a plan carries
        an explicit older pin), every cached device column and plan result
        derived from the old snapshot is invalidated first. The pin is
        registered in the process-wide reader-lease table so a concurrent
        vacuum can never delete the pinned snapshot's files. Returns the
        pinned version, or None for non-lakehouse names."""
        e = self.entries.get(name)
        if e is None or e.fmt != "lakehouse":
            return None
        from ..lakehouse.leases import LEASES, resolve_lease_ttl
        from ..lakehouse.table import LakehouseTable

        held = getattr(self._pin_holds, "names", None)
        if version is None and held and name in held:
            return e.pinned_version
        lt = LakehouseTable(e.path, conf=self.session.conf)
        snap = lt.snapshot(version)
        ttl = resolve_lease_ttl(self.session.conf)
        if e.pinned_version != snap.version:
            # the pin moves: anything cached from the old snapshot is
            # stale (device columns, plan results, join orders)
            self.invalidate(name)
            e.pinned_version = snap.version
            e.pinned_snapshot = snap
            # registers locally AND (catalog mode) in the fleet catalog,
            # so a vacuum on another host respects this pin too
            e.lease_id = lt.acquire_reader_lease(snap, ttl)
        else:
            if e.pinned_snapshot is None:
                e.pinned_snapshot = snap
            if e.lease_id is None or not LEASES.renew(e.lease_id, ttl):
                e.lease_id = lt.acquire_reader_lease(snap, ttl)
        return e.pinned_version

    def hold_pins(self, names):
        """Context manager freezing the named tables' pins for this thread:
        nested statements (a DML's survivor scan, scalar subqueries) keep
        reading the transaction's snapshot instead of re-resolving the
        head mid-transaction."""
        import contextlib

        holds = self._pin_holds

        @contextlib.contextmanager
        def _hold():
            prev = getattr(holds, "names", None)
            holds.names = frozenset(prev or ()) | {
                str(n).lower() for n in names
            }
            try:
                yield
            finally:
                holds.names = prev

        return _hold()

    def load(self, name, columns=None, lake_version=None,
             lake_files=None) -> Table:
        """Load (a projection of) a table to device, caching per column so
        repeated queries over different column subsets never re-read or
        re-upload what is already in HBM.

        `lake_version`: the plan-time snapshot pin this scan must read
        (engine/exec.py threads it from Scan.lake_version). When another
        statement has since moved the entry's pin, the entry is re-pinned
        to the scan's version first — per-plan snapshot isolation even on
        a session shared by concurrent streams.

        `lake_files`: a zone-map pruned subset of the pinned snapshot's
        file list (Scan.lake_files). Subset loads NEVER touch the entry's
        device-column cache — cached columns are the FULL table's, and a
        pruned read mixed into them would poison every later scan — so
        they take the detached path: read exactly those files, serve the
        plan directly (the same isolation shape as version-detached
        reads)."""
        e = self.entries.get(name)
        if e is None:
            raise KeyError(f"unknown table {name}")
        if (
            lake_version is not None
            and e.fmt == "lakehouse"
            and e.pinned_version != lake_version
            # FORWARD-only re-pin: a plan AHEAD of the entry (a fresh
            # statement after a commit) moves the shared pin up. A plan
            # BEHIND it (another statement already advanced the shared
            # entry on this serve/throughput session) must NOT yank the
            # pin — and the newer pin's lease + device cache — backward
            # out from under the newer statements: it reads its own
            # older snapshot DETACHED below, under its own lease.
            and (e.pinned_version is None or lake_version > e.pinned_version)
        ):
            self.pin_lakehouse(name, version=lake_version)
        self._use_tick += 1
        e.last_use = self._use_tick
        if columns is None:
            sch = self.schema(name)
            columns = sch.names
        from .. import faults

        if faults.active():
            # io/oom injection site for table loads (e.g. io:store_sales:2
            # exercises the transient-IO ladder rung end to end)
            faults.maybe_fire(f"load:{name}")
            faults.maybe_fire(name)
        # the thread-bound tracer wins over the session's: a serve
        # request's per-request forwarding tracer is bound around the
        # execution (obs_trace.bind), so its catalog loads carry the
        # request's trace_id/tenant instead of the shared session stream
        from ..obs import trace as _obs_trace

        tracer = _obs_trace.current() or getattr(self.session, "tracer", None)
        t0 = _perf() if tracer is not None else 0.0
        # capture THIS load's snapshot handle: a concurrent stream
        # re-pinning the shared entry must not swap the manifest (or the
        # column cache) out from under an in-flight read. When the
        # captured pin does not match the PLAN's version (the entry was
        # re-pinned between our pin attempt above and this capture), the
        # load detaches: it resolves the plan's own snapshot and serves
        # it without touching the entry cache at all — cached columns
        # belong to the other pin now.
        snap = e.pinned_snapshot
        subset = e.fmt == "lakehouse" and lake_files is not None
        detached = (
            e.fmt == "lakehouse"
            and lake_version is not None
            and (snap is None or snap.version != lake_version)
        )
        if detached:
            from ..lakehouse.leases import resolve_lease_ttl
            from ..lakehouse.table import LakehouseTable

            lt = LakehouseTable(e.path, conf=self.session.conf)
            snap = lt.snapshot(lake_version)
            # a detached read is not covered by the entry's lease (that
            # belongs to the entry's pin, possibly a different version):
            # register its own TTL-bounded lease BEFORE reading so a
            # concurrent vacuum cannot delete this snapshot's files
            # mid-scan. No release point exists (the statement may keep
            # re-loading), so expiry is the TTL's job — the lease
            # table's documented leak bound.
            lt.acquire_reader_lease(
                snap, resolve_lease_ttl(self.session.conf)
            )
        if subset and not columns:
            # zero-projection pruned scan (count-style): the row count
            # must come from the pruned subset, never the entry's cached
            # full-table nrows
            ds = self._dataset(e, snapshot=snap, files=list(lake_files))
            return Table({}, ds.count_rows())
        missing = (
            list(columns) if detached or subset
            else [c for c in columns if c not in e.device_cols]
        )
        if missing:

            def _load(cols_to_load):
                arrow = e.arrow
                if arrow is None:
                    arrow = self._dataset(
                        e, snapshot=snap,
                        files=(list(lake_files) if subset else None),
                    ).to_table(columns=cols_to_load)
                else:
                    arrow = arrow.select(cols_to_load)
                return self._to_device(name, arrow, e)

            try:
                t = _load(missing)
            except Exception as exc:  # recoverable device OOM: drop + retry
                if "RESOURCE_EXHAUSTED" not in str(exc):
                    raise
                # full recovery (plan cache included) — a retained result
                # cache could otherwise keep the reload OOMing
                self.session.recover_memory("device memory exhausted "
                                            f"loading {name!r}")
                # the wipe dropped this entry's cache too — reload the full
                # requested column set, not just the previously-missing ones
                t = _load(columns)
                self.session.notify_failure(
                    f"task retry: device memory exhausted loading {name!r}; "
                    f"dropped cached tables and reloaded"
                )
            if detached or subset or (
                snap is not None and e.pinned_snapshot is not snap
            ):
                # detached up front, or a concurrent stream re-pinned the
                # entry mid-load: serve THIS plan's snapshot (reloading
                # any columns that came from the entry cache, which now
                # belongs to the other pin) and leave the cache alone —
                # per-plan isolation without cross-version cache poisoning
                if set(missing) != set(columns):
                    t = _load(columns)
                if tracer is not None:
                    tracer.emit(
                        "catalog_load", table=name, columns=len(columns),
                        loaded=len(columns), rows=t.nrows,
                        dur_ms=round((_perf() - t0) * 1000.0, 3),
                        cache="miss",
                    )
                return Table(
                    {c: t.columns[c] for c in columns}, t.nrows
                )
            e.nrows = t.nrows
            e.device_cols.update(t.columns)
            self._evict_to_budget(keep_name=name)
        if e.nrows is None:
            # all requested columns cached but nrows unset (can't happen in
            # practice; guard for empty column list)
            e.nrows = 0
        if tracer is not None:
            tracer.emit(
                "catalog_load",
                table=name,
                columns=len(columns),
                loaded=len(missing),
                rows=e.nrows,
                dur_ms=round((_perf() - t0) * 1000.0, 3),
                cache=(
                    "hit" if not missing
                    else "miss" if len(missing) == len(columns)
                    else "partial"
                ),
            )
        from ..schema import TABLE_PRIMARY_KEYS

        out = Table({c: e.device_cols[c] for c in columns}, e.nrows)
        pk = TABLE_PRIMARY_KEYS.get(name)
        if pk is not None and all(c in columns for c in pk):
            if e.pk_verified is None:
                st = (
                    out.columns[pk[0]].stats if len(pk) == 1 else None
                )
                if st is not None and st.unique:
                    # single-column PK: ingest-time host stats already
                    # know distinctness — zero device work
                    e.pk_verified = True
                else:
                    # composite PK (the 7 fact tables), or a single-column
                    # PK whose ingest stats didn't establish uniqueness
                    # (stats skip count_distinct above a row threshold —
                    # unique=False there means UNKNOWN): one-time device
                    # sort + sync, memoized until DML invalidates
                    e.pk_verified = _pk_holds(out, pk)
            if e.pk_verified:
                out.unique_key = frozenset(pk)
        return out

    def _to_device(self, name, arrow, e: _Entry):
        t = table_from_arrow(arrow, e.schema, with_stats=True)
        mesh = self.session.mesh
        if mesh is None:
            return t
        # mesh placement: fact tables shard on rows over the `data` axis,
        # dimension tables replicate — the star-query layout (partial agg +
        # psum over ICI; dim joins stay chip-local gathers)
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as PS

        from ..schema import TABLE_PARTITIONING
        from .columnar import Column as Col

        n_dev = mesh.devices.size

        if jax.process_count() > 1:
            # multi-process (DCN tier): device_put cannot target
            # non-addressable devices; build the global array from each
            # process's local slices of the host copy instead (the
            # hosts-read-own-chunks ingestion path uses
            # multihost.shard_rows_across_hosts directly)
            import numpy as np

            def _put(x, spec):
                host = np.asarray(x)
                return jax.make_array_from_callback(
                    host.shape, spec, lambda idx: host[idx]
                )
        else:
            _put = jax.device_put

        cols = {}
        warned = False
        for cname, c in t.columns.items():
            if name in TABLE_PARTITIONING:
                if c.data.shape[0] % n_dev == 0:
                    spec = NamedSharding(mesh, PS("data"))
                else:
                    # capacities are power-of-two buckets, so this only
                    # happens on a non-power-of-two mesh (or cap < n_dev);
                    # never degrade a fact table to full replication silently
                    spec = NamedSharding(mesh, PS())
                    if not warned:
                        warned = True
                        e.mesh_fallback = True
                        tracer = self.session.tracer
                        if tracer is not None:
                            # structured evidence beside the listener line:
                            # the mesh_fallback event feeds the metrics sink
                            # (nds_mesh_fallback_total) and the profiler, and
                            # the entry flag above arms the verifier's
                            # replicated-dim rule for every later plan that
                            # scans this table
                            tracer.emit(
                                "mesh_fallback", table=name, n_dev=int(n_dev),
                                cap=int(c.data.shape[0]),
                                bytes=int(sum(
                                    tc.data.nbytes + (
                                        tc.valid.nbytes
                                        if tc.valid is not None else 0
                                    )
                                    for tc in t.columns.values()
                                )),
                            )
                        self.session.notify_failure(
                            f"sharding fallback: fact table {name!r} "
                            f"(cap {c.data.shape[0]}) is not divisible by "
                            f"the {n_dev}-device mesh; replicating instead "
                            f"of row-sharding"
                        )
            else:
                spec = NamedSharding(mesh, PS())
            valid = None if c.valid is None else _put(c.valid, spec)
            cols[cname] = Col(
                _put(c.data, spec), c.dtype, valid, c.dictionary,
                c.stats,
            )
        return Table(cols, t.nrows)

    def renew_lake_leases(self) -> int:
        """Renew every lakehouse entry's reader lease (local table +
        catalog write-through) — the memwatch heartbeat calls this so a
        statement outliving `engine.lake_lease_ttl_s` (a slow SF100-scale
        scan) can never have its pinned snapshot vacuumed mid-read; the
        pre-heartbeat behavior only renewed on re-resolution. Returns the
        number of leases renewed. Best-effort: an expired lease is left
        for the next pin_lakehouse to re-acquire (the files it protected
        are re-checked through the plan's own detached path)."""
        from ..lakehouse.leases import LEASES, resolve_lease_ttl

        ttl = resolve_lease_ttl(self.session.conf)
        renewed = 0
        for e in list(self.entries.values()):
            if e.fmt == "lakehouse" and e.lease_id is not None:
                try:
                    if LEASES.renew(e.lease_id, ttl):
                        renewed += 1
                except Exception:
                    continue  # renewal must never take a query down
        return renewed

    def invalidate(self, name):
        self.session._catalog_changed()
        e = self.entries.get(name)
        if e is not None:
            e.device_cols = {}
            e.nrows = None
            # DML may have broken (or restored) the declared PK; re-verify
            # on next load before any join trusts the uniqueness claim
            e.pk_verified = None
            # drop the snapshot pin: the next statement re-resolves (and
            # re-leases) the head at its own plan time
            e.pinned_version = None
            e.pinned_snapshot = None
            if e.lease_id is not None:
                from ..lakehouse.leases import LEASES

                LEASES.release(e.lease_id)
                e.lease_id = None


class Result:
    """Executed query result."""

    def __init__(self, session, plan_node):
        self.session = session
        self.plan = plan_node
        self._table = None
        self.executor = None  # kept so callers can read per-query stats
        # (e.g. last_blocked_union) without racing other sessions' threads

    def table(self, tracer=None) -> Table:
        """Execute (memoized). `tracer` overrides the executor's event
        destination for THIS execution — serve mode passes a per-request
        forwarding tracer so every op_span/exec_cache event carries the
        request id + tenant instead of aliasing across concurrent
        requests on the shared session."""
        if self._table is None:
            self.executor = self.session._executor(tracer=tracer)
            self._table = self.executor.execute(self.plan)
            # commit this statement's buffered cardinality records (one
            # merge+write per touched key, not per executed node) so a
            # second run — or another process sharing the store dir —
            # plans from what this one measured
            store = getattr(self.session, "feedback_store", None)
            if store is not None:
                with self.session.cache_lock:
                    store.flush()
        return self._table

    def collect(self, tracer=None) -> pa.Table:
        return table_to_arrow(self.table(tracer=tracer))

    def to_pylist(self):
        return self.collect().to_pylist()

    def num_rows(self):
        return self.table().nrows

    def explain(self) -> str:
        return P.explain(self.plan)

    def write_parquet(self, path):
        import pyarrow.parquet as pq

        pq.write_table(self.collect(), path)

    def write(self, path, fmt="parquet", transform=None):
        """Write the result as a single-file dataset dir `path/part-0.<fmt>`
        (the layout the validator reads back; reference analogue:
        df.write.format(fmt).save(path), nds/nds_power.py:132-135).
        `transform(arrow) -> arrow` hooks callers like the Power Run's
        column-name sanitizer in before the write."""
        import pyarrow.csv as pacsv
        import pyarrow.parquet as pq

        os.makedirs(path, exist_ok=True)
        arrow = self.collect()
        if transform is not None:
            arrow = transform(arrow)
        if fmt == "parquet":
            pq.write_table(arrow, os.path.join(path, "part-0.parquet"))
        elif fmt == "csv":
            pacsv.write_csv(arrow, os.path.join(path, "part-0.csv"))
        else:
            raise ValueError(f"unsupported output format {fmt}")


class Session:
    def __init__(
        self,
        use_decimal: bool = True,
        conf: Optional[dict] = None,
        mesh=None,
    ):
        """mesh: optional jax.sharding.Mesh with a `data` axis. When set,
        fact-table scans shard rows across the mesh and dimension tables
        replicate, so query execution runs SPMD over all devices (the
        reference scales via Spark executors/shuffle partitions instead:
        nds/base.template:28-31)."""
        _enable_persistent_compile_cache()
        self.use_decimal = use_decimal
        self.conf = dict(conf or {})  # engine options (property-file tier)
        # failure-domain: install any configured fault-injection spec
        # (conf engine.fault_spec / env NDS_FAULT_SPEC) so engine-level
        # injection points are armed; idempotent for an unchanged spec, so
        # per-stream sessions in a throughput run share one fire budget
        from .. import faults

        faults.install_from_env(self.conf)
        # observability: with a trace dir configured (conf engine.trace_dir
        # / env NDS_TRACE_DIR) every executor, catalog load, and harness
        # report emits structured events into this session's own
        # events-<appid>.jsonl (rotating at engine.trace_rotate_bytes when
        # set); None = tracing disabled at zero cost
        from ..obs.trace import tracer_from_conf

        self.tracer = tracer_from_conf(self.conf)
        # live telemetry (obs/metrics.py + obs/httpserv.py): with
        # engine.metrics_port / NDS_METRICS_PORT set, tracer_from_conf
        # started the process-wide /metrics + /statusz endpoint and
        # attached its MetricsSink to the tracer (building a sink-only
        # tracer when no trace dir is configured) — one resolution path,
        # so session.metrics and tracer.sink can never disagree. With
        # neither knob set the hot path keeps its `tracer is None` check.
        self.metrics = getattr(self.tracer, "sink", None)
        self.mesh = mesh
        self.catalog = Catalog(self)
        self._listeners = []  # task-failure observers  # nds-guarded-by: cache_lock
        self.plan_cache = _PlanResultCache(  # nds-guarded-by: cache_lock
            int(self.conf.get("engine.plan_cache_bytes", 1 << 30))
        )
        # fused-pipeline executable reuse (engine/fuse.py): survives catalog
        # changes on purpose — entries are keyed by stage structure + dtype
        # signature + dictionary identity, so a stale entry can never be
        # wrongly hit, and the per-query temp-view churn of a power stream
        # must not evict the stream-wide executables
        from .fuse import ExecutableCache

        self.exec_cache = ExecutableCache(  # nds-guarded-by: cache_lock
            int(self.conf.get("engine.exec_cache_entries", 512))
        )
        # persistent AOT executable cache (engine/aotcache.py): fused
        # pipelines resolve per-bucket compiled executables through it, so
        # a FRESH PROCESS deserializes from disk instead of recompiling —
        # cold start is paid once per environment, ever. Single-device
        # sessions only: under a mesh the inputs are sharded and the
        # lowered-without-shardings avals would not describe them; and
        # multi-process loads cannot target non-addressable devices.
        # Disable with NDS_AOT_CACHE_DIR=0 / engine.aot_cache_dir="".
        from .aotcache import (
            AotCache,
            PromotionStore,
            resolve_aot_cache_bytes,
            resolve_aot_cache_dir,
            sweep_at_session_start as _aot_sweep,
        )

        self.aot_cache = None
        self.promotion_store = None
        _aot_dir = resolve_aot_cache_dir(self.conf)
        if _aot_dir:
            # promotion memos persist even where executables cannot (the
            # verdicts are keyed by backend environment, not by sharding)
            self.promotion_store = PromotionStore(_aot_dir)
            if mesh is None:
                import jax as _jax

                if _jax.process_count() == 1:
                    _aot_sweep(_aot_dir)
                    self.aot_cache = AotCache(
                        _aot_dir,
                        resolve_aot_cache_bytes(self.conf, _aot_dir),
                        tracer=lambda: self.tracer,
                    )
        # estimate-vs-actual cardinality feedback (analysis/feedback.py):
        # persistent (node_fp, scale)-keyed actuals shared across
        # processes and serve replicas. Rides the AOT cache dir by
        # default (<dir>/feedback) so the --aot_cache_dir fleet wiring
        # shares learned cardinalities exactly like compiled
        # executables; works under a mesh (JSON stats, no executables).
        # Disable with NDS_FEEDBACK_DIR=0 / engine.plan_feedback=off.
        from ..analysis.feedback import (
            FeedbackStore,
            resolve_feedback_bytes,
            resolve_feedback_dir,
        )

        self.feedback_store = None
        _fb_dir = resolve_feedback_dir(self.conf)
        if _fb_dir:
            _aot_sweep(_fb_dir)  # same .tmp-<pid> naming scheme
            self.feedback_store = FeedbackStore(
                _fb_dir, resolve_feedback_bytes(self.conf, _fb_dir)
            )
        # stats of the most recent blocked union-aggregation any executor
        # of this session ran (bench.py's OOM-bail heuristic reads it)
        self.last_blocked_union = None
        # MultiJoin greedy-order memo: fingerprint -> recorded join steps
        # (exec._multijoin_greedy). Replaying skips the per-step blocking
        # row-count syncs of the cost scan on every re-execution.
        self.join_order_cache = {}  # nds-guarded-by: cache_lock
        # Pallas promotion memo (engine.pallas_agg=auto): per
        # (fn, rows, group-cap) shape, the measured jnp-vs-Pallas A/B and
        # the winning route (exec._pallas_promoted). Session-lived: the
        # measurement is backend-stable, so one A/B covers every re-run.
        self.pallas_promotions = {}
        # one lock guards every session-level cache mutation (plan_cache,
        # exec_cache, join_order_cache, pallas_promotions): the serve work
        # (ROADMAP item 4) makes these multi-tenant, and the
        # cache-lock-discipline lint flags unguarded mutations. RLock: the
        # recovery path clears caches from inside already-locked regions.
        self.cache_lock = make_lock(
            "Session.cache_lock", self.conf, reentrant=True
        )
        # static plan-budget verdict of the most recent statement
        # (analysis/budget.py budget_plan); the report ladder's first
        # device-OOM rung consumes the window recommendation
        self.last_plan_budget = None
        # host-RSS watermark pre-emption flag (obs.memwatch -> report.py):
        # the blocked-union window loop polls it between windows and
        # shrinks the remaining windows when set
        self._mem_pressure = False
        # watermark hysteresis latch (report.py): True while the process
        # RSS excursion that last fired the watermark is still above it,
        # so one crossing shrinks the window once, not once per query
        self._rss_above_watermark = False
        # out-of-core tier (engine/spill.py): the host-RAM spill pool is
        # built lazily on first spill; session start sweeps segment files a
        # previous CRASHED process left in the spill dir (once per process
        # per directory — the manifest/fingerprint-guarded orphan sweep)
        from .spill import resolve_spill_dir, sweep_at_session_start

        self._spill_pool = None  # nds-guarded-by: cache_lock
        sweep_at_session_start(resolve_spill_dir(self.conf))
        # marker (like last_blocked_union): stats of the most recent
        # statement that routed through an out-of-core spill path; harness
        # loops reset it per statement and read it as spill evidence
        self.last_spill = None
        # liveness beat of the most recent spill partition/run/merge phase
        # (monotonic seconds): the report watchdog re-arms while a healthy
        # out-of-core op keeps beating, so a long external sort is not
        # misclassified as a hang (report.BenchReport._attempt)
        # single atomic tuple store, read by the report watchdog from
        # another thread; an object-reference store cannot tear
        self._progress_ts = None  # nds-guarded-by: none

    @property
    def spill_pool(self):
        """The session's host-RAM spill pool (engine/spill.py), built on
        first use. Knobs: `engine.spill_pool_bytes` / NDS_SPILL_POOL_BYTES
        (host budget before segments tier to disk), `engine.spill_dir` /
        NDS_SPILL_DIR (disk tier; empty string disables it)."""
        if self._spill_pool is None:
            from .spill import SpillPool, resolve_pool_bytes, resolve_spill_dir

            with self.cache_lock:
                if self._spill_pool is None:
                    self._spill_pool = SpillPool(
                        budget_bytes=resolve_pool_bytes(self.conf),
                        spill_dir=resolve_spill_dir(self.conf),
                        app_id=getattr(self.tracer, "app_id", None),
                    )
        return self._spill_pool

    def spill_progress(self):
        """Stamp out-of-core progress (called by the executor's spill paths
        per partition/run): the per-query watchdog reads this to tell a
        slow-but-alive external sort/merge from a genuine hang. The beat
        carries the beating thread's identity so the watchdog only honors
        beats from ITS OWN attempt's worker — an abandoned previous
        attempt's zombie worker keeps beating on the shared session, and
        those beats must not shield the next query's genuine hang."""
        self._progress_ts = (threading.get_ident(), _monotonic())

    def _catalog_changed(self):
        """Any registration/drop/invalidation: cached plan results may now
        be stale — drop them all."""
        with self.cache_lock:
            self.plan_cache.clear()
            # join orders are only a perf heuristic, but sizes may have
            # shifted enough to make a recorded order pathological
            self.join_order_cache.clear()

    def union_agg_window_rows(
        self, row_bytes: int, static_rows: Optional[int] = None
    ) -> int:
        """Rows per window for blocked union-aggregation (engine/exec.py).

        Resolution order: `engine.union_agg_window_rows` session conf, then
        the NDS_UNION_AGG_WINDOW_ROWS env knob (both honored exactly —
        tests force tiny windows through them), then `static_rows` (the
        plan budgeter's statically chosen `budget_window_rows` annotation,
        analysis/budget.py), else derived at runtime by the same formula
        the budgeter uses (budget.default_window_rows) against the
        catalog's device budget — plan-time and runtime sizing share one
        derivation so they cannot drift."""
        v = self.conf.get("engine.union_agg_window_rows") or os.environ.get(
            "NDS_UNION_AGG_WINDOW_ROWS"
        )
        if v:
            return max(int(v), 1)
        if static_rows:
            return max(int(static_rows), 1)
        from ..analysis import budget as _budget

        return _budget.default_window_rows(
            row_bytes, self.catalog.DEVICE_BUDGET_BYTES
        )

    # ---- registration ----------------------------------------------------
    def register_arrow(self, name, arrow: pa.Table, schema=None):
        self._catalog_changed()
        self.catalog.entries[name.lower()] = _Entry(schema=schema, arrow=arrow)

    def register_parquet(self, name, path, schema=None):
        self._catalog_changed()
        self.catalog.entries[name.lower()] = _Entry(
            schema=schema, path=path, fmt="parquet"
        )

    def register_orc(self, name, path, schema=None):
        self._catalog_changed()
        self.catalog.entries[name.lower()] = _Entry(
            schema=schema, path=path, fmt="orc"
        )

    def register_csv_dir(self, name, path, schema):
        """Raw pipe-delimited .dat directory (generator output layout)."""
        from ..io.csv import read_dat_dir

        arrow = read_dat_dir(path, schema, self.use_decimal)
        self.register_arrow(name, arrow, schema)

    def register_csv_warehouse(self, name, path, schema):
        """Transcoded csv warehouse dir (comma-delimited part files, possibly
        hive-partitioned) — lazy, like parquet registration."""
        self._catalog_changed()
        self.catalog.entries[name.lower()] = _Entry(
            schema=schema, path=path, fmt="csv"
        )

    def register_lakehouse(self, name, path, schema=None):
        """Snapshot-manifest (ACID) table — the Iceberg/Delta-equivalent
        warehouse format used by the Data Maintenance phase. Registration
        runs the once-per-process crash-hygiene sweep: a previous CRASHED
        writer's staged-but-uncommitted data files and torn manifest
        temps are removed before any query reads the table."""
        from ..lakehouse.table import sweep_table_at_session_start

        sweep_table_at_session_start(path)
        self._catalog_changed()
        self.catalog.entries[name.lower()] = _Entry(
            schema=schema, path=path, fmt="lakehouse"
        )

    def register_nds_tables(self, data_root, fmt="parquet", maintenance=False):
        """Register all source (or maintenance) tables under a warehouse dir."""
        schemas = (
            get_maintenance_schemas(self.use_decimal)
            if maintenance
            else get_schemas(self.use_decimal)
        )
        import posixpath

        from ..io.fs import get_fs, join as fs_join

        fs, root = get_fs(data_root)
        if fmt == "lakehouse":
            from ..lakehouse.table import sweep_table_at_session_start
        for tname, schema in schemas.items():
            if fs.exists(posixpath.join(root, tname)):
                path = fs_join(data_root, tname)
                if fmt == "lakehouse":
                    # session-start crash hygiene, once per process/table
                    sweep_table_at_session_start(path)
                self.catalog.entries[tname] = _Entry(
                    schema=schema, path=path, fmt=fmt
                )

    def drop(self, name):
        self._catalog_changed()
        self.catalog.entries.pop(name.lower(), None)

    # ---- memory recovery -------------------------------------------------
    def recover_memory(self, reason: str = "device OOM"):
        """Drop every recoverable device allocation: the plan-result cache
        and all cached catalog columns. Called by the harness loops when a
        query dies with RESOURCE_EXHAUSTED mid-execution (the catalog's
        own load-time retry cannot see those), after which the query is
        retried once against a clean device (reference analogue: Spark
        executor loss -> task retry on a fresh executor)."""
        import gc

        with self.cache_lock:
            self.plan_cache.clear()
            # fused-pipeline executables bake dictionary lookup tables in
            # as device constants; a full wipe must release those too
            # (rebuilds are cheap next to an OOM'd retry failing again)
            self.exec_cache.clear()
            self.join_order_cache.clear()
        for e in self.catalog.entries.values():
            e.device_cols = {}
        gc.collect()
        self.notify_failure(f"task retry: {reason}; dropped device caches")

    # ---- listeners (reference: python_listener/PythonListener.py) --------
    def register_listener(self, cb):
        with self.cache_lock:
            self._listeners.append(cb)

    def unregister_listener(self, cb):
        with self.cache_lock:
            try:
                self._listeners.remove(cb)
            except ValueError:
                pass

    def notify_failure(self, reason: str):
        """Fan a recoverable task-failure event out to listeners (reference:
        jvm_listener Manager.notifyAll -> PythonListener.notify)."""
        with self.cache_lock:
            listeners = list(self._listeners)
        for cb in listeners:
            cb(reason)

    # ---- SQL -------------------------------------------------------------
    def _executor(self, tracer=None):
        return Executor(
            self.catalog, on_task_failure=self.notify_failure, tracer=tracer
        )

    def sql(self, text: str) -> Result:
        stmt = parse_sql(text)
        return self.run_stmt(stmt)

    def plan_sql(self, text: str):
        """Parse + plan ONE SELECT statement atomically with respect to
        every other planner on this session, returning
        `(Result, plan-budget record)`.

        Serve mode's admission path needs the budgeter verdict that
        belongs to THIS statement: `last_plan_budget` is a single field
        on a session shared across concurrent tenants, so planning and
        verdict capture must be one critical section (held under
        `cache_lock`, the same lock the plan caches already take) or two
        requests could read each other's verdicts. Execution stays
        outside the lock — only planning serializes. A `reject` verdict
        raises PlanBudgetError out of here, BEFORE anything dispatches
        (the serve 429 path)."""
        stmts = parse_script(text)
        if len(stmts) != 1 or not isinstance(stmts[0], A.SelectStmt):
            raise ValueError(
                "plan_sql takes exactly one SELECT statement "
                f"(got {len(stmts)} statement(s))"
            )
        return self.plan_stmt(stmts[0])

    def plan_stmt(self, stmt):
        """`plan_sql` over an already-parsed SELECT statement — callers
        that parsed the text to classify it (serve's SELECT-vs-DML
        routing) must not pay a second parse inside the one lock that
        serializes every tenant's planning."""
        if not isinstance(stmt, A.SelectStmt):
            raise ValueError(
                f"plan_stmt wants a SELECT, got {type(stmt).__name__}"
            )
        with self.cache_lock:
            res = self.run_stmt(stmt)
            rec = self.last_plan_budget
            return res, (dict(rec) if isinstance(rec, dict) else None)

    def run_script(self, text: str):
        out = None
        for stmt in parse_script(text):
            out = self.run_stmt(stmt)
        return out

    def _finish_plan(self, plan, promotions=()):
        """Post-bind rewrite sequence: prune scans, annotate blocked
        union-aggregates, then fuse Filter/Project chains into pipelines
        (fusion last — the blocked-union annotation sees the raw wrappers,
        and its executor-side shape check peels Pipeline nodes).

        With `engine.verify_plans` / NDS_VERIFY_PLANS set (off by default,
        one dict lookup when off), the PlanVerifier re-checks structural
        invariants: `final` verifies the finished plan once, `all` verifies
        after binding and after EACH rewrite pass — the Catalyst-style
        analyzer re-run. Violations raise PlanVerifyError (a classified
        `planner` failure: deterministic, the report ladder fails fast) and
        emit a `plan_verify` trace event per checked stage."""
        level = self.conf.get("engine.verify_plans") or os.environ.get(
            "NDS_VERIFY_PLANS"
        )
        verify = None
        if level and str(level).lower() != "off":
            from ..analysis import verifier as _verifier

            level = _verifier.resolve_level(self.conf)

            def verify(p, stage):
                _verifier.verify_plan(
                    p, self.catalog, stage=stage, promotions=promotions,
                    tracer=self.tracer, mesh=self.mesh,
                )

        if verify is not None and level == "all":
            verify(plan, "bind")
        plan = prune_columns(plan, self.catalog)
        if verify is not None and level == "all":
            verify(plan, "prune_columns")
        # snapshot pin + zone-map pruning BEFORE the budgeter: pinning
        # here (rather than around run_stmt, where it used to live) means
        # pruning, budgeting and execution all see the SAME manifest
        # version — no window for a concurrent commit to skew the stats
        # the budget was modeled from
        self._pin_lake_scans(plan)
        self._prune_lake_scans(plan)
        P.mark_blocked_union_aggs(plan)
        if verify is not None and level == "all":
            verify(plan, "mark_blocked_union_aggs")
        if self.conf.get("engine.fuse", "on") != "off":
            from .fuse import mark_pipelines

            plan, _ = mark_pipelines(
                plan,
                # Pallas segment-reduce routes (on/auto) hook the eager
                # per-aggregate seam, so the aggregate stays a separate
                # eager node — but its feeding chain still fuses
                fuse_aggs=(
                    self.conf.get("engine.fuse_agg", "on") != "off"
                    and self.conf.get("engine.pallas_agg", "off") == "off"
                ),
            )
            if verify is not None and level == "all":
                verify(plan, "mark_pipelines")
        # static plan budgeter (analysis/budget.py): modeled peak vs the
        # working-set budget decides direct | blocked(window) | over |
        # reject BEFORE anything dispatches; `blocked` annotates the
        # statically sized window (exec consumes it), `reject` raises.
        # Runs before the final verify so the verifier's annotation-
        # coverage rule sees the budget_window_rows it just placed.
        from ..analysis.budget import budget_plan

        budget_plan(plan, self)
        if verify is not None and level == "all":
            verify(plan, "plan_budget")
        if verify is not None and level == "final":
            verify(plan, "final")
        return plan

    def _pin_lake_scans(self, plan):
        """Snapshot-isolate this statement: resolve each lakehouse scan's
        manifest version ONCE at plan time, annotate the Scan nodes with
        it (engine/exec.py threads the pin into catalog.load), and
        register the pins as reader leases. A query that scans a table
        twice — or re-executes after a device-OOM recovery wiped the
        column cache — therefore reads ONE snapshot even while a
        concurrent replace()/append() commits (Iceberg's snapshot
        isolation, per statement)."""
        if not any(
            e.fmt == "lakehouse" for e in self.catalog.entries.values()
        ):
            return plan  # no lake tables registered: zero-cost path
        pinned = {}
        for n in P.walk_plan(plan):
            if isinstance(n, P.Scan):
                if n.table not in pinned:
                    pinned[n.table] = self.catalog.pin_lakehouse(n.table)
                if pinned[n.table] is not None:
                    n.lake_version = pinned[n.table]
        return plan

    def _prune_lake_scans(self, plan):
        """Zone-map file pruning: for each Filter directly over a pinned
        lakehouse Scan, evaluate the filter's simple single-column
        conjuncts against the pinned manifest's per-file stats and
        annotate the Scan with the surviving file subset
        (Scan.lake_files; exec threads it into catalog.load so pruned
        files are never opened) and the surviving-row upper bound
        (Scan.prune_rows; the budgeter clamps its scan estimate with
        it). Purely an annotation pass — the filter still runs over
        every surviving row, so a conservative zone map costs IO, never
        correctness. `engine.lake_prune=off` disables it."""
        if str(self.conf.get("engine.lake_prune", "on")).lower() == "off":
            return plan
        from ..lakehouse.zonemap import prune_files

        for n in P.walk_plan(plan):
            if not (
                isinstance(n, P.Filter) and isinstance(n.child, P.Scan)
            ):
                continue
            scan = n.child
            if scan.lake_version is None:
                continue
            e = self.catalog.entries.get(scan.table)
            snap = e.pinned_snapshot if e is not None else None
            if snap is None or snap.version != scan.lake_version:
                continue  # detached pin: skip rather than re-resolve
            stats = snap.file_stats()
            if not stats:
                continue  # pre-stats manifest (back-compat): nothing known
            preds = _zone_preds(n.predicate, scan.alias)
            if not preds:
                continue
            t0 = _perf()
            keep, pruned_rows = prune_files(snap.rel_files, stats, preds)
            n_total = len(snap.rel_files)
            if len(keep) < n_total:
                scan.lake_files = tuple(keep)
                total = snap.num_rows()
                if total >= 0:
                    scan.prune_rows = max(total - pruned_rows, 0)
            if self.tracer is not None:
                self.tracer.emit(
                    "scan_prune", table=scan.table, files_total=n_total,
                    files_pruned=n_total - len(keep),
                    rows_bound=scan.prune_rows,
                    dur_ms=round((_perf() - t0) * 1000.0, 3),
                )
        return plan

    def run_stmt(self, stmt) -> Optional[Result]:
        if isinstance(stmt, A.SelectStmt):
            binder = Binder(self.catalog)
            plan = self._finish_plan(binder.bind(stmt), binder.promotions)
            if self.tracer is not None:
                # flight-recorder context: keep this statement's plan at
                # hand so a failure bundle carries the FAILING query's
                # plan, not a reconstruction. Noted as a LAZY thunk —
                # P.explain renders only if a bundle actually flushes, so
                # the serve hot path pays one lock + one lambda per
                # statement, never a string render
                from ..obs import flight as _obs_flight

                rec = _obs_flight.recorder(self.conf)
                if rec is not None:
                    from .. import faults as _faults

                    rec.note_plan(
                        _faults.current_scope(),
                        lambda p=plan: P.explain(p),
                    )
            return Result(self, plan)
        if isinstance(stmt, A.CreateViewStmt):
            binder = Binder(self.catalog)
            plan = self._finish_plan(
                binder.bind(stmt.query), binder.promotions
            )
            arrow = Result(self, plan).collect()
            self.register_arrow(stmt.name, arrow)
            return None
        if isinstance(stmt, A.DropViewStmt):
            self.drop(stmt.name)
            return None
        if isinstance(stmt, (A.InsertStmt, A.DeleteStmt, A.CreateTableStmt, A.CallStmt)):
            from ..lakehouse.dml import run_dml

            return run_dml(self, stmt)
        raise TypeError(f"unsupported statement {type(stmt).__name__}")


# ---------------------------------------------------------------------------
# Zone-map pruning: extract prunable conjuncts from a scan's filter
# ---------------------------------------------------------------------------

#: immutable operator-mirror lookup (literal-on-left comparisons flip);
#: never mutated
_ZONE_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}  # nds-lint: disable=mutable-module-global


def _zone_preds(pred, alias):
    """Reduce a filter predicate to the conjuncts zone maps can act on,
    as the plain tuples lakehouse/zonemap.py evaluates: column-vs-literal
    comparisons, BETWEEN, IN lists and IS NOT NULL over THIS scan's
    columns. Anything else (OR trees, expressions over the column,
    NULL literals, negated forms) is simply not extracted — unextracted
    conjuncts mean less pruning, never wrong pruning."""
    prefix = alias + "."
    out = []

    def col(e):
        if isinstance(e, E.Col) and e.name.startswith(prefix):
            return e.name.split(".", 1)[1]
        return None

    def lit(e):
        if isinstance(e, E.Lit) and e.value is not None:
            return e.value
        return None

    def walk(e):
        if isinstance(e, E.BinOp):
            if e.op == "and":
                walk(e.left)
                walk(e.right)
                return
            if e.op in _ZONE_FLIP:
                c, v = col(e.left), lit(e.right)
                if c is not None and v is not None:
                    out.append(("cmp", c, e.op, v))
                    return
                c, v = col(e.right), lit(e.left)
                if c is not None and v is not None:
                    out.append(("cmp", c, _ZONE_FLIP[e.op], v))
            return
        if isinstance(e, E.Between) and not e.negated:
            c = col(e.operand)
            lo, hi = lit(e.low), lit(e.high)
            if c is not None and lo is not None and hi is not None:
                out.append(("between", c, lo, hi))
            return
        if isinstance(e, E.InList) and not e.negated and e.values:
            c = col(e.operand)
            if c is not None:
                vals = tuple(lit(v) for v in e.values)
                if all(v is not None for v in vals):
                    out.append(("in", c, vals))
            return
        if isinstance(e, E.UnaryOp) and e.op == "isnotnull":
            c = col(e.operand)
            if c is not None:
                out.append(("notnull", c))

    walk(pred)
    return out


# ---------------------------------------------------------------------------
# Projection pruning: annotate Scans with the minimal column set
# ---------------------------------------------------------------------------


def _pk_holds(t, pk) -> bool:
    """One-time device check that the declared primary key is actually
    unique in this table's data (exact packed words via the same
    K.pack_key_words the join probes use, sort, adjacent compare; one host
    sync, memoized per catalog entry by the caller). Conservative False
    when columns aren't packable ints with stats."""
    import jax.numpy as jnp

    from ..ops import kernels as K

    cols = [t.columns[c] for c in pk]
    if any(
        c.dtype.is_string or c.dtype.is_decimal or c.stats is None
        for c in cols
    ):
        return False
    words = K.pack_key_words(
        [[(c.data, c.valid) for c in cols]],
        [(c.stats.vmin, c.stats.vmax) for c in cols],
    )
    if words is None:
        return False
    big = jnp.iinfo(jnp.int64).max
    w = jnp.where(t.row_mask(), words[0], big)
    ws = w[K.kv_sort_perm(w)]
    return not bool(jnp.any((ws[1:] == ws[:-1]) & (ws[1:] != big)))


def prune_columns(node: P.PlanNode, catalog=None) -> P.PlanNode:
    """Top-down required-column propagation; sets Scan.columns so the IO layer
    only reads/transfers what the query touches (the columnar-format win the
    reference gets from parquet + Spark column pruning)."""

    def expr_refs(e):
        return {c.name for c in E.walk(e) if isinstance(c, E.Col)}

    def visit(n, req):
        if isinstance(n, P.Scan):
            if req is None:
                n.columns = None
            else:
                bare = sorted({r.split(".", 1)[1] for r in req if r.startswith(n.alias + ".")})
                if not bare and catalog is not None:
                    # a pure row-count consumer (e.g. bare count(*)) still
                    # needs one physical column to carry the row count
                    sch = catalog.schema(n.table)
                    if sch is not None:
                        bare = [sch.names[0]]
                n.columns = bare or None
            return
        if isinstance(n, P.Project):
            child_req = set()
            for e, _ in n.items:
                child_req |= expr_refs(e)
            visit(n.child, child_req)
            return
        if isinstance(n, P.Filter):
            if req is None:
                visit(n.child, None)
            else:
                visit(n.child, req | expr_refs(n.predicate))
            return
        if isinstance(n, P.Join):
            extra = set()
            for e in n.left_keys + n.right_keys:
                extra |= expr_refs(e)
            if n.residual is not None:
                extra |= expr_refs(n.residual)
            sub = None if req is None else req | extra
            visit(n.left, sub)
            visit(n.right, sub)
            return
        if isinstance(n, P.MultiJoin):
            extra = set()
            for _, _, le, re_ in n.edges:
                extra |= expr_refs(le) | expr_refs(re_)
            if n.residual is not None:
                extra |= expr_refs(n.residual)
            sub = None if req is None else req | extra
            for r in n.relations:
                visit(r, sub)
            return
        if isinstance(n, P.Aggregate):
            child_req = set()
            for e, _ in n.keys:
                child_req |= expr_refs(e)
            for a, _ in n.aggs:
                if a.arg is not None:
                    child_req |= expr_refs(a.arg)
            visit(n.child, child_req)
            return
        if isinstance(n, P.Window):
            child_req = set() if req is None else set(req)
            for wf, _ in n.fns:
                for c in wf.children():
                    child_req |= expr_refs(c)
            visit(n.child, None if req is None else child_req)
            return
        if isinstance(n, P.Sort):
            child_req = None
            if req is not None:
                child_req = set(req)
                for e, _, _ in n.keys:
                    child_req |= expr_refs(e)
            visit(n.child, child_req)
            return
        if isinstance(n, (P.Limit, P.Distinct)):
            visit(n.child, req)
            return
        if isinstance(n, P.SetOp):
            visit(n.left, None)
            visit(n.right, None)
            return
        if isinstance(n, P.MaterializedScan):
            return
        for c in n.children():
            if c is not None:
                visit(c, None)

    visit(node, None)
    return node
