"""Host-RAM spill pool: the device->host victim tier for out-of-core ops.

The reference harness gets out-of-core resilience for free from Spark
executor spill — `power_run_gpu.template:29-36` budgets host spill memory
explicitly before a single task runs. This engine's device (HBM) tier has
no allocator-level spill underneath it, so the equivalent lives here: a
budgeted host-side pool holding the partitioned build sides, sorted runs
and distinct hash partitions the executor's out-of-core paths
(exec._spilled_join / _spilled_take / _spilled_distinct) evict from HBM.

Tiering: a spilled segment lands in host RAM first (one batched
device->host transfer, trimmed to live rows). When the pool's host budget
(`engine.spill_pool_bytes` / NDS_SPILL_POOL_BYTES) is exceeded — or the
report layer's RSS watermark pre-empts (`SpillPool.evict_host`) — the
least-recently-used segments are written to `engine.spill_dir` /
NDS_SPILL_DIR as atomic `.npz` files (temp name + rename, the fs_open_atomic
pattern) and their RAM buffers are dropped. Reads transparently reload from
disk. String dictionaries always stay in RAM: they are host-side Arrow
arrays shared by reference with live device tables, and re-serializing them
per segment would cost more than they weigh.

Crash hygiene: each pool writes one `spill-manifest-<pid>.json` (atomic,
fingerprint-guarded — same pattern as full_bench's bench_state.json) before
its first disk segment. `sweep_orphans` removes segment/temp files whose
owning process is dead, so a crashed run's spill dir never accumulates;
Session start runs it once per process per directory.

Failure domain: segment write/read/eviction are `spill:<site>` fault
injection points (io/crash kinds only — an `oom:` rule is about device
sites). Real disk errors wrap into SpillIOError, which faults.classify maps
to `io_transient`, so the report ladder's io_backoff_retry rung retries the
query instead of failing it.
"""

from __future__ import annotations

import itertools
import json
import os
import tempfile
import threading
import time
import uuid
from collections import OrderedDict

import numpy as np

from .. import faults
from .lockdebug import make_lock

#: default host-RAM budget for spilled segments — mirrors the reference's
#: explicit executor-spill sizing (power_run_gpu.template pins host pools
#: before any task runs); beyond it segments tier down to spill_dir
DEFAULT_POOL_BYTES = 4 << 30

#: partition/run count when out-of-core execution is FORCED without a
#: static recommendation — the one default shared by the executor's
#: `engine.spill=force` mode and the report ladder's spill_retry rung
#: (the budgeter's `spill` verdict sizes partitions itself)
DEFAULT_FORCE_PARTITIONS = 8

#: manifest fingerprint: sweep_orphans only ever touches files whose
#: manifest carries this magic (a shared temp dir may hold foreign files)
_MANIFEST_MAGIC = "nds-tpu-spill-pool-v1"

_SEG_PREFIX = "spill-"


class SpillError(Exception):
    pass


class SpillIOError(SpillError, OSError):
    """A spill segment write/read failed at the filesystem tier. Named so
    faults.classify maps it to `io_transient` (see faults._IO_PAT): object
    stores and overlay filesystems throttle/reset routinely, and one failed
    segment write must walk the ladder's backoff rung, not kill the query."""


def resolve_spill_dir(conf: dict | None = None) -> str | None:
    """Disk tier directory: conf `engine.spill_dir`, env NDS_SPILL_DIR,
    else a per-user default under the system temp dir. Explicit empty
    string / "0" disables the disk tier (RAM-only pool)."""
    v = None
    if conf:
        v = conf.get("engine.spill_dir")
    if v is None:
        v = os.environ.get("NDS_SPILL_DIR")
    if v is None:
        return os.path.join(tempfile.gettempdir(), "nds-tpu-spill")
    v = str(v)
    return v if v not in ("", "0") else None


#: `auto` pool sizing: 1/4 of physical host RAM, power-of-two, clamped —
#: the same share-of-a-resource derivation the union window applies to the
#: device budget (analysis/budget.derive_share_bytes; ROADMAP item 2's
#: carry-forward: SF100 working sets need the pool sized to the HOST, not
#: to a fixed 4 GiB constant)
_AUTO_POOL_FRACTION = 4
_AUTO_POOL_LO = 1 << 30
_AUTO_POOL_HI = 64 << 30


def resolve_pool_bytes(conf: dict | None = None) -> int:
    v = None
    if conf:
        v = conf.get("engine.spill_pool_bytes")
    v = v if v is not None else os.environ.get("NDS_SPILL_POOL_BYTES")
    if v is not None and str(v).lower() == "auto":
        from ..analysis.budget import derive_share_bytes, host_ram_bytes

        return derive_share_bytes(
            host_ram_bytes(), _AUTO_POOL_FRACTION,
            _AUTO_POOL_LO, _AUTO_POOL_HI,
        )
    try:
        return max(int(v), 0) if v is not None and v != "" else DEFAULT_POOL_BYTES
    except (TypeError, ValueError):
        return DEFAULT_POOL_BYTES


class SpillSegment:
    """One spilled table: per-column host buffers (or a disk path once
    evicted) + the metadata needed to rebuild a device Table exactly."""

    __slots__ = (
        "sid", "nrows", "nbytes", "names", "dtypes", "dictionaries",
        "datas", "valids", "path",
    )

    def __init__(self, sid, nrows, names, dtypes, dictionaries, datas, valids):
        self.sid = sid
        self.nrows = nrows
        self.names = names
        self.dtypes = dtypes
        self.dictionaries = dictionaries  # host-resident always (see module doc)
        self.datas = datas  # list[np.ndarray] | None when on disk
        self.valids = valids  # list[np.ndarray | None] | None when on disk
        self.path = None
        self.nbytes = sum(a.nbytes for a in datas) + sum(
            v.nbytes for v in valids if v is not None
        )


class SpillPool:
    """Budgeted host-side pool of spilled segments with an LRU disk tier.

    Thread-safe (one lock around segment bookkeeping); device transfers and
    file IO run outside the lock. `stats` is a plain dict snapshot-read by
    the executor's spill evidence (bytes_in/bytes_out/evictions/segments).
    """

    def __init__(self, budget_bytes: int | None = None,
                 spill_dir: str | None = None, app_id: str | None = None):
        self.budget = (
            budget_bytes if budget_bytes is not None else DEFAULT_POOL_BYTES
        )
        self.dir = spill_dir
        self.app = app_id or f"pid{os.getpid()}-{uuid.uuid4().hex[:6]}"
        self._seq = itertools.count()
        self._lock = make_lock("SpillPool._lock")
        # sid -> segment (RAM-resident, LRU)     # nds-guarded-by: _lock
        self._host = OrderedDict()
        self._all = {}  # sid -> segment          # nds-guarded-by: _lock
        self.host_bytes = 0  # nds-guarded-by: _lock
        self.stats = {  # nds-guarded-by: _lock
            "bytes_in": 0, "bytes_out": 0, "evictions": 0, "segments": 0,
        }
        # idempotent once-flag set by the (unlocked, by design) disk-tier
        # writer; duplicate manifest writes are atomic replaces of
        # identical content
        self._manifest_written = False  # nds-guarded-by: none
        self._ram_only_warned = False  # nds-guarded-by: _lock

    # ------------------------------------------------------------------
    def put(self, table) -> SpillSegment:
        """Spill a device Table's live rows to the host tier. One batched
        device->host transfer for every buffer; arrays are trimmed to the
        live row count so the pool never holds capacity padding."""
        import jax

        table = table.compacted()
        nrows = table.nrows
        names = list(table.columns)
        cols = list(table.columns.values())
        flat = []
        for c in cols:
            flat.append(c.data)
            if c.valid is not None:
                flat.append(c.valid)
        fetched = iter(jax.device_get(flat)) if flat else iter(())
        datas, valids = [], []
        for c in cols:
            datas.append(np.asarray(next(fetched))[:nrows].copy())
            if c.valid is not None:
                valids.append(np.asarray(next(fetched))[:nrows].copy())
            else:
                valids.append(None)
        seg = SpillSegment(
            next(self._seq), nrows, names,
            [c.dtype for c in cols], [c.dictionary for c in cols],
            datas, valids,
        )
        with self._lock:
            self._all[seg.sid] = seg
            self._host[seg.sid] = seg
            self.host_bytes += seg.nbytes
            self.stats["bytes_in"] += seg.nbytes
            self.stats["segments"] += 1
        self._enforce_budget()
        return seg

    def read(self, seg: SpillSegment):
        """[(name, data, valid, dtype, dictionary)] for one segment,
        reloading from the disk tier when evicted. Accounts bytes_out.
        The RAM-vs-disk decision snapshots under the lock: a concurrent
        eviction (the RSS-watermark thread) nulls the RAM buffers only
        AFTER the disk file is committed and only under this same lock,
        so a reader sees either live arrays or a readable path — never
        a half-evicted segment."""
        with self._lock:
            self.stats["bytes_out"] += seg.nbytes
            if seg.sid in self._host:
                self._host.move_to_end(seg.sid)
            datas, valids = seg.datas, seg.valids
        if datas is None:
            datas, valids = self._read_segment_file(seg)
        return [
            (n, d, v, dt, dic)
            for n, d, v, dt, dic in zip(
                seg.names, datas, valids, seg.dtypes, seg.dictionaries
            )
        ]

    def release(self, segments):
        """Drop segments (RAM and disk alike); disk files are unlinked
        best-effort — sweep_orphans is the backstop for anything missed."""
        with self._lock:
            for seg in segments:
                if self._all.pop(seg.sid, None) is None:
                    continue
                if self._host.pop(seg.sid, None) is not None:
                    self.host_bytes -= seg.nbytes
        for seg in segments:
            if seg.path is not None:
                try:
                    os.unlink(seg.path)
                except OSError:
                    pass
                seg.path = None

    def evict_host(self) -> int:
        """Move EVERY RAM-resident segment to the disk tier (the RSS
        watermark pre-emption hook: relieve host memory before the
        allocator fails). Returns the number of segments evicted; 0 when
        the disk tier is disabled."""
        if self.dir is None:
            return 0
        n = 0
        while True:
            with self._lock:
                if not self._host:
                    return n
                sid, seg = next(iter(self._host.items()))
                self._host.pop(sid)
                self.host_bytes -= seg.nbytes
            self._evict_checked(seg)
            n += 1

    def close(self):
        self.release(list(self._all.values()))
        if self._manifest_written:
            try:
                os.unlink(_manifest_path(self.dir, os.getpid()))
            except OSError:
                pass
            self._manifest_written = False

    # ------------------------------------------------------------------
    def _enforce_budget(self):
        while True:
            with self._lock:
                if self.host_bytes <= self.budget or len(self._host) <= 1:
                    return
                if self.dir is None:
                    # no disk tier configured: the budget is advisory —
                    # warn once and keep segments in RAM (dropping data is
                    # never an option)
                    if not self._ram_only_warned:
                        self._ram_only_warned = True
                        print(
                            "spill: pool over budget "
                            f"({self.host_bytes} > {self.budget}B) with no "
                            "engine.spill_dir; keeping segments in host RAM"
                        )
                    return
                sid, seg = next(iter(self._host.items()))  # LRU victim
                self._host.pop(sid)
                self.host_bytes -= seg.nbytes
            self._evict_checked(seg)

    def _evict_checked(self, seg: SpillSegment):
        """Evict one segment; on ANY failure the segment is re-registered
        in RAM before the error propagates — data is never dropped, and
        the ladder's backoff retry finds a consistent pool."""
        try:
            faults.maybe_fire("spill:evict", kinds=("io", "crash"))
            dest = self._write_segment_file(seg)
        except BaseException:
            with self._lock:
                if seg.sid in self._all:
                    self._host[seg.sid] = seg
                    self.host_bytes += seg.nbytes
            raise
        unlink_now = False
        with self._lock:
            # publish the tier change atomically wrt read(): path first,
            # RAM buffers nulled in the same critical section
            seg.path = dest
            seg.datas = None
            seg.valids = None
            self.stats["evictions"] += 1
            if seg.sid not in self._all:
                # released mid-eviction: nobody will ever read or release
                # this file again — clean it up here, not at process death
                unlink_now = True
                seg.path = None
        if unlink_now:
            try:
                os.unlink(dest)
            except OSError:
                pass

    # -- disk tier ------------------------------------------------------
    def _seg_path(self, seg: SpillSegment) -> str:
        return os.path.join(self.dir, f"{_SEG_PREFIX}{self.app}-{seg.sid}.npz")

    def _write_segment_file(self, seg: SpillSegment) -> str:
        """Atomic segment write: temp sibling + os.replace, so a crash
        mid-write leaves only a `.tmp-*` file the orphan sweep removes.
        Returns the committed path; the caller publishes the tier change
        (seg.path / RAM-buffer drop) under the pool lock."""
        faults.maybe_fire("spill:write", kinds=("io", "crash"))
        dest = self._seg_path(seg)
        tmp = f"{dest}.tmp-{uuid.uuid4().hex[:8]}"
        arrays = {}
        for i, (d, v) in enumerate(zip(seg.datas, seg.valids)):
            arrays[f"d{i}"] = d
            if v is not None:
                arrays[f"v{i}"] = v
        try:
            self._ensure_manifest()
            with open(tmp, "wb") as f:
                np.savez(f, **arrays)
            os.replace(tmp, dest)
        except faults.FaultError:
            raise  # injected faults keep their own (classifiable) identity
        except OSError as exc:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise SpillIOError(
                f"spill segment write failed ({dest}): {exc}"
            ) from exc
        return dest

    def _read_segment_file(self, seg: SpillSegment):
        faults.maybe_fire("spill:read", kinds=("io", "crash"))
        try:
            with np.load(seg.path) as z:
                datas = [z[f"d{i}"] for i in range(len(seg.names))]
                valids = [
                    z[f"v{i}"] if f"v{i}" in z.files else None
                    for i in range(len(seg.names))
                ]
        except faults.FaultError:
            raise
        except (OSError, KeyError, ValueError) as exc:
            raise SpillIOError(
                f"spill segment read failed ({seg.path}): {exc}"
            ) from exc
        return datas, valids

    def _ensure_manifest(self):
        """Write this process's pool manifest (atomic) before the first
        disk segment: the liveness record sweep_orphans keys on."""
        if self._manifest_written:
            return
        os.makedirs(self.dir, exist_ok=True)
        path = _manifest_path(self.dir, os.getpid())
        tmp = f"{path}.tmp-{uuid.uuid4().hex[:8]}"
        rec = {
            "magic": _MANIFEST_MAGIC,
            "pid": os.getpid(),
            "app": self.app,
            "created": int(time.time()),
        }
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(rec, f)
        os.replace(tmp, path)
        self._manifest_written = True


# ---------------------------------------------------------------------------
# crash hygiene: orphaned-segment sweep
# ---------------------------------------------------------------------------


def _manifest_path(spill_dir: str, pid: int) -> str:
    return os.path.join(spill_dir, f"spill-manifest-{pid}.json")


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True  # exists but owned elsewhere: treat as alive
    return True


def sweep_orphans(spill_dir: str) -> int:
    """Remove spill segments (and manifests, and torn `.tmp-*` files) left
    behind by a crashed process. Only files matching the pool's own naming
    scheme are ever touched, and only when their manifest carries the pool
    magic with a dead pid (or no manifest claims them at all) — a shared
    temp directory's foreign files are never at risk. Returns the number of
    files removed."""
    if not spill_dir or not os.path.isdir(spill_dir):
        return 0
    try:
        entries = os.listdir(spill_dir)
    except OSError:
        return 0
    live_apps = set()
    removed = 0
    for name in entries:
        if not (name.startswith("spill-manifest-") and name.endswith(".json")):
            continue
        path = os.path.join(spill_dir, name)
        try:
            with open(path, encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue  # torn/foreign manifest: leave it alone
        if rec.get("magic") != _MANIFEST_MAGIC:
            continue  # fingerprint guard: not ours
        pid = rec.get("pid")
        if pid == os.getpid() or (isinstance(pid, int) and _pid_alive(pid)):
            live_apps.add(rec.get("app"))
            continue
        try:
            os.unlink(path)
            removed += 1
        except OSError:
            pass
    for name in entries:
        if not name.startswith(_SEG_PREFIX):
            continue
        base = name
        if ".tmp-" in base:
            base = base.split(".tmp-", 1)[0]
        if name.startswith("spill-manifest-"):
            # a torn manifest write (.tmp-*) from a crashed process: the
            # owning pid is in the name itself, so it can be liveness-
            # checked directly (committed manifests were handled above)
            if ".tmp-" not in name or not base.endswith(".json"):
                continue
            try:
                pid = int(base[len("spill-manifest-"):-len(".json")])
            except ValueError:
                continue
            if pid != os.getpid() and not _pid_alive(pid):
                try:
                    os.unlink(os.path.join(spill_dir, name))
                    removed += 1
                except OSError:
                    pass
            continue
        if not base.endswith(".npz"):
            continue
        # name format: spill-<app>-<sid>.npz; app may itself contain dashes
        stem = base[len(_SEG_PREFIX):-len(".npz")]
        app = stem.rsplit("-", 1)[0] if "-" in stem else stem
        if app in live_apps:
            continue
        try:
            os.unlink(os.path.join(spill_dir, name))
            removed += 1
        except OSError:
            pass
    if removed:
        print(f"spill: swept {removed} orphaned file(s) from {spill_dir}")
    return removed


# one sweep per (process, directory): session construction is per-stream in
# throughput runs, and re-listing the spill dir per session buys nothing.
# Process-lifetime once-latch, not per-stream state; worst case under a
# race is a second, idempotent sweep.
# nds-lint: disable=mutable-module-global
_SWEPT_DIRS = set()


def sweep_at_session_start(spill_dir: str | None):
    if not spill_dir or spill_dir in _SWEPT_DIRS:
        return
    _SWEPT_DIRS.add(spill_dir)
    sweep_orphans(spill_dir)


# ---------------------------------------------------------------------------
# segment reassembly (executor side)
# ---------------------------------------------------------------------------


def assemble_segments(pool: SpillPool, segments) -> "object":
    """One device Table from an ordered list of spilled segments: per-column
    host concatenation (string dictionaries re-unified when partitions
    carry distinct ones), padded to a capacity bucket and uploaded once per
    column. Row order is the segment order — the out-of-core paths choose
    segment boundaries so this matches (sort) or is order-insensitive to
    (join/distinct, which SQL leaves unordered) the direct path."""
    import jax.numpy as jnp
    import pyarrow as pa
    import pyarrow.compute as pc

    from .columnar import Column, Table, bucket_cap

    if not segments:
        raise SpillError("assemble_segments needs at least one segment")
    reads = [pool.read(s) for s in segments]
    names = [n for n, *_ in reads[0]]
    total = sum(s.nrows for s in segments)
    cap = bucket_cap(max(total, 1))
    cols = {}
    for ci, name in enumerate(names):
        dtype = reads[0][ci][3]
        dicts = [r[ci][4] for r in reads]
        datas = [r[ci][1] for r in reads]
        dictionary = None
        if any(d is not None for d in dicts):
            first = dicts[0]
            if all(d is first for d in dicts):
                # partitions of one input share the dictionary object:
                # codes are directly comparable, skip the host unify
                dictionary = first
            else:
                casted = [
                    (d if d is not None else pa.array([], pa.string())).cast(
                        pa.string()
                    )
                    for d in dicts
                ]
                dictionary = pc.unique(pa.concat_arrays(casted))
                remapped = []
                for d, arr in zip(casted, datas):
                    if len(d) == 0:
                        remapped.append(arr)
                        continue
                    remap = (
                        pc.index_in(d, dictionary)
                        .to_numpy(zero_copy_only=False)
                        .astype(np.int32)
                    )
                    remapped.append(remap[np.clip(arr, 0, len(d) - 1)])
                datas = remapped
        data = np.concatenate(datas) if len(datas) > 1 else datas[0]
        buf = np.zeros(cap, dtype=data.dtype)
        buf[:total] = data
        valids = [r[ci][2] for r in reads]
        valid = None
        if any(v is not None for v in valids):
            vbuf = np.zeros(cap, dtype=bool)
            off = 0
            for seg, v in zip(segments, valids):
                vbuf[off:off + seg.nrows] = True if v is None else v
                off += seg.nrows
            valid = jnp.asarray(vbuf)
        cols[name] = Column(jnp.asarray(buf), dtype, valid, dictionary)
    return Table(cols, total)
