"""Logical/physical plan IR.

The binder lowers SQL AST into this tree; the executor interprets it over
device Tables. Column identity is by unique string name ("alias.col" for base
columns, binder-generated names for derived ones), so plans carry no separate
symbol table.

This is the engine's counterpart of the Catalyst plans the reference submits
to Spark (reference: nds/nds_power.py:125-135 `spark.sql(query)`).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from . import expr as E


@dataclass
class PlanNode:
    def children(self):
        return []


@dataclass
class Scan(PlanNode):
    table: str  # catalog name
    alias: str  # column prefix in the output
    columns: list = None  # projection pushdown: subset of base columns or None
    # lakehouse snapshot pin: the manifest version this statement resolved
    # at plan time (Session._pin_lake_scans); None for non-lake tables. A
    # dataclass field on purpose — it participates in plan.fingerprint, so
    # plan-cache entries can never alias across snapshot versions.
    lake_version: int = None
    # zone-map pruning (Session._prune_lake_scans): the pinned manifest's
    # files that MAY match this scan's bound predicate (None = read all),
    # and the surviving-row upper bound the budgeter consumes. Dataclass
    # fields like lake_version — they participate in fingerprint, so a
    # pruned plan can never alias an unpruned one in the plan cache.
    lake_files: tuple = None
    prune_rows: int = None


@dataclass
class Project(PlanNode):
    items: list  # (Expr, out_name)
    child: PlanNode = None

    def children(self):
        return [self.child]


@dataclass
class Filter(PlanNode):
    predicate: E.Expr
    child: PlanNode = None

    def children(self):
        return [self.child]


@dataclass
class Join(PlanNode):
    kind: str  # inner | left | right | full | semi | anti | cross | mark
    left: PlanNode = None
    right: PlanNode = None
    left_keys: list = field(default_factory=list)  # Exprs over left
    right_keys: list = field(default_factory=list)  # Exprs over right
    residual: Optional[E.Expr] = None  # non-equi condition applied post-match
    mark_name: Optional[str] = None  # kind == "mark": bool "has a match" column

    def children(self):
        return [self.left, self.right]


@dataclass
class Aggregate(PlanNode):
    keys: list  # (Expr, out_name)
    aggs: list  # (E.Agg, out_name)
    child: PlanNode = None
    grouping_sets: Optional[list] = None  # list of key-index subsets (rollup)
    # planner annotation (mark_blocked_union_aggs): the input is a union_all
    # chain reachable through Project/Filter wrappers, so the executor may
    # evaluate it in bounded row windows with partial-aggregate merging
    # instead of materializing the full concat (the SF10 HBM ceiling)
    blocked_union: bool = False

    def children(self):
        return [self.child]


@dataclass
class Window(PlanNode):
    fns: list  # (E.WindowFn, out_name)
    child: PlanNode = None

    def children(self):
        return [self.child]


@dataclass
class Sort(PlanNode):
    keys: list  # (Expr, ascending, nulls_first|None)
    child: PlanNode = None

    def children(self):
        return [self.child]


@dataclass
class Limit(PlanNode):
    n: int
    child: PlanNode = None

    def children(self):
        return [self.child]


@dataclass
class Distinct(PlanNode):
    child: PlanNode = None

    def children(self):
        return [self.child]


@dataclass
class SetOp(PlanNode):
    op: str  # union_all | union | intersect | except
    left: PlanNode = None
    right: PlanNode = None

    def children(self):
        return [self.left, self.right]


@dataclass
class MultiJoin(PlanNode):
    """N-way inner join over a predicate graph; the executor picks the join
    order greedily from *actual* post-filter row counts (eager execution makes
    real sizes available — the TPU answer to Spark's CBO/AQE, reference:
    nds/properties/aqe-on.properties)."""

    relations: list = field(default_factory=list)  # PlanNodes
    edges: list = field(default_factory=list)  # (i, j, left_expr, right_expr)
    residual: Optional[E.Expr] = None

    def children(self):
        return list(self.relations)


@dataclass
class MaterializedScan(PlanNode):
    """Scan of an already-materialized Table (CTE result, temp view)."""

    name: str
    table: object = None  # columnar.Table


@dataclass
class Pipeline(PlanNode):
    """A maximal linear Filter/Project chain fused into one compiled unit.

    `stages` holds detached Filter/Project nodes (child=None) in EXECUTION
    order (innermost first); `child` is the chain's input. The executor
    compiles the whole chain as ONE jitted function over the child's device
    columns (engine/fuse.py) — no per-node dispatch, no materialized
    intermediates, masks deferred to the pipeline boundary — and falls back
    to eager per-stage evaluation when the chain doesn't trace (host-side
    string casts, subqueries). Structural passes that peel Project/Filter
    wrappers (blocked union-aggregation shape detection) see through this
    node via `_peel_wrappers`.

    `agg` (optional) is a detached Aggregate tail (child=None, plain shape:
    no grouping sets, no blocked_union, decomposable agg set): the fused
    body then runs the evaluator chain AND the partial-aggregate scatter in
    ONE dispatch (direct mixed-radix group codes + segment reductions over
    a domain-bucket output cap), and the Pipeline's output is the aggregate
    result. An agg-tail Pipeline is a plan-cacheable terminal node, never a
    see-through wrapper (`_peel_wrappers` stops at it)."""

    stages: list = field(default_factory=list)  # Filter/Project, child=None
    child: PlanNode = None
    # set by fuse.mark_pipelines: the child's result is single-consumer and
    # uncached, so the fused call may donate input buffers the child table
    # actually owns (its live mask; data/validity buffers marked
    # Column.owned by minting producers — see README "Performance")
    donate_ok: bool = False
    agg: Optional["Aggregate"] = None  # detached aggregate tail (child=None)

    def children(self):
        return [self.child]


import itertools as _itertools

_fp_serials = _itertools.count()


def fingerprint(node: PlanNode) -> str:
    """Stable structural identity of a plan subtree.

    Two separately-bound plans with the same structure (same scans, exprs,
    operators) get equal fingerprints, so executor results can be reused
    across statements — e.g. the shared CTE text of query14_part1/_part2
    re-resolves to the same key (reference analogue: Spark reuses nothing
    across spark.sql calls; this is the eager engine's materialized-CTE
    win). Shared subtrees are serialized once and back-referenced, which
    also keeps the cost linear in plan size."""
    import dataclasses
    import hashlib

    out = []
    memo = {}

    def emit(v):
        if isinstance(v, MaterializedScan):
            # a populated table is identity, not structure: tag it with a
            # monotonic serial (id() values are reused after GC, which
            # could alias plan-cache entries across statements)
            if v.table is None:
                t = "none"
            else:
                t = getattr(v.table, "_fp_serial", None)
                if t is None:
                    t = v.table._fp_serial = next(_fp_serials)
            out.append(f"MScan:{v.name}:{t}")
        elif isinstance(v, (PlanNode, E.Expr)):
            key = id(v)
            if key in memo:
                out.append(f"@{memo[key]}")
                return
            memo[key] = len(memo)
            out.append(type(v).__name__)
            out.append("(")
            for f in dataclasses.fields(v):
                emit(getattr(v, f.name))
            out.append(")")
        elif isinstance(v, (list, tuple)):
            out.append("[")
            for x in v:
                emit(x)
            out.append("]")
        elif v is None or isinstance(v, (str, int, float, bool, frozenset)):
            out.append(repr(v))
        else:
            # DType and other small value objects: repr is structural
            out.append(type(v).__name__ + ":" + repr(v))

    emit(node)
    return hashlib.sha256("\x00".join(out).encode()).hexdigest()


def walk_plan(root):
    """Yield every PlanNode and Expr reachable from `root` exactly once
    (id-deduplicated; subquery plans riding inside expressions included),
    via generic dataclass-field recursion — the one traversal shared by
    the annotation/analysis passes so a plan-IR field change lands in one
    place."""
    import dataclasses

    seen = set()
    stack = [root]
    while stack:
        v = stack.pop()
        if isinstance(v, (PlanNode, E.Expr)):
            if id(v) in seen:
                continue
            seen.add(id(v))
            yield v
            for f in dataclasses.fields(v):
                stack.append(getattr(v, f.name))
        elif isinstance(v, (list, tuple)):
            stack.extend(v)


def _peel_wrappers(n):
    """(Project/Filter wrapper list top-down, first non-wrapper node).

    Pipeline nodes expand into their stages: fusion must not hide a
    union-aggregation shape from the blocked-execution path (the detached
    stage nodes carry no children, which _apply_wrappers never reads).
    A Pipeline with an aggregate tail is NOT a wrapper — it terminates the
    peel like the Aggregate it absorbed would."""
    wrappers = []
    while isinstance(n, (Project, Filter, Pipeline)):
        if isinstance(n, Pipeline):
            if n.agg is not None:
                break  # aggregate tail: a terminal node, not a wrapper
            # stages are in execution (innermost-first) order; the wrapper
            # list is top-down (outermost first)
            wrappers.extend(reversed(n.stages))
        else:
            wrappers.append(n)
        n = n.child
    return wrappers, n


def union_agg_shape(node: "Aggregate"):
    """(outer_wrappers, join, inner_wrappers, union branch plans) when an
    Aggregate's input is a union_all chain reachable through Project/Filter
    wrappers — optionally with one inner MultiJoin in between whose
    relations include the union (the query5 shape: a fact-scale
    sales+returns union joined to dimension tables before the channel
    aggregation; inner joins distribute over union rows, so windows can
    flow straight through the join). `join` is None for the direct shape,
    else `(multijoin_node, union_relation_index)`. Returns None when the
    input is not this shape.

    Shared by the planner's annotation pass and the executor's blocked
    union-aggregation path so the two recognize exactly the same shapes.
    Only pure `union_all` chains qualify: UNION (distinct), INTERSECT and
    EXCEPT have whole-input set semantics that do not decompose over row
    windows, so such a SetOp terminates branch flattening instead."""
    outer, n = _peel_wrappers(node.child)
    join = None
    inner = []
    if isinstance(n, MultiJoin):
        # the FIRST union-shaped relation is the windowed side; every other
        # relation executes once and joins against each window
        for i, r in enumerate(n.relations):
            w, m = _peel_wrappers(r)
            if isinstance(m, SetOp) and m.op == "union_all":
                join = (n, i)
                inner = w
                n = m
                break
        if join is None:
            return None
    if not (isinstance(n, SetOp) and n.op == "union_all"):
        return None
    branches = []

    def collect(x):
        if isinstance(x, SetOp) and x.op == "union_all":
            collect(x.left)
            collect(x.right)
        else:
            branches.append(x)

    collect(n)
    return outer, join, inner, branches


def aggs_decomposable(agg_items) -> bool:
    """True when every aggregate of an Aggregate node decomposes over row
    windows: plain sum/min/max/count compose with themselves, avg via its
    hidden sum+count split. Distinct aggregates, stddev/var and grouping()
    do not merge over partials. The SAME predicate gates the executor's
    blocked-union machinery (exec._rollup_base_aggs) — the planner
    annotation and the runtime path must agree, and the plan verifier
    (analysis/verifier.py) checks annotations against exactly this rule."""
    return all(
        not a.distinct and a.fn in ("sum", "min", "max", "count", "avg")
        for a, _ in agg_items
    )


def mark_blocked_union_aggs(node: PlanNode) -> int:
    """Annotate every Aggregate (anywhere in the tree, subquery plans
    included) whose input is a union_all chain AND whose aggregates
    decompose over row windows: sets `blocked_union` so the executor may
    take the windowed partial-aggregation path. Grouping-set aggregates
    qualify too — their from-scratch levels run windowed and the rollup
    cascade re-aggregates the (small) results. Non-decomposable aggregate
    sets (count distinct, stddev) are NOT annotated: the windowed path
    cannot merge their partials, so annotating them would only invite an
    unsound rewrite — the verifier flags such annotations. Returns the
    number of nodes marked (plan-introspection aid for tests/tools)."""
    import dataclasses

    marked = 0
    seen = set()

    def visit(v):
        nonlocal marked
        if isinstance(v, (PlanNode, E.Expr)):
            if id(v) in seen:
                return
            seen.add(id(v))
            if (
                isinstance(v, Aggregate)
                and aggs_decomposable(v.aggs)
                and union_agg_shape(v) is not None
            ):
                v.blocked_union = True
                marked += 1
            # generic field recursion reaches subquery plans riding inside
            # expressions (E.ScalarSubquery.plan) as well as plan children
            for f in dataclasses.fields(v):
                visit(getattr(v, f.name))
        elif isinstance(v, (list, tuple)):
            for x in v:
                visit(x)

    visit(node)
    return marked


def node_desc(node: PlanNode) -> str:
    """One-line description of a SINGLE node — no child recursion (the
    op-span tracer calls this per executed node; recursing would render
    every subtree O(depth) times over a traced plan)."""
    name = type(node).__name__
    return {
        "Scan": lambda: f"Scan {node.table} as {node.alias}",
        "MaterializedScan": lambda: f"MaterializedScan {node.name}",
        "Project": lambda: f"Project [{', '.join(n for _, n in node.items)}]",
        "Filter": lambda: f"Filter {node.predicate}",
        "Join": lambda: f"Join {node.kind} on {list(zip(node.left_keys, node.right_keys))}"
        + (f" residual {node.residual}" if node.residual else ""),
        "Aggregate": lambda: f"Aggregate keys=[{', '.join(n for _, n in node.keys)}] "
        f"aggs=[{', '.join(n for _, n in node.aggs)}]"
        + (f" sets={node.grouping_sets}" if node.grouping_sets else ""),
        "Window": lambda: f"Window [{', '.join(n for _, n in node.fns)}]",
        "Sort": lambda: f"Sort {[(str(k), a) for k, a, _ in node.keys]}",
        "Limit": lambda: f"Limit {node.n}",
        "Distinct": lambda: "Distinct",
        "SetOp": lambda: f"SetOp {node.op}",
        "Pipeline": lambda: "Pipeline "
        + "".join(
            "F" if isinstance(s, Filter) else "P" for s in node.stages
        )
        + ("+A" if node.agg is not None else ""),
    }.get(name, lambda: name)()


def explain(node: PlanNode, indent=0) -> str:
    pad = "  " * indent
    out = pad + node_desc(node) + "\n"
    for c in node.children():
        if c is not None:
            out += explain(c, indent + 1)
    return out
