"""Relational AST for the NDS SQL dialect.

Scalar expressions reuse the engine IR (nds_tpu.engine.expr) directly; this
module only adds the relational shapes (SELECT, FROM items, set ops, DML).
The dialect matches what the reference's patched query templates emit for
Spark SQL (reference: nds/tpcds-gen/patches/templates.patch — `+ interval N
days` date arithmetic, double-quoted aliases, ROLLUP, window functions).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional


@dataclass
class TableRef:
    name: str
    alias: Optional[str] = None


@dataclass
class SubqueryRef:
    query: "SelectStmt"
    alias: str


@dataclass
class JoinClause:
    left: object
    right: object
    kind: str  # inner | left | right | full | cross
    on: Optional[object] = None  # Expr


@dataclass
class OrderItem:
    expr: object
    ascending: bool = True
    nulls_first: Optional[bool] = None  # None -> dialect default by direction


@dataclass
class SelectStmt:
    select_items: list = field(default_factory=list)  # (Expr, alias|None) or ("*", qualifier|None)
    distinct: bool = False
    from_items: list = field(default_factory=list)
    where: Optional[object] = None
    group_by: list = field(default_factory=list)  # Exprs
    rollup: bool = False
    grouping_sets: Optional[list] = None
    having: Optional[object] = None
    order_by: list = field(default_factory=list)  # OrderItem
    limit: Optional[int] = None
    ctes: list = field(default_factory=list)  # (name, SelectStmt)
    set_ops: list = field(default_factory=list)  # (op, SelectStmt); op in {union, union all, intersect, except}


@dataclass
class InsertStmt:
    table: str
    query: SelectStmt


@dataclass
class DeleteStmt:
    table: str
    where: Optional[object] = None


@dataclass
class CreateViewStmt:
    name: str
    query: SelectStmt
    temp: bool = True


@dataclass
class DropViewStmt:
    name: str


@dataclass
class CreateTableStmt:
    name: str
    query: SelectStmt  # CTAS only
    using: Optional[str] = None
    location: Optional[str] = None
    partitioned_by: list = field(default_factory=list)


@dataclass
class CallStmt:
    """CALL system.rollback_to_timestamp(...) — lakehouse procedures
    (reference: nds/nds_rollback.py:46-51)."""

    procedure: str
    args: list = field(default_factory=list)
