"""SQL tokenizer + recursive-descent parser for the NDS dialect.

Covers the surface the 99 TPC-DS query templates and 11 maintenance scripts
need (reference: nds/tpcds-gen/patches/templates.patch; nds/data_maintenance/
*.sql): WITH CTEs, joins (comma + ANSI), subqueries (scalar/IN/EXISTS),
CASE/CAST, BETWEEN/IN/LIKE/IS NULL, UNION [ALL]/INTERSECT/EXCEPT, GROUP BY
[ROLLUP], HAVING, window functions with frames, ORDER BY/LIMIT, INTERVAL date
arithmetic, and the DML/DDL used by data maintenance (INSERT INTO ... SELECT,
DELETE FROM ... WHERE, CREATE TEMP VIEW, CALL rollback procedures).

Produces engine expression IR (nds_tpu.engine.expr) + relational AST
(nds_tpu.engine.sql.ast); no external parser dependency.
"""

from __future__ import annotations

import re
from typing import Optional

from ...dtypes import DType, parse_dtype
from .. import expr as E
from . import ast as A

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|--[^\n]*)
  | (?P<num>\d+\.\d*(?:[eE][+-]?\d+)?|\.\d+|\d+(?:[eE][+-]?\d+)?)
  | (?P<str>'(?:[^']|'')*')
  | (?P<qid>`[^`]+`|"[^"]+")
  | (?P<id>[A-Za-z_][A-Za-z_0-9]*)
  | (?P<op><=|>=|<>|!=|\|\||[+\-*/(),.=<>;])
    """,
    re.VERBOSE,
)

# frozenset: a read-only vocabulary constant, never per-stream state (and
# the mutable-module-global lint rule holds engine/ to exactly that)
KEYWORDS = frozenset({
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "as", "and", "or", "not", "in", "between", "like", "is", "null", "case",
    "when", "then", "else", "end", "cast", "distinct", "union", "all",
    "intersect", "except", "join", "inner", "left", "right", "full", "outer",
    "cross", "on", "with", "exists", "interval", "date", "days", "day",
    "rollup", "grouping", "sets", "over", "partition", "rows", "preceding",
    "following", "unbounded", "current", "row", "asc", "desc", "nulls",
    "first", "last", "insert", "into", "delete", "create", "drop", "table",
    "view", "temp", "temporary", "using", "location", "partitioned", "call",
    "values", "semi", "anti", "any", "some", "exists", "substring", "top",
})


class Token:
    __slots__ = ("kind", "value", "pos")

    def __init__(self, kind, value, pos):
        self.kind = kind  # num str id qid op kw eof
        self.value = value
        self.pos = pos

    def __repr__(self):
        return f"Token({self.kind},{self.value!r})"


def tokenize(sql: str):
    out = []
    pos = 0
    n = len(sql)
    while pos < n:
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            raise SyntaxError(f"bad character {sql[pos]!r} at {pos}: ...{sql[max(0,pos-30):pos+10]!r}")
        pos = m.end()
        if m.lastgroup == "ws":
            continue
        kind = m.lastgroup
        val = m.group()
        if kind == "id":
            low = val.lower()
            if low in KEYWORDS:
                out.append(Token("kw", low, m.start()))
            else:
                out.append(Token("id", low, m.start()))
        elif kind == "qid":
            out.append(Token("id", val[1:-1].lower(), m.start()))
        elif kind == "str":
            out.append(Token("str", val[1:-1].replace("''", "'"), m.start()))
        else:
            out.append(Token(kind, val, m.start()))
    out.append(Token("eof", None, n))
    return out


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.toks = tokenize(sql)
        self.i = 0

    # ---- token helpers ---------------------------------------------------
    def peek(self, k=0) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *kws) -> bool:
        t = self.peek()
        return t.kind == "kw" and t.value in kws

    def at_op(self, *ops) -> bool:
        t = self.peek()
        return t.kind == "op" and t.value in ops

    def accept_kw(self, *kws) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def accept_op(self, *ops) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_kw(self, kw):
        if not self.accept_kw(kw):
            self.err(f"expected {kw.upper()}")

    def expect_op(self, op):
        if not self.accept_op(op):
            self.err(f"expected {op!r}")

    def err(self, msg):
        t = self.peek()
        ctx = self.sql[max(0, t.pos - 40) : t.pos + 40]
        raise SyntaxError(f"{msg}, got {t} near ...{ctx!r}")

    # ---- entry points ----------------------------------------------------
    def parse_statement(self):
        if self.at_kw("select", "with") or self.at_op("("):
            return self.parse_select()
        if self.at_kw("insert"):
            return self.parse_insert()
        if self.at_kw("delete"):
            return self.parse_delete()
        if self.at_kw("create"):
            return self.parse_create()
        if self.at_kw("drop"):
            return self.parse_drop()
        if self.at_kw("call"):
            return self.parse_call()
        self.err("expected statement")

    def parse_script(self):
        """Parse a ';'-separated list of statements."""
        stmts = []
        while not self.peek().kind == "eof":
            stmts.append(self.parse_statement())
            while self.accept_op(";"):
                pass
        return stmts

    # ---- statements ------------------------------------------------------
    def parse_insert(self):
        self.expect_kw("insert")
        self.expect_kw("into")
        name = self.qualified_name()
        if self.at_kw("table"):  # INSERT INTO TABLE t
            self.next()
            name = self.qualified_name()
        q = self.parse_select()
        return A.InsertStmt(name, q)

    def parse_delete(self):
        self.expect_kw("delete")
        self.expect_kw("from")
        name = self.qualified_name()
        where = None
        if self.accept_kw("where"):
            where = self.expr()
        return A.DeleteStmt(name, where)

    def parse_create(self):
        self.expect_kw("create")
        temp = self.accept_kw("temp", "temporary")
        if self.accept_kw("view"):
            name = self.qualified_name()
            self.expect_kw("as")
            q = self.parse_select()
            return A.CreateViewStmt(name, q, temp=True if temp else temp)
        self.expect_kw("table")
        name = self.qualified_name()
        using = None
        location = None
        parts = []
        while True:
            if self.accept_kw("using"):
                using = self.next().value
            elif self.accept_kw("partitioned"):
                self.expect_kw("by")
                self.expect_op("(")
                while True:
                    parts.append(self.next().value)
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            elif self.accept_kw("location"):
                location = self.next().value
            else:
                break
        self.expect_kw("as")
        q = self.parse_select()
        return A.CreateTableStmt(name, q, using, location, parts)

    def parse_drop(self):
        self.expect_kw("drop")
        self.expect_kw("view")
        # IF EXISTS
        if self.peek().kind == "id" and self.peek().value == "if":
            self.next()
            self.expect_kw("exists")
        return A.DropViewStmt(self.qualified_name())

    def parse_call(self):
        self.expect_kw("call")
        name = self.qualified_name()
        args = []
        self.expect_op("(")
        if not self.at_op(")"):
            while True:
                # named arg: id => value
                args.append(self.expr())
                if not self.accept_op(","):
                    break
        self.expect_op(")")
        return A.CallStmt(name, args)

    def qualified_name(self) -> str:
        parts = [self.next().value]
        while self.accept_op("."):
            parts.append(self.next().value)
        return ".".join(parts)

    # ---- SELECT ----------------------------------------------------------
    def parse_select(self) -> A.SelectStmt:
        ctes = []
        if self.accept_kw("with"):
            while True:
                name = self.next().value
                self.expect_kw("as")
                self.expect_op("(")
                sub = self.parse_select()
                self.expect_op(")")
                ctes.append((name, sub))
                if not self.accept_op(","):
                    break
        stmt = self.parse_select_core()
        stmt.ctes = ctes
        # set operations
        while self.at_kw("union", "intersect", "except"):
            op = self.next().value
            if op == "union" and self.accept_kw("all"):
                op = "union all"
            elif op in ("intersect", "except"):
                self.accept_kw("all")  # treated as set semantics
            rhs = self.parse_select_core_or_paren(in_setop=True)
            stmt.set_ops.append((op, rhs))
        # trailing ORDER BY / LIMIT bind to the whole set expression
        if self.accept_kw("order"):
            self.expect_kw("by")
            stmt.order_by = self.order_items()
        if self.accept_kw("limit"):
            stmt.limit = int(self.next().value)
        return stmt

    def parse_select_core_or_paren(self, in_setop=False):
        if self.accept_op("("):
            s = self.parse_select()
            self.expect_op(")")
            return s
        return self.parse_select_core(in_setop=in_setop)

    def parse_select_core(self, in_setop=False) -> A.SelectStmt:
        if self.accept_op("("):
            s = self.parse_select()
            self.expect_op(")")
            return s
        self.expect_kw("select")
        stmt = A.SelectStmt()
        stmt.distinct = self.accept_kw("distinct")
        self.accept_kw("all")
        if self.accept_kw("top"):  # TOP n (some dsqgen dialects)
            stmt.limit = int(self.next().value)
        while True:
            if self.at_op("*"):
                self.next()
                stmt.select_items.append(("*", None))
            elif (
                self.peek().kind == "id"
                and self.peek(1).kind == "op"
                and self.peek(1).value == "."
                and self.peek(2).kind == "op"
                and self.peek(2).value == "*"
            ):
                qual = self.next().value
                self.next()
                self.next()
                stmt.select_items.append(("*", qual))
            else:
                e = self.expr()
                alias = self.maybe_alias()
                stmt.select_items.append((e, alias))
            if not self.accept_op(","):
                break
        if self.accept_kw("from"):
            stmt.from_items = self.from_list()
        if self.accept_kw("where"):
            stmt.where = self.expr()
        if self.accept_kw("group"):
            self.expect_kw("by")
            if self.accept_kw("rollup"):
                stmt.rollup = True
                self.expect_op("(")
                stmt.group_by = self.expr_list()
                self.expect_op(")")
            elif self.accept_kw("grouping"):
                self.expect_kw("sets")
                self.expect_op("(")
                sets = []
                while True:
                    self.expect_op("(")
                    if self.at_op(")"):
                        sets.append([])
                    else:
                        sets.append(self.expr_list())
                    self.expect_op(")")
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
                stmt.grouping_sets = sets
                seen = []
                for s in sets:
                    for e in s:
                        if e not in seen:
                            seen.append(e)
                stmt.group_by = seen
            else:
                stmt.group_by = self.expr_list()
        if self.accept_kw("having"):
            stmt.having = self.expr()
        # When this core is the RHS of a set operation, a trailing
        # ORDER BY / LIMIT belongs to the whole set expression, not the core.
        if not in_setop:
            if self.accept_kw("order"):
                self.expect_kw("by")
                stmt.order_by = self.order_items()
            if self.accept_kw("limit"):
                stmt.limit = int(self.next().value)
        return stmt

    def order_items(self):
        items = []
        while True:
            e = self.expr()
            asc = True
            if self.accept_kw("desc"):
                asc = False
            else:
                self.accept_kw("asc")
            nf = None
            if self.accept_kw("nulls"):
                if self.accept_kw("first"):
                    nf = True
                else:
                    self.expect_kw("last")
                    nf = False
            items.append(A.OrderItem(e, asc, nf))
            if not self.accept_op(","):
                break
        return items

    def expr_list(self):
        out = [self.expr()]
        while self.accept_op(","):
            out.append(self.expr())
        return out

    def maybe_alias(self) -> Optional[str]:
        if self.accept_kw("as"):
            return self.next().value
        t = self.peek()
        if t.kind == "id":
            self.next()
            return t.value
        return None

    # ---- FROM ------------------------------------------------------------
    def from_list(self):
        items = [self.join_chain()]
        while self.accept_op(","):
            items.append(self.join_chain())
        return items

    def join_chain(self):
        left = self.table_primary()
        while True:
            kind = None
            if self.accept_kw("inner"):
                kind = "inner"
            elif self.accept_kw("left"):
                self.accept_kw("outer")
                kind = "left"
                if self.accept_kw("semi"):
                    kind = "semi"
                elif self.accept_kw("anti"):
                    kind = "anti"
            elif self.accept_kw("right"):
                self.accept_kw("outer")
                kind = "right"
            elif self.accept_kw("full"):
                self.accept_kw("outer")
                kind = "full"
            elif self.accept_kw("cross"):
                kind = "cross"
            elif self.at_kw("join"):
                kind = "inner"
            if kind is None:
                return left
            self.expect_kw("join")
            right = self.table_primary()
            on = None
            if kind != "cross":
                self.expect_kw("on")
                on = self.expr()
            left = A.JoinClause(left, right, kind, on)

    def table_primary(self):
        if self.accept_op("("):
            if self.at_kw("select", "with"):
                q = self.parse_select()
                self.expect_op(")")
                alias = self.maybe_alias() or f"_subq{self.i}"
                return A.SubqueryRef(q, alias)
            # `((select ...) intersect (select ...)) alias`: a set expression
            # whose first operand is itself parenthesized. Look past the
            # leading parens; if a select starts there, parse the whole thing
            # as one select expression (backtrack to a join group on failure).
            k = 0
            while (
                self.peek(k).kind == "op" and self.peek(k).value == "("
            ):
                k += 1
            if self.peek(k).kind == "kw" and self.peek(k).value in (
                "select", "with",
            ):
                save = self.i
                try:
                    q = self.parse_select()
                    self.expect_op(")")
                    alias = self.maybe_alias() or f"_subq{self.i}"
                    return A.SubqueryRef(q, alias)
                except SyntaxError:
                    self.i = save
            j = self.join_chain()
            self.expect_op(")")
            return j
        name = self.qualified_name()
        alias = self.maybe_alias()
        return A.TableRef(name, alias)

    # ---- expressions -----------------------------------------------------
    def expr(self) -> E.Expr:
        return self.or_expr()

    def or_expr(self):
        left = self.and_expr()
        while self.accept_kw("or"):
            left = E.BinOp("or", left, self.and_expr())
        return left

    def and_expr(self):
        left = self.not_expr()
        while self.accept_kw("and"):
            left = E.BinOp("and", left, self.not_expr())
        return left

    def not_expr(self):
        if self.accept_kw("not"):
            return E.UnaryOp("not", self.not_expr())
        return self.predicate()

    def predicate(self):
        if self.at_kw("exists"):
            self.next()
            self.expect_op("(")
            q = self.parse_select()
            self.expect_op(")")
            return E.SubqueryExpr(q, "exists")
        left = self.additive()
        while True:
            if self.at_op("=", "<>", "!=", "<", "<=", ">", ">="):
                op = self.next().value
                if op == "!=":
                    op = "<>"
                # comparison with quantified/scalar subquery
                if self.at_op("(") and self.peek(1).kind == "kw" and self.peek(1).value in ("select", "with"):
                    self.next()
                    q = self.parse_select()
                    self.expect_op(")")
                    right = E.SubqueryExpr(q, "scalar")
                else:
                    right = self.additive()
                left = E.BinOp(op, left, right)
                continue
            negated = False
            save = self.i
            if self.accept_kw("not"):
                negated = True
            if self.accept_kw("between"):
                lo = self.additive()
                self.expect_kw("and")
                hi = self.additive()
                left = E.Between(left, lo, hi, negated)
                continue
            if self.accept_kw("in"):
                self.expect_op("(")
                if self.at_kw("select", "with"):
                    q = self.parse_select()
                    self.expect_op(")")
                    left = E.SubqueryExpr(q, "in", left, negated)
                else:
                    vals = []
                    while True:
                        v = self.additive()
                        vals.append(v)
                        if not self.accept_op(","):
                            break
                    self.expect_op(")")
                    vals = tuple(_as_lit(v) for v in vals)
                    left = E.InList(left, vals, negated)
                continue
            if self.accept_kw("like"):
                pat = self.next()
                left = E.Like(left, pat.value, negated)
                continue
            if negated:
                self.i = save
                break
            if self.accept_kw("is"):
                neg = self.accept_kw("not")
                self.expect_kw("null")
                left = E.UnaryOp("isnotnull" if neg else "isnull", left)
                continue
            break
        return left

    def additive(self):
        left = self.multiplicative()
        while True:
            if self.at_op("+", "-"):
                op = self.next().value
                right = self.multiplicative()
                if isinstance(right, E.Interval):
                    fn = "date_add" if op == "+" else "date_sub"
                    left = E.Func(fn, (left, E.Lit(right.days)))
                else:
                    left = E.BinOp(op, left, right)
            elif self.at_op("||"):
                self.next()
                left = E.BinOp("||", left, self.multiplicative())
            else:
                return left

    def multiplicative(self):
        left = self.unary()
        while self.at_op("*", "/"):
            op = self.next().value
            left = E.BinOp(op, left, self.unary())
        return left

    def unary(self):
        if self.accept_op("-"):
            operand = self.unary()
            if isinstance(operand, E.Lit) and isinstance(operand.value, (int, float)):
                return E.Lit(-operand.value, operand.dtype)
            return E.UnaryOp("neg", operand)
        if self.accept_op("+"):
            return self.unary()
        return self.primary()

    def primary(self):
        t = self.peek()
        if t.kind == "num":
            self.next()
            if "." in t.value or "e" in t.value.lower():
                if "e" in t.value.lower():
                    return E.Lit(float(t.value))
                # exact decimal literal
                frac = t.value.split(".")[1] if "." in t.value else ""
                scale = len(frac)
                return E.Lit(float(t.value), DType("decimal", 38, scale))
            return E.Lit(int(t.value))
        if t.kind == "str":
            self.next()
            return E.Lit(t.value)
        if self.accept_op("("):
            if self.at_kw("select", "with"):
                q = self.parse_select()
                self.expect_op(")")
                return E.SubqueryExpr(q, "scalar")
            e = self.expr()
            self.expect_op(")")
            return e
        if self.at_kw("case"):
            return self.case_expr()
        if self.at_kw("cast"):
            return self.cast_expr()
        if self.at_kw("null"):
            self.next()
            return E.Lit(None)
        if self.at_kw("interval"):
            self.next()
            v = self.next()  # number or string
            n = int(v.value)
            self.accept_kw("days", "day")
            return E.Interval(n)
        if self.at_kw("date"):
            # DATE 'yyyy-mm-dd' literal, or a column named `date`
            if self.peek(1).kind == "str":
                self.next()
                s = self.next().value
                return E.Lit(s, parse_dtype("date"))
            self.next()
            return E.Col("date")
        if (
            self.peek().kind == "id"
            and self.peek().value == "timestamp"
            and self.peek(1).kind == "str"
        ):
            # TIMESTAMP '...' literal (CALL rollback_to_timestamp syntax,
            # reference: nds/nds_rollback.py:46-51); kept as a plain string
            self.next()
            return E.Lit(self.next().value)
        if self.at_kw("exists"):
            return self.predicate()
        if self.at_kw("grouping"):
            self.next()
            self.expect_op("(")
            arg = self.expr()
            self.expect_op(")")
            return E.Agg("grouping", arg)
        if self.at_kw("distinct"):
            # e.g. count(distinct x) handled in func call; bare distinct invalid
            self.err("unexpected DISTINCT")
        if self.at_kw("substring"):
            self.next()
            self.expect_op("(")
            a = self.expr()
            if self.accept_op(","):
                b = self.expr()
                self.expect_op(",")
                c = self.expr()
            else:
                self.expect_kw("from")
                b = self.expr()
                self.expect_kw("for")
                c = self.expr()
            self.expect_op(")")
            return E.Func("substr", (a, b, c))
        if t.kind in ("id", "kw"):
            name = self.next().value
            if self.at_op("(") :
                return self.func_call(name)
            if self.accept_op("."):
                col = self.next().value
                return E.Col(col, name)
            return E.Col(name)
        self.err("expected expression")

    _AGG_FNS = {"sum", "avg", "count", "min", "max", "stddev_samp", "stddev", "var_samp"}
    _WIN_FNS = {"rank", "dense_rank", "row_number", "ntile", "lag", "lead", "first_value", "last_value"}

    def func_call(self, name):
        self.expect_op("(")
        distinct = False
        args = []
        if self.at_op("*"):
            self.next()
            args = []
            star = True
        else:
            star = False
            if not self.at_op(")"):
                distinct = self.accept_kw("distinct")
                args = self.expr_list()
        self.expect_op(")")
        over = None
        if self.accept_kw("over"):
            over = self.window_spec()
        if name in self._AGG_FNS and over is None:
            if name == "count" and star:
                return E.Agg("count", None, distinct)
            if name == "stddev":
                name = "stddev_samp"
            return E.Agg(name, args[0] if args else None, distinct)
        if over is not None:
            partition_by, order_by, frame = over
            arg = args[0] if args else None
            fn = name
            if name == "count" and star:
                arg = None
            return E.WindowFn(fn, arg, tuple(partition_by), tuple(order_by), frame)
        return E.Func(name, tuple(args))

    def window_spec(self):
        self.expect_op("(")
        partition_by = []
        order_by = []
        frame = None
        if self.accept_kw("partition"):
            self.expect_kw("by")
            partition_by = self.expr_list()
        if self.accept_kw("order"):
            self.expect_kw("by")
            for it in self.order_items():
                order_by.append((it.expr, it.ascending))
        if self.accept_kw("rows"):
            frame = self.frame_spec()
        self.expect_op(")")
        return partition_by, order_by, frame

    def frame_spec(self):
        def bound():
            if self.accept_kw("unbounded"):
                which = self.next().value  # preceding / following
                return ("unbounded", which)
            if self.accept_kw("current"):
                self.expect_kw("row")
                return ("current", None)
            n = int(self.next().value)
            which = self.next().value
            return (n, which)

        if self.accept_kw("between"):
            lo = bound()
            self.expect_kw("and")
            hi = bound()
            return (lo, hi)
        lo = bound()
        return (lo, ("current", None))

    def case_expr(self):
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.expr()
        branches = []
        while self.accept_kw("when"):
            cond = self.expr()
            self.expect_kw("then")
            val = self.expr()
            if operand is not None:
                cond = E.BinOp("=", operand, cond)
            branches.append((cond, val))
        default = None
        if self.accept_kw("else"):
            default = self.expr()
        self.expect_kw("end")
        return E.Case(tuple(branches), default)

    def cast_expr(self):
        self.expect_kw("cast")
        self.expect_op("(")
        e = self.expr()
        self.expect_kw("as")
        target = self.type_name()
        self.expect_op(")")
        return E.Cast(e, target)

    def type_name(self) -> DType:
        t = self.next()
        name = t.value
        if name in ("integer", "int"):
            return parse_dtype("int32")
        if name == "bigint":
            return parse_dtype("int64")
        if name == "smallint":
            return parse_dtype("int32")
        if name in ("double", "float", "real"):
            return parse_dtype("float64")
        if name in ("string",):
            return parse_dtype("string")
        if name == "date":
            return parse_dtype("date")
        if name in ("decimal", "numeric", "char", "varchar"):
            if self.accept_op("("):
                a = int(self.next().value)
                b = 0
                if self.accept_op(","):
                    b = int(self.next().value)
                self.expect_op(")")
                if name in ("decimal", "numeric"):
                    return DType("decimal", a, b)
                return DType(name, a)
            if name in ("decimal", "numeric"):
                return DType("decimal", 10, 0)
            return parse_dtype("string")
        raise SyntaxError(f"unknown type {name}")


def _as_lit(e):
    if isinstance(e, E.Lit):
        return e
    if isinstance(e, E.Cast) and isinstance(e.operand, E.Lit):
        return e.operand
    # constant folding: IN lists may contain literal arithmetic like [YEAR]+1
    if (
        isinstance(e, E.BinOp)
        and e.op in ("+", "-", "*")
        and isinstance(e.left, E.Lit)
        and isinstance(e.right, E.Lit)
        and isinstance(e.left.value, (int, float))
        and isinstance(e.right.value, (int, float))
    ):
        v = {"+": lambda a, b: a + b, "-": lambda a, b: a - b,
             "*": lambda a, b: a * b}[e.op](e.left.value, e.right.value)
        return E.Lit(v)
    raise SyntaxError(f"IN list must be literals, got {e}")


def parse_sql(sql: str):
    p = Parser(sql)
    stmt = p.parse_statement()
    while p.accept_op(";"):
        pass
    if p.peek().kind != "eof":
        p.err("trailing tokens")
    return stmt


def parse_script(sql: str):
    return Parser(sql).parse_script()
