"""Scalar expression IR + columnar evaluator.

Expressions evaluate over a `Table` to produce a `Column` (vectorized, whole
column at once, jnp ops on device). SQL three-valued logic is carried as a
(data, valid) pair; string functions run on the host over the column's
dictionary (O(|distinct|)) and reach the device as a single gather — the
design that keeps every TPU op dense and integer-typed (see dtypes.py).

This layer is the engine's counterpart of the expression kernels the reference
gets from Spark Catalyst + the rapids plugin (configured, not contained:
reference nds/power_run_gpu.template:33).
"""

from __future__ import annotations

import datetime
import re
from dataclasses import dataclass, field
from typing import Optional, Tuple

import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..dtypes import BOOL, DATE, DType, FLOAT64, INT32, INT64, STRING
from .columnar import Column, Table, sort_dictionary, unify_dictionaries

_EPOCH = datetime.date(1970, 1, 1)


def _civil_from_days(days):
    """Vectorized days-since-epoch -> (year, month, day) on device
    (Hinnant's civil calendar algorithm: pure integer floor arithmetic, so
    the date split runs as one fused XLA kernel instead of a host
    round-trip of the whole column)."""
    z = days.astype(jnp.int64) + 719468
    era = z // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y, m, d


def date_to_days(s: str) -> int:
    y, m, d = s.split("-")
    return (datetime.date(int(y), int(m), int(d)) - _EPOCH).days


def days_to_date(n: int) -> str:
    return (_EPOCH + datetime.timedelta(days=int(n))).isoformat()


# ---------------------------------------------------------------------------
# IR nodes
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Expr:
    def children(self) -> tuple:
        return ()


@dataclass(frozen=True)
class Col(Expr):
    name: str
    table: Optional[str] = None  # qualifier, resolved during binding

    def __str__(self):
        return f"{self.table}.{self.name}" if self.table else self.name


@dataclass(frozen=True)
class Lit(Expr):
    value: object  # python int/float/str/bool/None
    dtype: DType = None  # inferred when None

    def __str__(self):
        return repr(self.value)


@dataclass(frozen=True)
class Interval(Expr):
    """INTERVAL n DAYS — only the day unit appears in the NDS dialect
    (reference: nds/tpcds-gen/patches/templates.patch date arithmetic)."""

    days: int


@dataclass(frozen=True)
class BinOp(Expr):
    op: str  # + - * / and or = <> < <= > >= ||
    left: Expr
    right: Expr

    def children(self):
        return (self.left, self.right)


@dataclass(frozen=True)
class UnaryOp(Expr):
    op: str  # neg, not, isnull, isnotnull
    operand: Expr

    def children(self):
        return (self.operand,)


@dataclass(frozen=True)
class Between(Expr):
    operand: Expr
    low: Expr
    high: Expr
    negated: bool = False

    def children(self):
        return (self.operand, self.low, self.high)


@dataclass(frozen=True)
class InList(Expr):
    operand: Expr
    values: tuple  # of Lit
    negated: bool = False

    def children(self):
        return (self.operand,) + self.values


@dataclass(frozen=True)
class Like(Expr):
    operand: Expr
    pattern: str
    negated: bool = False

    def children(self):
        return (self.operand,)


@dataclass(frozen=True)
class Case(Expr):
    branches: tuple  # of (cond, value)
    default: Optional[Expr]

    def children(self):
        out = []
        for c, v in self.branches:
            out += [c, v]
        if self.default is not None:
            out.append(self.default)
        return tuple(out)


@dataclass(frozen=True)
class Cast(Expr):
    operand: Expr
    target: DType

    def children(self):
        return (self.operand,)


@dataclass(frozen=True)
class Func(Expr):
    """Scalar function call: substr, coalesce, abs, round, concat, ..."""

    name: str
    args: tuple

    def children(self):
        return self.args


@dataclass(frozen=True)
class Agg(Expr):
    """Aggregate function; consumed by the Aggregate operator, never by the
    scalar evaluator."""

    fn: str  # sum avg count min max stddev_samp count_distinct sum_distinct avg_distinct grouping
    arg: Optional[Expr]  # None for count(*)
    distinct: bool = False

    def children(self):
        return () if self.arg is None else (self.arg,)


@dataclass(frozen=True)
class WindowFn(Expr):
    """Window function; consumed by the Window operator."""

    fn: str  # rank dense_rank row_number sum avg min max count
    arg: Optional[Expr]
    partition_by: tuple = ()
    order_by: tuple = ()  # of (Expr, ascending)
    frame: Optional[tuple] = None  # ((lo, unit), (hi, unit)) ROWS frame

    def children(self):
        out = list(self.partition_by) + [e for e, _ in self.order_by]
        if self.arg is not None:
            out.append(self.arg)
        return tuple(out)


@dataclass(frozen=True)
class SubqueryExpr(Expr):
    """Scalar / IN / EXISTS subquery; replaced during planning."""

    query: object  # ast.SelectStmt
    kind: str  # scalar | in | exists
    operand: Optional[Expr] = None  # for IN
    negated: bool = False


@dataclass(frozen=True)
class ScalarSubquery(Expr):
    """Bound uncorrelated scalar subquery: the executor runs `plan` once
    (cached by identity) and broadcasts the single value."""

    plan: object = field(hash=False, compare=False, default=None)
    out_name: str = ""


def walk(e: Expr):
    yield e
    for c in e.children():
        yield from walk(c)


def contains_agg(e: Expr) -> bool:
    return any(isinstance(x, Agg) for x in walk(e))


def contains_window(e: Expr) -> bool:
    return any(isinstance(x, WindowFn) for x in walk(e))


# ---------------------------------------------------------------------------
# Evaluator
# ---------------------------------------------------------------------------


def _and_valid(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _lit_dtype(v) -> DType:
    if isinstance(v, bool):
        return BOOL
    if isinstance(v, int):
        return INT32 if -(2**31) <= v < 2**31 else INT64
    if isinstance(v, float):
        return FLOAT64
    if isinstance(v, str):
        return STRING
    if v is None:
        return INT32
    raise TypeError(f"bad literal {v!r}")


_DATE_RE = re.compile(r"^\d{4}-\d{2}-\d{2}$")


class Evaluator:
    """Evaluates an Expr over a Table, returning a Column of equal capacity."""

    def __init__(self, table: Table):
        self.table = table

    def eval(self, e: Expr) -> Column:
        m = getattr(self, f"_eval_{type(e).__name__.lower()}", None)
        if m is None:
            raise NotImplementedError(f"eval of {type(e).__name__}")
        return m(e)

    # ---- leaves ---------------------------------------------------------
    def _eval_col(self, e: Col) -> Column:
        key = f"{e.table}.{e.name}" if e.table else e.name
        if key in self.table.columns:
            return self.table.columns[key]
        if e.name in self.table.columns:
            return self.table.columns[e.name]
        raise KeyError(f"unknown column {key}; have {self.table.names[:8]}...")

    def _const(self, value, dtype: DType) -> Column:
        cap = self.table.cap
        if value is None:
            data = jnp.zeros(cap, dtype=dtype.device_np_dtype())
            return Column(data, dtype, jnp.zeros(cap, dtype=bool))
        if dtype.is_string:
            d = pa.array([value], type=pa.string())
            return Column(jnp.zeros(cap, dtype=jnp.int32), STRING, None, d)
        if dtype.kind == "date":
            v = date_to_days(value) if isinstance(value, str) else int(value)
            return Column(jnp.full(cap, v, dtype=jnp.int32), DATE)
        if dtype.is_decimal:
            v = int(round(float(value) * 10**dtype.scale))
            return Column(jnp.full(cap, v, dtype=jnp.int64), dtype)
        return Column(
            jnp.full(cap, value, dtype=dtype.device_np_dtype()), dtype
        )

    def _eval_lit(self, e: Lit) -> Column:
        dtype = e.dtype or _lit_dtype(e.value)
        return self._const(e.value, dtype)

    # ---- arithmetic / comparison ---------------------------------------
    def _numeric_pair(self, a: Column, b: Column):
        """Align two numeric columns onto a common computational dtype.

        decimals are aligned to a common scale (exact int64 path) unless mixed
        with float, which demotes both to float64.
        """
        da, db = a.dtype, b.dtype
        if da.is_decimal and db.is_decimal:
            s = max(da.scale, db.scale)
            xa = a.data * (10 ** (s - da.scale))
            xb = b.data * (10 ** (s - db.scale))
            return xa, xb, DType("decimal", 38, s)
        if da.is_decimal and db.is_numeric:
            if db.kind == "float64":
                return a.data.astype(jnp.float64) / 10**da.scale, b.data, FLOAT64
            return a.data, b.data.astype(jnp.int64) * 10**da.scale, da
        if db.is_decimal:
            xb, xa, dt = self._numeric_pair(b, a)[0:3]
            return xa, xb, dt
        if da.kind == "float64" or db.kind == "float64":
            return (
                a.data.astype(jnp.float64),
                b.data.astype(jnp.float64),
                FLOAT64,
            )
        if da.kind == "date" and db.kind == "date":
            return a.data, b.data, DATE
        if da.kind == "int64" or db.kind == "int64":
            return a.data.astype(jnp.int64), b.data.astype(jnp.int64), INT64
        return a.data, b.data, INT32

    def _eval_binop(self, e: BinOp) -> Column:
        op = e.op
        if op in ("and", "or"):
            return self._eval_logical(e)
        if op == "||":
            return self._eval_concat(e)
        a = self.eval(e.left)
        b = self.eval(e.right)
        valid = _and_valid(a.valid, b.valid)
        # date +/- interval
        if isinstance(e.right, Interval) or b.dtype.kind == "interval":
            raise AssertionError("interval handled via Func below")
        if op in ("+", "-") and a.dtype.kind == "date" and b.dtype.is_integer:
            data = a.data + b.data.astype(jnp.int32) * (1 if op == "+" else -1)
            return Column(data, DATE, valid)
        if op in ("+", "-") and b.dtype.kind == "date" and a.dtype.is_integer:
            data = b.data + a.data.astype(jnp.int32) * (1 if op == "+" else -1)
            return Column(data, DATE, valid)
        if op in ("=", "<>", "<", "<=", ">", ">="):
            return self._compare(op, a, b, valid)
        if a.dtype.is_string or b.dtype.is_string:
            raise TypeError(f"arith {op} on strings")
        if op == "*" and (a.dtype.is_decimal or b.dtype.is_decimal):
            # products multiply *unscaled* operands: scale(s1)*scale(s2) ->
            # scale s1+s2 (the common-scale alignment of _numeric_pair is
            # only right for +/-/compare, and would waste two multiplies)
            s1 = a.dtype.scale if a.dtype.is_decimal else 0
            s2 = b.dtype.scale if b.dtype.is_decimal else 0
            if a.dtype.kind == "float64" or b.dtype.kind == "float64":
                fa = a.data.astype(jnp.float64) / 10**s1
                fb = b.data.astype(jnp.float64) / 10**s2
                return Column(fa * fb, FLOAT64, valid)
            da = a.data.astype(jnp.int64)
            db = b.data.astype(jnp.int64)
            return Column(da * db, DType("decimal", 38, s1 + s2), valid)
        xa, xb, dt = self._numeric_pair(a, b)
        if op == "+":
            return Column(xa + xb, dt, valid)
        if op == "-":
            dtr = INT32 if dt.kind == "date" else dt
            return Column(xa - xb, dtr, valid)
        if op == "*":
            return Column(xa * xb, dt, valid)
        if op == "/":
            fa = xa.astype(jnp.float64)
            fb = xb.astype(jnp.float64)
            if dt.is_decimal:
                fa = fa / 10**dt.scale
                fb = fb / 10**dt.scale
            zero = fb == 0
            data = jnp.where(zero, jnp.nan, fa / jnp.where(zero, 1.0, fb))
            valid = _and_valid(valid, ~zero)  # SQL: x/0 is NULL
            return Column(data, FLOAT64, valid)
        raise NotImplementedError(f"binop {op}")

    def _compare(self, op, a: Column, b: Column, valid) -> Column:
        if a.dtype.is_string or b.dtype.is_string:
            xa, xb = self._string_cmp_codes(a, b, op)
        else:
            xa, xb, _ = self._numeric_pair(a, b)
        fn = {
            "=": jnp.equal,
            "<>": jnp.not_equal,
            "<": jnp.less,
            "<=": jnp.less_equal,
            ">": jnp.greater,
            ">=": jnp.greater_equal,
        }[op]
        return Column(fn(xa, xb), BOOL, valid)

    def _string_cmp_codes(self, a: Column, b: Column, op):
        """Map both string operands to comparable integer keys."""
        if a.dtype.is_string and b.dtype.is_string:
            if op in ("=", "<>"):
                ca, cb, _ = unify_dictionaries(a, b)
                return ca, cb
            ra, _ = sort_dictionary(a)
            rb, _ = sort_dictionary(b)
            # ordering across two dictionaries needs a shared ranking
            ca, cb, ud = unify_dictionaries(a, b)
            uni_col_a = Column(ca, STRING, a.valid, ud)
            uni_col_b = Column(cb, STRING, b.valid, ud)
            ra, _ = sort_dictionary(uni_col_a)
            rb, _ = sort_dictionary(uni_col_b)
            return ra, rb
        # string vs non-string: cast the string side
        s, o = (a, b) if a.dtype.is_string else (b, a)
        sc = _cast_column(s, o.dtype if o.dtype.kind != "date" else DATE, self.table.cap)
        xa = sc.data if a.dtype.is_string else a.data
        xb = b.data if a.dtype.is_string else sc.data
        if o.dtype.is_decimal:
            pass
        return (xa, xb)

    # ---- boolean logic (Kleene) ----------------------------------------
    def _eval_logical(self, e: BinOp) -> Column:
        a = self.eval(e.left)
        b = self.eval(e.right)
        av = a.valid if a.valid is not None else jnp.ones(self.table.cap, bool)
        bv = b.valid if b.valid is not None else jnp.ones(self.table.cap, bool)
        ad = a.data.astype(bool)
        bd = b.data.astype(bool)
        if e.op == "and":
            data = (ad & av) & (bd & bv)
            # false if either side is definitively false
            false_ = (av & ~ad) | (bv & ~bd)
            valid = av & bv | false_
        else:
            data = (ad & av) | (bd & bv)
            true_ = (av & ad) | (bv & bd)
            valid = av & bv | true_
        return Column(data, BOOL, valid)

    def _eval_unaryop(self, e: UnaryOp) -> Column:
        a = self.eval(e.operand)
        if e.op == "neg":
            return Column(-a.data, a.dtype, a.valid)
        if e.op == "not":
            return Column(~a.data.astype(bool), BOOL, a.valid)
        if e.op == "isnull":
            v = (
                jnp.zeros(self.table.cap, bool)
                if a.valid is None
                else ~a.valid
            )
            return Column(v, BOOL, None)
        if e.op == "isnotnull":
            v = (
                jnp.ones(self.table.cap, bool)
                if a.valid is None
                else a.valid
            )
            return Column(v, BOOL, None)
        raise NotImplementedError(e.op)

    # ---- predicates -----------------------------------------------------
    def _eval_between(self, e: Between) -> Column:
        lo = BinOp(">=", e.operand, e.low)
        hi = BinOp("<=", e.operand, e.high)
        out = self._eval_logical(BinOp("and", lo, hi))
        if e.negated:
            return Column(~out.data, BOOL, out.valid)
        return out

    def _eval_inlist(self, e: InList) -> Column:
        a = self.eval(e.operand)
        values = [v.value for v in e.values]
        if a.dtype.is_string:
            d = a.dictionary
            hit = pc.is_in(d.cast(pa.string()), value_set=pa.array(values, pa.string()))
            lut = jnp.asarray(hit.to_numpy(zero_copy_only=False))
            data = lut[jnp.clip(a.data, 0, len(d) - 1)]
        else:
            data = jnp.zeros(self.table.cap, bool)
            for v in values:
                cmp = self._compare("=", a, self._lit_like(v, a.dtype), None)
                data = data | cmp.data
        data = data if not e.negated else ~data
        return Column(data, BOOL, a.valid)

    def _lit_like(self, v, dtype: DType) -> Column:
        if dtype.kind == "date" and isinstance(v, str):
            return self._const(v, DATE)
        if dtype.is_decimal:
            return self._const(v, dtype)
        return self._const(v, dtype if not dtype.is_string else STRING)

    def _eval_like(self, e: Like) -> Column:
        a = self.eval(e.operand)
        if not a.dtype.is_string:
            raise TypeError("LIKE on non-string")
        d = a.dictionary.cast(pa.string())
        hit = pc.match_like(d, e.pattern)
        lut = jnp.asarray(
            hit.to_numpy(zero_copy_only=False).astype(bool)
        )
        data = lut[jnp.clip(a.data, 0, max(len(d) - 1, 0))]
        if e.negated:
            data = ~data
        return Column(data, BOOL, a.valid)

    # ---- case / cast / functions ----------------------------------------
    def _eval_case(self, e: Case) -> Column:
        branches = [(self.eval(c), self.eval(v)) for c, v in e.branches]
        default = (
            self.eval(e.default)
            if e.default is not None
            else None
        )
        vals = [v for _, v in branches] + ([default] if default else [])
        out_dtype = _common_dtype([v.dtype for v in vals])
        vals = [_cast_column(v, out_dtype, self.table.cap) for v in vals]
        if out_dtype.is_string:
            vals, shared = _share_dictionary(vals)
        else:
            shared = None
        n = len(branches)
        if default is not None:
            data = vals[n].data
            valid = (
                vals[n].valid
                if vals[n].valid is not None
                else jnp.ones(self.table.cap, bool)
            )
        else:
            data = jnp.zeros(self.table.cap, out_dtype.device_np_dtype())
            valid = jnp.zeros(self.table.cap, bool)
        decided = jnp.zeros(self.table.cap, bool)
        for (cond, _), val in zip(branches, vals[:n]):
            cv = cond.valid if cond.valid is not None else jnp.ones(self.table.cap, bool)
            take = cond.data.astype(bool) & cv & ~decided
            data = jnp.where(take, val.data, data)
            vv = val.valid if val.valid is not None else jnp.ones(self.table.cap, bool)
            valid = jnp.where(take, vv, valid)
            decided = decided | take
        return Column(data, out_dtype, valid, shared)

    def _eval_cast(self, e: Cast) -> Column:
        return _cast_column(self.eval(e.operand), e.target, self.table.cap)

    def _eval_interval(self, e: Interval) -> Column:
        return self._const(e.days, INT32)

    def _eval_func(self, e: Func) -> Column:
        name = e.name
        if name == "coalesce":
            cols = [self.eval(a) for a in e.args]
            dt = _common_dtype([c.dtype for c in cols])
            cols = [_cast_column(c, dt, self.table.cap) for c in cols]
            if dt.is_string:
                cols, shared = _share_dictionary(cols)
            else:
                shared = None
            data = cols[-1].data
            valid = cols[-1].valid
            for c in reversed(cols[:-1]):
                cv = c.valid if c.valid is not None else jnp.ones(self.table.cap, bool)
                data = jnp.where(cv, c.data, data)
                pv = valid if valid is not None else jnp.ones(self.table.cap, bool)
                valid = jnp.where(cv, True, pv)
            return Column(data, dt, valid, shared)
        if name == "abs":
            a = self.eval(e.args[0])
            return Column(jnp.abs(a.data), a.dtype, a.valid)
        if name == "round":
            a = self.eval(e.args[0])
            nd = e.args[1].value if len(e.args) > 1 else 0
            if a.dtype.is_decimal:
                s = a.dtype.scale
                if nd >= s:
                    return a
                q = 10 ** (s - nd)
                half = q // 2
                data = jnp.where(
                    a.data >= 0, (a.data + half) // q, -((-a.data + half) // q)
                ) * q
                return Column(data, a.dtype, a.valid)
            f = 10.0**nd
            return Column(jnp.round(a.data * f) / f, FLOAT64, a.valid)
        if name in ("substr", "substring"):
            return self._string_transform(
                e.args[0],
                lambda d: pc.utf8_slice_codeunits(
                    d,
                    start=e.args[1].value - 1,
                    stop=e.args[1].value - 1 + e.args[2].value,
                ),
            )
        if name == "upper":
            return self._string_transform(e.args[0], pc.utf8_upper)
        if name == "lower":
            return self._string_transform(e.args[0], pc.utf8_lower)
        if name == "trim":
            return self._string_transform(e.args[0], pc.utf8_trim_whitespace)
        if name in ("year", "month", "day"):
            a = self.eval(e.args[0])
            y, m, d = _civil_from_days(a.data)
            out = y if name == "year" else (m if name == "month" else d)
            return Column(out.astype(jnp.int32), INT32, a.valid)
        if name == "date_add":
            a = self.eval(e.args[0])
            b = self.eval(e.args[1])
            return Column(a.data + b.data.astype(jnp.int32), DATE, _and_valid(a.valid, b.valid))
        if name == "date_sub":
            a = self.eval(e.args[0])
            b = self.eval(e.args[1])
            return Column(a.data - b.data.astype(jnp.int32), DATE, _and_valid(a.valid, b.valid))
        if name == "nullif":
            a = self.eval(e.args[0])
            b = self.eval(e.args[1])
            eq = self._compare("=", a, b, None)
            # NULLIF(a, NULL) = a: the equality only nulls when b is valid,
            # else a NULL b whose fill value matches a.data would null a out.
            nulled = eq.data if b.valid is None else eq.data & b.valid
            av = a.valid if a.valid is not None else jnp.ones(self.table.cap, bool)
            return Column(a.data, a.dtype, av & ~nulled, a.dictionary)
        if name == "concat":
            out = self.eval(e.args[0])
            for arg in e.args[1:]:
                out = self._concat_cols(out, self.eval(arg))
            return out
        raise NotImplementedError(f"function {name}")

    def _string_transform(self, arg: Expr, fn) -> Column:
        a = self.eval(arg)
        if not a.dtype.is_string:
            raise TypeError("string function on non-string")
        d = a.dictionary.cast(pa.string())
        new_vals = fn(d)
        # canonicalize the transformed dictionary (dedupe) + remap codes
        enc = pc.dictionary_encode(new_vals)
        remap = jnp.asarray(
            enc.indices.to_numpy(zero_copy_only=False).astype(np.int32)
        )
        codes = remap[jnp.clip(a.data, 0, len(d) - 1)]
        return Column(codes, STRING, a.valid, enc.dictionary)

    def _eval_concat(self, e: BinOp) -> Column:
        return self._concat_cols(self.eval(e.left), self.eval(e.right))

    def _concat_cols(self, a: Column, b: Column) -> Column:
        valid = _and_valid(a.valid, b.valid)
        if a.dtype.is_string and b.dictionary is None and not b.dtype.is_string:
            raise TypeError("concat with non-string")
        da = a.dictionary.cast(pa.string())
        db = b.dictionary.cast(pa.string())
        if len(da) * len(db) <= 65536:
            # small cross-product: build the pairwise dictionary on host
            cross = pc.binary_join_element_wise(
                pa.array(np.repeat(np.asarray(da), len(db))),
                pa.array(np.tile(np.asarray(db), len(da))),
                "",
            )
            enc = pc.dictionary_encode(cross)
            remap = jnp.asarray(
                enc.indices.to_numpy(zero_copy_only=False).astype(np.int32)
            ).reshape(len(da), len(db))
            codes = remap[
                jnp.clip(a.data, 0, len(da) - 1), jnp.clip(b.data, 0, len(db) - 1)
            ]
            return Column(codes, STRING, valid, enc.dictionary)
        # large: materialize row-wise on host (rare path)
        av = np.asarray(da)[np.clip(np.asarray(a.data), 0, len(da) - 1)]
        bv = np.asarray(db)[np.clip(np.asarray(b.data), 0, len(db) - 1)]
        joined = pc.binary_join_element_wise(
            pa.array(av.astype(object)), pa.array(bv.astype(object)), ""
        )
        enc = pc.dictionary_encode(joined)
        codes = jnp.asarray(
            enc.indices.to_numpy(zero_copy_only=False).astype(np.int32)
        )
        return Column(codes, STRING, valid, enc.dictionary)


# ---------------------------------------------------------------------------
# Casting / type unification
# ---------------------------------------------------------------------------


def _common_dtype(dtypes) -> DType:
    out = dtypes[0]
    for d in dtypes[1:]:
        out = _promote(out, d)
    return out


def _promote(a: DType, b: DType) -> DType:
    if a == b:
        return a
    if a.is_string or b.is_string:
        return STRING
    if a.kind == "float64" or b.kind == "float64":
        return FLOAT64
    if a.is_decimal and b.is_decimal:
        return DType("decimal", 38, max(a.scale, b.scale))
    if a.is_decimal:
        return a
    if b.is_decimal:
        return b
    if a.kind == "date" or b.kind == "date":
        return DATE
    if a.kind == "int64" or b.kind == "int64":
        return INT64
    if a.is_bool and b.is_bool:
        return BOOL
    return INT32


def _cast_column(c: Column, target: DType, cap: int) -> Column:
    src = c.dtype
    if src == target or (src.is_string and target.is_string):
        return c
    if target.is_string:
        # non-string -> string: format on host via dictionary of distinct vals
        arr = np.asarray(c.data)
        if src.is_decimal:
            vals = arr / 10**src.scale
            strs = np.array([f"{v:.{src.scale}f}" for v in vals], dtype=object)
        elif src.kind == "date":
            strs = np.array([days_to_date(v) for v in arr], dtype=object)
        else:
            strs = arr.astype(str).astype(object)
        enc = pc.dictionary_encode(pa.array(strs, pa.string()))
        return Column(
            jnp.asarray(enc.indices.to_numpy(zero_copy_only=False).astype(np.int32)),
            STRING,
            c.valid,
            enc.dictionary,
        )
    if src.is_string:
        # string -> numeric/date: parse the dictionary on host, gather codes.
        # Unparseable entries become NULL (Spark cast semantics), not 0 —
        # a garbage date must never join date_dim's epoch row.
        d = c.dictionary.cast(pa.string())
        entries = np.asarray(d).tolist()
        if not entries:
            npdt = (
                np.int32
                if target.kind == "date"
                else np.int64 if target.is_decimal else target.device_np_dtype()
            )
            n = c.data.shape[0]
            return Column(
                jnp.zeros(n, npdt), target, jnp.zeros(n, bool)
            )
        lut = []
        lut_ok = []
        for s in entries:
            try:
                if s is None or (isinstance(s, str) and not s.strip()):
                    raise ValueError
                s = s.strip() if isinstance(s, str) else s
                if target.kind == "date":
                    if not _DATE_RE.match(s):
                        raise ValueError
                    v = date_to_days(s)
                elif target.is_decimal:
                    v = int(round(float(s) * 10**target.scale))
                else:
                    v = target.device_np_dtype()(float(s))
                lut.append(v)
                lut_ok.append(True)
            except (ValueError, TypeError):
                lut.append(0)
                lut_ok.append(False)
        npdt = (
            np.int32
            if target.kind == "date"
            else np.int64 if target.is_decimal else target.device_np_dtype()
        )
        lut = np.asarray(lut, dtype=npdt)
        lut_ok = np.asarray(lut_ok, dtype=bool)
        codes = jnp.clip(c.data, 0, max(len(entries) - 1, 0))
        data = jnp.asarray(lut)[codes]
        parsed = jnp.asarray(lut_ok)[codes] if not lut_ok.all() else None
        valid = _and_valid(c.valid, parsed)
        return Column(data, target, valid)
    if target.is_decimal:
        if src.is_decimal:
            shift = target.scale - src.scale
            data = c.data * 10**shift if shift >= 0 else c.data // 10 ** (-shift)
            return Column(data, target, c.valid)
        if src.kind == "float64":
            data = jnp.round(c.data * 10**target.scale).astype(jnp.int64)
            return Column(data, target, c.valid)
        return Column(
            c.data.astype(jnp.int64) * 10**target.scale, target, c.valid
        )
    if src.is_decimal:
        if target.kind == "float64":
            return Column(
                c.data.astype(jnp.float64) / 10**src.scale, target, c.valid
            )
        return Column(
            (c.data // 10**src.scale).astype(target.device_np_dtype()),
            target,
            c.valid,
        )
    return Column(c.data.astype(target.device_np_dtype()), target, c.valid)


def _share_dictionary(cols):
    """Remap string columns onto one merged dictionary (CASE/COALESCE)."""
    dicts = [
        (c.dictionary if c.dictionary is not None else pa.array([], pa.string())).cast(
            pa.string()
        )
        for c in cols
    ]
    unified = pc.unique(pa.concat_arrays(dicts))
    out = []
    for c, d in zip(cols, dicts):
        if len(d) == 0:
            out.append(Column(c.data, STRING, c.valid, unified))
            continue
        remap = jnp.asarray(
            pc.index_in(d, unified).to_numpy(zero_copy_only=False).astype(np.int32)
        )
        out.append(
            Column(remap[jnp.clip(c.data, 0, len(d) - 1)], STRING, c.valid, unified)
        )
    return out, unified
