"""Plan executor: interprets a logical plan over device Tables.

Eager, operator-at-a-time execution. Each operator is built from the jitted
kernels in nds_tpu.ops.kernels over power-of-two-bucketed buffers, so the
shapes XLA compiles stay bounded while live row counts vary freely. Join
ordering inside MultiJoin is greedy over *actual* row counts — eager
execution's answer to AQE (reference: nds/properties/aqe-on.properties:1).

The executor is the engine the reference delegates to Spark executors + the
rapids plugin (reference: nds/nds_power.py:125-135 spark.sql -> collect).
"""

from __future__ import annotations

import itertools
import math

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from dataclasses import replace as _dc_replace
from time import perf_counter as _perf

from .. import faults
from ..dtypes import BOOL, DType, FLOAT64, INT64
from ..ops import kernels as K
from . import aotcache as AOTC
from . import expr as E
from . import fuse
from . import plan as P
from . import spill as SP
from .columnar import (
    Column,
    Table,
    _dyn_slice,
    bucket_cap,
    table_to_arrow,
    unify_dictionaries,
    sort_dictionary,
    table_device_bytes,
    window_slice,
)
from .expr import (
    Evaluator,
    _and_valid,
    _cast_column,
    _common_dtype,
    _share_dictionary,
)


class ExecError(Exception):
    pass


# executor instance ids for op-span grouping in the event log (profiling
# reconstructs span nesting per (query, executor) from seq/depth)
_EXEC_IDS = itertools.count(1)


def _resolve_bounds(datas, valids, stats_list, wanted, live):
    """(vmin, vmax) per column: from cached ColStats when present, else one
    batched min/max kernel + a single device->host transfer for ALL missing
    ranges. `wanted[i]=False` slots return None. Shared by the group-key and
    sort-key packers."""
    bounds, need = [], []
    for i, (st, w) in enumerate(zip(stats_list, wanted)):
        if not w:
            bounds.append(None)
            continue
        if st is not None and st.vmin is not None and st.vmax is not None:
            bounds.append((int(st.vmin), int(st.vmax)))
        else:
            bounds.append(None)
            need.append(i)
    if need:
        fetched = jax.device_get(
            K.batched_min_max(
                [datas[i].astype(jnp.int64) for i in need],
                [valids[i] for i in need],
                live,
            )
        )
        for i, mm in zip(need, fetched):
            bounds[i] = (int(mm[0]), int(mm[1]))
    return bounds


def _cascade_agg_items(agg_items):
    """Re-aggregation exprs for the rollup cascade, or None when any
    aggregate doesn't decompose over partial results. sum/min/max compose
    with themselves; any count becomes a sum of the level below's counts."""
    out = []
    for a, name in agg_items:
        if a.distinct or a.fn not in ("sum", "min", "max", "count"):
            return None
        fn = "sum" if a.fn == "count" else a.fn
        out.append((E.Agg(fn, E.Col(name)), name))
    return out


def _rollup_base_aggs(agg_items):
    """(base agg list, avg items) for grouping-sets execution: every plain
    avg is decomposed into hidden sum (__cs_<name>) + count (__cc_<name>)
    columns so the cascade can compose it; the visible avg column is
    derived per part by _derive_rollup_avgs with the exact semantics of
    the direct avg path (float64, decimal descale, NULL on empty).
    Returns (None, []) when any aggregate rules the rewrite out."""
    if not P.aggs_decomposable(agg_items):
        return None, []
    avg_items = [(a, n) for a, n in agg_items if a.fn == "avg"]
    if not avg_items:
        return list(agg_items), []
    base = []
    for a, name in agg_items:
        if a.fn == "avg":
            base.append((E.Agg("sum", a.arg), f"__cs_{name}"))
            base.append((E.Agg("count", a.arg), f"__cc_{name}"))
        else:
            base.append((a, name))
    return base, avg_items


def _derive_rollup_avgs(part: "Table", avg_items):
    if not avg_items:
        return part
    cols = dict(part.columns)
    for _, name in avg_items:
        cs = cols[f"__cs_{name}"]
        cc = cols[f"__cc_{name}"]
        n = cc.data
        val = cs.data.astype(jnp.float64) / jnp.maximum(n, 1)
        if cs.dtype.is_decimal:
            val = val / 10**cs.dtype.scale
        cols[name] = Column(val, FLOAT64, n > 0)
    return Table(cols, part.nrows_lazy, live=part.live)


def _plain_col_names(exprs, table):
    """Column names referenced by plain Col exprs, resolved the way the
    evaluator resolves them against `table` (qualified first, bare next)."""
    out = set()
    for e in exprs:
        if isinstance(e, E.Col):
            key = f"{e.table}.{e.name}" if e.table else e.name
            if key not in table.columns and e.name in table.columns:
                key = e.name
            out.add(key)
    return out


def _active_key_names(key_items, key_cols):
    """Group-by output rows are pairwise distinct over the active (non-
    rolled-up) key columns; probe-style joins read this to skip runtime
    uniqueness checks."""
    return frozenset(
        name for (_, name), c in zip(key_items, key_cols) if c is not None
    )


def _group_key_stats(c: "Column", n_active_keys: int):
    """Output stats for a group-by key column: bounds are the input's
    (group keys are a value subset); a single-key grouping's output is
    unique by construction — which is exactly what downstream probe-style
    joins (dense, packed) need to know to avoid a runtime uniqueness
    check."""
    st = c.subset_stats()
    if st is None:
        return None
    return _dc_replace(st, unique=(n_active_keys == 1))


class _DictStats:
    """Static bounds facade for dictionary-coded columns (codes/ranks span
    [0, len(dictionary)) by construction — no device fetch needed)."""

    __slots__ = ("vmin", "vmax", "unique", "base_rows")

    def __init__(self, vmin, vmax):
        self.vmin = vmin
        self.vmax = vmax
        self.unique = False
        self.base_rows = 0


class Executor:
    def __init__(self, catalog, on_task_failure=None, tracer=None):
        """catalog: object with .load(table_name) -> Table.

        on_task_failure(reason) is called for recoverable incidents the
        executor survives (capacity-overflow retries, fallbacks) so the
        harness can report CompletedWithTaskFailures (reference analogue:
        Spark task retries surfaced via jvm_listener).

        tracer: an obs.Tracer (defaults to the owning session's) — every
        executed plan node then records an `op_span` event with inclusive
        wall time, output rows, and estimated output bytes. Per-executor
        span state (exec id, seq, depth) is thread-safe by construction:
        each concurrent throughput stream builds its own Executor per
        statement, so streams never share span collections (the old
        module-global TRACE_NODES would have corrupted across streams)."""
        self.catalog = catalog
        self.on_task_failure = on_task_failure or (lambda reason: None)
        self._cte_cache = {}  # id(plan) -> Table
        self._scalar_cache = {}  # id(plan) -> python value
        self._fp_cache = {}  # id(plan) -> structural fingerprint
        # stats of the most recent blocked union-aggregation (tests/tools)
        self.last_blocked_union = None
        # stats of this statement's out-of-core (spilled) operator
        # executions, accumulated across ops (tests/bench evidence)
        self.last_spill = None
        self._fault_checked = False  # exec-root injection fires once
        # inside a spilled-join partition loop the mesh exchange path is
        # disabled: the partitions exist because an exchange (or the
        # budgeter) already decided the whole join can't fit — re-entering
        # the exchange per partition pair could recurse under skew
        self._exchange_disabled = False
        if tracer is None:
            tracer = getattr(
                getattr(catalog, "session", None), "tracer", None
            )
        self.tracer = tracer
        self._span_depth = 0
        self._span_seq = 0
        self._exec_id = next(_EXEC_IDS) if tracer is not None else 0

    # plan-node types worth caching across statements: the expensive
    # pipeline breakers (a CTE body virtually always ends in one)
    _CACHEABLE = (P.Aggregate, P.Distinct, P.SetOp, P.Window)

    def _session_cache(self):
        session = getattr(self.catalog, "session", None)
        if session is None:
            return None
        if session.conf.get("engine.plan_cache", "on") == "off":
            return None
        return session.plan_cache

    def _fp(self, node) -> str:
        key = id(node)
        fp = self._fp_cache.get(key)
        if fp is None:
            fp = self._fp_cache[key] = P.fingerprint(node)
        return fp

    # pipeline breakers whose actual row count is worth a forced host
    # sync when it isn't already there: a handful per plan, and their
    # consumers are about to sync anyway. Row-preserving nodes record
    # only opportunistically (count already on host) — feedback must not
    # add a device round-trip per traced node.
    _FEEDBACK_SYNC = (P.Join, P.MultiJoin, P.Aggregate, P.Distinct,
                      P.SetOp, P.Window, P.Sort)

    def _record_feedback(self, node, out):
        """Record this node's measured cardinality into the session
        FeedbackStore (buffered; Result.table flushes per statement).
        Only called for nodes budget_plan annotated with `node_fp` —
        i.e. engine.plan_feedback is record/on and a store exists."""
        session = getattr(self.catalog, "session", None)
        store = getattr(session, "feedback_store", None)
        if store is None:
            return
        rows = out.nrows_known
        if rows is None and (
            isinstance(node, self._FEEDBACK_SYNC)
            or (isinstance(node, P.Pipeline) and node.agg is not None)
        ):
            rows = out.nrows
        if rows is None:
            return
        est_rows = getattr(node, "est_rows", None)
        with session.cache_lock:
            err = store.record(
                node.node_fp, rows=rows, nbytes=table_device_bytes(out),
                est_rows=est_rows,
            )
        if self.tracer is not None:
            ev = dict(op="record", result="ok",
                      node=type(node).__name__, actual_rows=int(rows))
            if est_rows is not None:
                ev["est_rows"] = int(est_rows)
            if err is not None:
                ev["abs_log_err"] = round(err, 4)
            self.tracer.emit("plan_feedback", **ev)

    # ------------------------------------------------------------------
    def execute(self, node: P.PlanNode) -> Table:
        if not self._fault_checked:
            # failure-domain injection site at the executor root (once per
            # executor, i.e. per statement): `exec:<query>` faults fire
            # inside the engine proper, past plan/bind, so the harness
            # ladder sees exactly what a mid-execution device failure
            # looks like. Zero-cost when no fault spec is installed.
            self._fault_checked = True
            if faults.active():
                scope = faults.current_scope()
                if scope is not None:
                    faults.maybe_fire(f"exec:{scope}")
        key = id(node)
        if key in self._cte_cache:
            return self._cte_cache[key]
        tracer = self.tracer
        # agg-tail Pipelines are the fused form of a (cacheable) Aggregate:
        # they keep the cross-statement CTE reuse the raw node had
        cacheable = isinstance(node, self._CACHEABLE) or (
            isinstance(node, P.Pipeline) and node.agg is not None
        )
        cache = self._session_cache() if cacheable else None
        if cache is not None:
            with self.catalog.session.cache_lock:
                hit = cache.get(self._fp(node))
            if tracer is not None:
                tracer.emit(
                    "plan_cache", node=type(node).__name__,
                    hit=hit is not None,
                )
            if hit is not None:
                self._cte_cache[key] = hit
                return hit
        m = getattr(self, f"_exec_{type(node).__name__.lower()}")
        if tracer is not None:
            # INCLUSIVE wall time (children execute inside this frame);
            # repeated visits are cte-cache dict hits, so each node records
            # once per executor. Spans emit in completion (post-) order
            # with (exec_id, seq, depth) so the profiler can rebuild the
            # tree and derive exclusive times.
            depth = self._span_depth
            self._span_depth = depth + 1
            t0 = _perf()
            try:
                out = m(node)
            finally:
                self._span_depth = depth
            dur_ms = (_perf() - t0) * 1000.0
            # estimate-vs-actual accounting BEFORE the span emit: a
            # pipeline-breaker record may force the queued count onto the
            # host, and the span's actual_rows should see it
            fp = getattr(node, "node_fp", None)
            if fp is not None:
                self._record_feedback(node, out)
            self._span_seq += 1
            span = dict(
                exec_id=self._exec_id,
                seq=self._span_seq,
                depth=depth,
                node=type(node).__name__,
                explain=P.node_desc(node)[:90],
                dur_ms=round(dur_ms, 3),
                # nrows_known only: forcing a queued count would add a
                # device sync to every traced node
                rows=out.nrows_known,
                est_bytes=table_device_bytes(out),
            )
            if fp is not None:
                # budgeter accounting (analysis/feedback.py annotations):
                # est_rows/est_live_bytes are the STATIC model's numbers,
                # actual_* what this execution measured. `est_bytes`
                # above keeps its historical meaning (realized device
                # bytes — the calibration harness pins it)
                span["node_fp"] = fp
                span["est_rows"] = getattr(node, "est_rows", None)
                span["est_live_bytes"] = getattr(
                    node, "est_live_bytes", None
                )
                span["actual_rows"] = out.nrows_known
                span["actual_bytes"] = table_device_bytes(out)
            tracer.emit("op_span", **span)
        else:
            out = m(node)
            if getattr(node, "node_fp", None) is not None:
                self._record_feedback(node, out)
        self._cte_cache[key] = out
        if cache is not None:
            with self.catalog.session.cache_lock:
                cache.put(self._fp(node), out)
        return out

    def to_arrow(self, node: P.PlanNode) -> pa.Table:
        return table_to_arrow(self.execute(node))

    # ------------------------------------------------------------------
    def _exec_scan(self, node: P.Scan) -> Table:
        # lake_version: the plan-time snapshot pin (Session._pin_lake_scans)
        # — threading it here keeps the scan on ITS statement's snapshot
        # even when another stream sharing this session has re-pinned the
        # catalog entry, and after a device-OOM recovery wiped the cache
        # lake_files: the zone-map pruned file subset
        # (Session._prune_lake_scans) — the load opens only surviving files
        t = self.catalog.load(
            node.table, node.columns, lake_version=node.lake_version,
            lake_files=node.lake_files,
        )
        uk = t.unique_key
        if uk is not None:
            uk = frozenset(f"{node.alias}.{n}" for n in uk)
        return Table(
            {f"{node.alias}.{n}": c for n, c in t.columns.items()}, t.nrows,
            unique_key=uk,
        )

    def _exec_materializedscan(self, node: P.MaterializedScan) -> Table:
        if node.name == "__dual__":
            return Table({}, 1)
        if node.table is None:
            raise ExecError(f"materialized scan {node.name} not populated")
        return node.table

    def _exec_project(self, node: P.Project) -> Table:
        return self._project_table(self.execute(node.child), node.items)

    def _project_table(self, child: Table, items) -> Table:
        ev = self._evaluator(child)
        child_cols = {id(c) for c in child.columns.values()}
        cols = {}
        renames = {}  # child column name -> output name (plain Col items)
        for e, name in items:
            c = ev.eval(e)
            # plain renames share the child's Column object: ownership must
            # not cross the node boundary (the child may be cache-retained)
            cols[name] = c.disowned() if id(c) in child_cols else c
            if isinstance(e, E.Col):
                # mirror Evaluator._eval_col resolution order
                key = f"{e.table}.{e.name}" if e.table else e.name
                if key not in child.columns and e.name in child.columns:
                    key = e.name
                renames.setdefault(key, name)
        if not cols:
            return Table({}, child.nrows)
        uk = child.unique_key
        if uk is not None and all(k in renames for k in uk):
            uk = frozenset(renames[k] for k in uk)
        else:
            uk = None
        # deferred-compaction mask rides through (masked rows hold garbage
        # expression values, which stay masked)
        return Table(cols, child.nrows_lazy, live=child.live, unique_key=uk)

    def _exec_filter(self, node: P.Filter) -> Table:
        child = self.execute(node.child)
        return self._masked(child, self._predicate_mask(child, node.predicate))

    # -- fused Filter/Project pipelines -----------------------------------
    # A Pipeline node (fuse.mark_pipelines) executes its whole chain as ONE
    # jitted function over the child's device columns: no per-node
    # dispatch, no materialized intermediates, masks deferred to the
    # pipeline boundary. Executables are reused across reruns AND across
    # structurally identical queries via the session ExecutableCache
    # (keyed on stage fingerprint + dtype signature; jax keys per capacity
    # bucket underneath). Chains that cannot trace fall back to the exact
    # eager per-stage path, and the signature is pinned so the build is
    # attempted once.

    def _aot_build_args(self, session):
        """(AotCache | None, conf signature) for a FusedPipeline build:
        the session's persistent executable cache plus the engine conf
        values that change traced code and therefore join the on-disk
        entry key (engine/aotcache.py key discipline)."""
        aot = getattr(session, "aot_cache", None) if session else None
        if aot is None:
            return None, ()
        return aot, (
            str(session.conf.get("engine.fuse_agg", "on")),
            str(session.conf.get("engine.pallas_agg", "off")),
        )

    def _exec_pipeline(self, node: P.Pipeline) -> Table:
        child = self.execute(node.child)
        session = getattr(self.catalog, "session", None)
        tracer = self.tracer
        t0 = _perf() if tracer is not None else 0.0
        out = None
        fused = False
        has_agg = node.agg is not None
        if (
            session is not None
            and session.conf.get("engine.fuse", "on") != "off"
            and child.columns
            and child.cap > 0
            # backstop only — the plan rewrite already skips agg absorption
            # under a Pallas mode (Session._finish_plan), so this fires
            # solely for plans cached before conf flipped pallas_agg on:
            # the fused scatter would bypass the per-aggregate Pallas seam
            and not (
                has_agg
                and session.conf.get("engine.pallas_agg", "off") != "off"
            )
        ):
            fp = getattr(node, "_stage_fp", None)
            if fp is None:
                fp = node._stage_fp = P.fingerprint(
                    P.Pipeline(stages=node.stages, child=None, agg=node.agg)
                )
            sig = fuse.input_signature(child, with_stats=has_agg)
            aot, conf_sig = self._aot_build_args(session)
            if has_agg:
                def build():
                    return fuse.FusedAggPipeline(
                        node.stages, node.agg, child,
                        aot=aot, fp=fp, conf_sig=conf_sig,
                    )
            else:
                def build():
                    return fuse.FusedPipeline(
                        node.stages, child, aot=aot, fp=fp,
                        conf_sig=conf_sig,
                    )
            with session.cache_lock:
                entry, hit = session.exec_cache.lookup(
                    fp, sig, child.cap, build
                )
            if tracer is not None:
                tracer.emit(
                    "exec_cache", pipeline=fp[:12], bucket=child.cap,
                    hit=hit, fused=entry is not None,
                )
            if entry is not None:
                donate = (
                    node.donate_ok
                    and session.conf.get("engine.fuse_donate", "off")
                    == "on"
                )
                try:
                    out = entry.call(child, donate)
                    fused = True
                except Exception as exc:
                    if donate:
                        # the failed call may already have donated (and so
                        # invalidated) the child's input buffers — an eager
                        # retry over those would read garbage; surface the
                        # failure to the harness ladder instead
                        raise
                    # compile/runtime failure on a chain that traced
                    # abstractly: pin the signature to the eager path
                    with session.cache_lock:
                        session.exec_cache.map[(fp, sig)] = None
                    self.on_task_failure(
                        f"pipeline fuse fallback: {str(exc)[:120]}"
                    )
        if out is None:
            # eager per-stage path (_apply_wrappers wants top-down order)
            out = self._apply_wrappers(child, list(reversed(node.stages)))
            if has_agg:
                out = self._aggregate_once(
                    node.agg.keys, node.agg.aggs, None, out,
                    out.row_mask(), out.nrows_known,
                )
        if tracer is not None:
            tracer.emit(
                "pipeline_span",
                stages=len(node.stages),
                fused=fused,
                agg=has_agg,
                dur_ms=round((_perf() - t0) * 1000.0, 3),
                rows=out.nrows_known,
            )
        return out

    def _exec_limit(self, node: P.Limit) -> Table:
        # top-k fusion: ORDER BY .. LIMIT n computes the sort order but
        # gathers only the first bucket_cap(n) sorted rows per column —
        # the full-capacity permutation gather of every output column was
        # pure waste at fact shapes (most TPC-DS queries end in exactly
        # this shape). Requires the rewrite pass's single-consumer
        # annotation (fuse.mark_pipelines sets _topk_safe) — a shared
        # Sort's full result must compute once and serve every consumer —
        # and falls back when the distributed sort engages (it returns a
        # fully packed table).
        if (
            isinstance(node.child, P.Sort)
            and getattr(node.child, "_topk_safe", False)
            and id(node.child) not in self._cte_cache
        ):
            sort = node.child
            child = self._pack_sparse(self.execute(sort.child))
            if child.nrows_known != 0:
                words, dist = self._sort_order_words(sort, child)
                if dist is None:
                    order = K.sort_by_words(words)
                    n = min(node.n, child.nrows)
                    cap = bucket_cap(max(n, 1))
                    return self._take(child, order[:cap], n)
                child = dist
            n = min(node.n, child.nrows)
            cap = bucket_cap(max(n, 1))
            child = child.compacted()
            cols = {
                name: Column(
                    c.data[:cap], c.dtype,
                    None if c.valid is None else c.valid[:cap],
                    c.dictionary, c.subset_stats(),
                )
                for name, c in child.columns.items()
            }
            return Table(cols, n)
        child = self.execute(node.child).compacted()
        n = min(node.n, child.nrows)
        cap = bucket_cap(n)
        cols = {
            name: Column(
                c.data[:cap],
                c.dtype,
                None if c.valid is None else c.valid[:cap],
                c.dictionary,
                c.subset_stats(),
            )
            for name, c in child.columns.items()
        }
        return Table(cols, n)

    def _exec_sort(self, node: P.Sort) -> Table:
        child = self._pack_sparse(self.execute(node.child))
        if child.nrows_known == 0:
            return child
        words, dist = self._sort_order_words(node, child)
        if dist is not None:
            return dist
        order = self._sort_perm_route(words)
        parts = self._spill_parts_for(node)
        if parts > 1:
            # external sort: the SAME device sort order, but the output
            # gather runs in bounded windows staged through the host spill
            # pool (sorted runs) instead of materializing every column's
            # full-capacity gather at once — results are bit-identical to
            # the direct path because the permutation is identical
            out = self._spilled_take(child, order, parts, op="sort")
            if out is not None:
                return out
        return self._take(child, order, child.nrows_lazy)

    def _sort_order_words(self, node: P.Sort, child: Table):
        """(sort words, distributed-sort result|None) for a Sort node over
        its already-executed input — shared by the full sort and the
        Limit-over-Sort top-k path."""
        ev = self._evaluator(child)
        keys = []
        cols = []
        for e, asc, nf in node.keys:
            col = ev.eval(e)
            cols.append(col)
            data = col.data
            if col.dtype.is_string:
                data, _ = sort_dictionary(col)
            if col.dtype.kind == "bool":
                data = data.astype(jnp.int32)
            if nf is None:
                nf = asc  # Spark: NULLS FIRST for ASC, NULLS LAST for DESC
            keys.append((data, col.valid, asc, nf))
        words = self._sort_words(keys, cols, child.row_mask())
        dist = self._try_dist_sort(
            child, [(w, None, True, True) for w in words]
        )
        return words, dist

    # -- sort-key word encoding -------------------------------------------
    # Every ordering in the engine (ORDER BY, group-by adjacency, window
    # partition sort) is encoded into int64 *words*, most significant
    # first, and sorted by stable LSD passes over the ONE canonical kv-sort
    # kernel per input cap (K.sort_by_words). XLA:TPU sort compiles cost
    # ~10-12 s per comparator operand at fact shapes, so per-query
    # comparator kernels were the dominant cold-start cost (q34's 3-operand
    # lexsort at 4M rows alone compiled for 102 s).
    #
    # Encoding per key, in significance order: integer-like keys with a
    # known span pack as mixed-radix fields (asc: v-vmin+1, desc: vmax-v+1;
    # null first -> 0, null last -> span-1) into shared <=62-bit words;
    # floats and huge-span ints emit a 1-bit null-rank field into the
    # shared stream plus one standalone full-width word (floats via the
    # order-preserving bit transform, descending via bitwise not). A
    # leading 1-bit live field keeps dead rows last. Exact — codes are
    # monotone (and injective) per key.

    def _sort_words(self, keys, cols, live, include_live=True):
        """keys: (data, valid, ascending, nulls_first) in major->minor
        order; cols: aligned Column|None for cached bounds (None or
        stats-less columns fetch bounds in one batched device round trip).
        Returns the int64 word list for K.sort_by_words/K.group_by_words."""
        packable = [
            not jnp.issubdtype(d.dtype, jnp.floating) for d, _, _, _ in keys
        ]
        stats_list = []
        for (d, v, _, _), c, pk in zip(keys, cols, packable):
            if c is not None and c.dictionary is not None:
                # dictionary codes/ranks span [0, len) statically: no stats
                # lookup and no device fetch needed
                stats_list.append(
                    _DictStats(0, max(len(c.dictionary) - 1, 0))
                )
            else:
                stats_list.append(c.stats if c is not None else None)
        bounds = _resolve_bounds(
            [k[0] for k in keys], [k[1] for k in keys], stats_list, packable,
            live,  # dead/padded rows must not widen the spans
        )
        # The encoding compiles as ONE jitted function per (spec, shapes)
        # key (K.build_sort_words) instead of an eager op chain per query;
        # widths quantize so queries with similar key spans share the
        # compiled encoder. Standalone words: ints fold direction via
        # order-reversing bitwise not; floats stay NATIVE f64 words (this
        # TPU toolchain cannot bitcast emulated 64-bit types) with -0.0
        # normalized, nulls masked before the NaN rank, NaN in a 1-bit
        # rank field (Spark: NaN greater than +inf), direction by negation.
        spec = []
        arrays = []
        if include_live:
            spec.append(("L",))
        for (d, v, asc, nf), pk, b in zip(keys, packable, bounds):
            if nf is None:
                nf = asc
            hv = v is not None
            if d.dtype == jnp.bool_:
                d = d.astype(jnp.int32)
            if pk:
                vmin, vmax = b
                if vmax < vmin:  # empty/all-null: constant key, skip
                    continue
                span = vmax - vmin + 3  # codes 1..span-2; 0, top for NULL
                width = K.quantize_width(max(1, int(span - 1).bit_length()))
                if width <= 62:
                    spec.append(("i", width, asc, nf, hv))
                    arrays += [d, jnp.int64(vmin), jnp.int64(vmax)]
                    if hv:
                        arrays.append(v)
                    continue
                spec.append(("I", asc, nf, hv))
            else:
                spec.append(("f", asc, nf, hv))
            arrays.append(d)
            if hv:
                arrays.append(v)
        if not spec:  # every key constant: one trivial live word
            spec.append(("L",))
        return list(K.build_sort_words(tuple(spec), live, *arrays))

    def _group_words(self, active_cols, live):
        """Word encoding for group-by adjacency (equality only): the sort
        encoding with asc/nulls-first defaults is injective, so equal words
        <=> equal keys and group enumeration order == key sort order."""
        keys = []
        for c in active_cols:
            d = c.data
            if d.dtype == jnp.bool_:
                d = d.astype(jnp.int32)
            keys.append((d, c.valid, True, True))
        return self._sort_words(keys, active_cols, live)

    # -- distributed sort -------------------------------------------------
    # ORDER BY over a mesh-sharded table: range-partitioned samplesort +
    # global rank compaction over ICI (nds_tpu/parallel/dist.py:sample_sort)
    # instead of the all-gathering lexsort the generic path would lower to.
    # Default threshold derives PER DEVICE (n_dev x this): the old flat
    # 256Ki floor was a dryrun-era cap that kept the exchange paths cold at
    # every realistic bench scale — SF0.01 fact scans must already route
    # through the collective machinery so the mesh gate exercises it.
    _DIST_SORT_MIN_ROWS_PER_DEV = 2048

    def _mesh_min_rows(self, session, conf_key, per_dev, n_dev) -> int:
        """Row threshold for a mesh collective path: explicit conf wins,
        else n_dev x per-device default (scale-out keeps the single-device
        crossover point instead of inheriting a flat pod-sized floor)."""
        v = session.conf.get(conf_key)
        if v is not None:
            try:
                return int(v)
            except (TypeError, ValueError):
                pass
        return int(n_dev) * int(per_dev)

    def _emit_exchange(self, op, n_dev, bytes_moved, counts, retries,
                       dur_ms=None, node_fp=None):
        """One `exchange` trace event per executed collective exchange:
        bytes moved over the interconnect (padded-capacity measure, both
        all_to_all passes), partition (device) count, the received-row
        skew ratio (max device / mean; 1.0 = perfectly balanced), how
        many capacity-overflow retries the step burned, the measured wall
        of the whole exchange step (`dur_ms`, retries included — the
        critical-path profiler's exchange-wait cause), and the per-device
        received-row counts (`per_device` — what names the straggler
        device).

        With a `node_fp` (plan_feedback record/on) the measured skew also
        records into the session FeedbackStore — the seed the NEXT
        execution's capacity guess consumes instead of the retry ladder —
        even when the session is untraced."""
        if self.tracer is None and node_fp is None:
            return
        c = np.asarray(counts, dtype=np.float64)
        total = float(c.sum())
        skew = 1.0
        if total > 0 and c.size:
            skew = float(c.max() / (total / c.size))
        if node_fp is not None:
            session = getattr(self.catalog, "session", None)
            store = getattr(session, "feedback_store", None)
            if store is not None:
                with session.cache_lock:
                    store.record_skew(node_fp, skew, retries=int(retries))
        if self.tracer is None:
            return
        self.tracer.emit(
            "exchange", op=op, partitions=int(n_dev),
            bytes_moved=int(bytes_moved), skew=round(skew, 3),
            retries=int(retries),
            per_device=[int(x) for x in c],
            **({"dur_ms": round(float(dur_ms), 3)}
               if dur_ms is not None else {}),
        )

    def _feedback_skew_seed(self, node_fp, n_dev) -> int:
        """Integer capacity multiplier from a recorded exchange skew for
        this plan node (plan_feedback=on), clamped to the mesh width (a
        single destination can never need more than n_dev x the balanced
        per-bucket share). 1 = no recorded skew worth seeding."""
        if node_fp is None:
            return 1
        session = getattr(self.catalog, "session", None)
        store = getattr(session, "feedback_store", None)
        if store is None:
            return 1
        # mesh-only cold path (see _try_exchange_join)
        # nds-lint: disable=local-import
        from ..analysis.feedback import resolve_feedback_mode

        if resolve_feedback_mode(session.conf) != "on":
            return 1
        with session.cache_lock:
            rec = store.lookup(node_fp)
        skew = float(((rec or {}).get("skew") or {}).get("max") or 0.0)
        if skew <= 1.25:
            return 1
        return int(min(math.ceil(skew), n_dev))

    def _try_dist_sort(self, child: Table, keys):
        if not keys:
            # every sort key was dropped by the packer (all-null/empty with
            # no stats): nothing to route on, use the local sort path
            return None
        session = getattr(self.catalog, "session", None)
        mesh = getattr(session, "mesh", None)
        if mesh is None:
            return None
        n_dev = mesh.devices.size
        min_rows = self._mesh_min_rows(
            session, "engine.dist_sort_min_rows",
            self._DIST_SORT_MIN_ROWS_PER_DEV, n_dev,
        )
        if child.nrows < min_rows:
            return None
        cap = child.cap
        if cap % n_dev or cap // n_dev == 0:
            return None
        # mesh-only cold path: keeps jax sharding/collective machinery out
        # of single-chip startup; reached once per distributed sort
        # nds-lint: disable=local-import
        from ..parallel.dist import get_sample_sort

        # transformed lexsort keys (major->minor), via the same fold as
        # K.sort_indices so the two orderings cannot diverge
        tkeys = []
        route = None
        for data, valid, asc, nf in keys:
            folded = K.fold_sort_key(data, valid, asc, nf)
            tkeys.extend(folded)
            if route is None:
                # routing value: monotone in (null_rank, value) of the primary
                # key — nulls fold to the dtype extreme so they colocate
                d = folded[-1]
                if valid is None:
                    route = d
                else:
                    if jnp.issubdtype(d.dtype, jnp.floating):
                        ext = jnp.asarray(-jnp.inf if nf else jnp.inf, d.dtype)
                    else:
                        info = jnp.iinfo(d.dtype)
                        ext = jnp.asarray(info.min if nf else info.max, d.dtype)
                    route = jnp.where(valid, d, ext)
        payload = []
        has_valid = []
        for c in child.columns.values():
            payload.append(c.data)
            has_valid.append(c.valid is not None)
        for c in child.columns.values():
            if c.valid is not None:
                payload.append(c.valid)
        live = child.row_mask()
        local_rows = cap // n_dev
        cap_route = bucket_cap(max(1, 2 * local_rows // n_dev))
        retries = 0
        ex_t0 = _perf()
        while True:
            fn = get_sample_sort(mesh, len(tkeys), len(payload), cap_route)
            out = fn(route, live, *tkeys, *payload)
            overflow = int(out[-1])
            if overflow == 0:
                break
            if cap_route >= local_rows:  # can't overflow at this cap; bug guard
                return None
            retries += 1
            self.on_task_failure(
                f"task retry: distributed sort bucket overflow "
                f"({overflow} rows); doubling route capacity"
            )
            cap_route = min(cap_route * 2, local_rows)
        per_row = sum(int(a.dtype.itemsize) for a in tkeys + payload) + 1
        self._emit_exchange(
            "sort", n_dev,
            per_row * (n_dev * n_dev * cap_route + n_dev * cap),
            out[-2], retries, dur_ms=(_perf() - ex_t0) * 1000.0,
        )
        cols_out = out[1:1 + len(child.columns)]
        valids_out = list(out[1 + len(child.columns):-2])
        cols = {}
        vi = 0
        for i, (name, c) in enumerate(child.columns.items()):
            valid = None
            if has_valid[i]:
                valid = valids_out[vi]
                vi += 1
            cols[name] = Column(
                cols_out[i], c.dtype, valid, c.dictionary, c.subset_stats()
            )
        return Table(cols, child.nrows)

    def _exec_distinct(self, node: P.Distinct) -> Table:
        child = self.execute(node.child)
        if child.nrows_known == 0:
            return child
        return self._distinct_table(
            child, spill_parts=self._spill_parts_for(node)
        )

    # ------------------------------------------------------------------
    def _exec_setop(self, node: P.SetOp) -> Table:
        left = self.execute(node.left)
        right = self.execute(node.right)
        if node.op == "union_all":
            return self._concat(left, right)
        if node.op == "union":
            return self._distinct_table(
                self._concat(left, right),
                spill_parts=self._spill_parts_for(node),
            )
        # intersect / except: set semantics over whole rows
        dl = self._distinct_table(left)
        names = list(dl.columns)
        rnames = list(right.columns)
        lkeys, lvalids, rkeys, rvalids = [], [], [], []
        for ln, rn in zip(names, rnames):
            for lk, rk in zip(
                *self._join_key_pair(dl.columns[ln], right.columns[rn])
            ):
                lkeys.append(lk.data)
                lvalids.append(lk.valid)
                rkeys.append(rk.data)
                rvalids.append(rk.valid)
        # NULLs compare equal in set ops: fold validity into the key and add
        # one null-flag key per column on BOTH sides (sides can differ in
        # nullability; the flag lists must stay aligned)
        keys_l, keys_r = [], []
        for d, v in zip(lkeys, lvalids):
            keys_l.append(
                jnp.where(v, d, jnp.zeros((), d.dtype)) if v is not None else d
            )
        for d, v in zip(rkeys, rvalids):
            keys_r.append(
                jnp.where(v, d, jnp.zeros((), d.dtype)) if v is not None else d
            )
        zl = jnp.zeros(dl.cap, bool)
        zr = jnp.zeros(right.cap, bool)
        for lv, rv in zip(lvalids, rvalids):
            keys_l.append(~lv if lv is not None else zl)
            keys_r.append(~rv if rv is not None else zr)
        li, ri, pl, _ = K.join_candidates(
            keys_l, [None] * len(keys_l), dl.row_mask(),
            keys_r, [None] * len(keys_r), right.row_mask(),
        )
        ok = K.verify_pairs(
            li, ri, pl,
            keys_l, [None] * len(keys_l), dl.row_mask(),
            keys_r, [None] * len(keys_r), right.row_mask(),
        )
        present = K.matched_mask(li, ok, dl.cap)
        if node.op == "intersect":
            mask = present & dl.row_mask()
        else:
            mask = ~present & dl.row_mask()
        return self._masked(dl, mask)

    # ------------------------------------------------------------------
    def _exec_join(self, node: P.Join) -> Table:
        left = self.execute(node.left)
        right = self.execute(node.right)
        return self._join(
            left, right, node.kind, node.left_keys, node.right_keys,
            node.residual, node.mark_name,
            spill_parts=self._spill_parts_for(node),
            node_fp=getattr(node, "node_fp", None),
        )

    def _exec_multijoin(self, node: P.MultiJoin) -> Table:
        tables = self._execute_relations_batched(node.relations)
        # join-order replay ACROSS statements: the greedy cost scan reads
        # joined-intermediate row counts, which is a blocking device->host
        # sync (~90 ms on the bench tunnel) per join step after the first.
        # Steady-state reruns and repeated stream queries replay the
        # recorded order instead (same fingerprint => same query text and
        # literals, so the recorded order stays the right one; any order
        # is correct regardless). This recovers the q3 rows/s the round-5
        # join-graph optimizer cost — see docs/q3_regression.md.
        trace = None
        session = getattr(self.catalog, "session", None)
        if (
            session is not None
            and session.conf.get("engine.join_order_cache", "on") != "off"
        ):
            with session.cache_lock:
                trace = session.join_order_cache.setdefault(
                    self._fp(node), {}
                )
        return self._multijoin_over_tables(
            tables, node.edges, trace=trace,
            spill_parts=self._spill_parts_for(node),
            node_fp=getattr(node, "node_fp", None),
        )

    def _multijoin_over_tables(self, tables, edges, trace=None,
                               spill_parts=0, node_fp=None) -> Table:
        """Greedy N-way inner join over already-executed relation tables
        (shared by _exec_multijoin and the blocked union-aggregation path,
        which re-joins each union window against the other relations).
        `trace`: optional dict; the first call records its join-order
        decisions into it and later calls replay them, skipping the greedy
        cost scan — whose current[g].nrows reads are blocking device->host
        syncs (~90 ms each on the bench tunnel) that would otherwise run
        once per window per join step."""
        n = len(tables)
        if n == 1:
            return tables[0]
        # adjacency: edge list by relation index
        edges = list(edges)
        merged = list(range(n))  # union-find-ish: relation -> group id

        def group(i):
            while merged[i] != i:
                i = merged[i]
            return i

        current = {i: tables[i] for i in range(n)}

        return self._multijoin_greedy(current, edges, merged, group, n, trace,
                                      spill_parts, node_fp=node_fp)

    def _execute_relations_batched(self, relations):
        """Execute a MultiJoin's relations and materialize their live
        counts with ONE device->host round trip.

        Filters produce deferred-compaction tables whose counts are queued
        asynchronously; the greedy join-order heuristic below needs host
        integers, so all still-lazy counts batch into a single
        jax.device_get instead of paying ~90 ms per relation."""
        tables = [self.execute(r) for r in relations]
        lazy = [t for t in tables if t.nrows_known is None]
        if lazy:
            for t, v in zip(lazy, jax.device_get([t.nrows_lazy for t in lazy])):
                t._nrows = int(v)
        return tables

    def _multijoin_greedy(self, current, edges, merged, group, n, trace=None,
                          spill_parts=0, node_fp=None):
        # greedy: repeatedly take the connecting edge whose joined inputs are
        # smallest (sum of live rows), execute that join. When `trace`
        # carries recorded steps, replay them instead (identical relation
        # sets join in the same order, and replay never reads .nrows — the
        # blocked union path joins every window with zero count syncs).
        replay = trace is not None and "steps" in trace
        steps = trace["steps"] if replay else []
        step_i = 0
        while True:
            groups = {group(i) for i in range(n)}
            if len(groups) == 1:
                break
            if replay:
                kind, gi, gj = steps[step_i]
                step_i += 1
            else:
                best = None
                for k, (i, j, le, re_) in enumerate(edges):
                    gi, gj = group(i), group(j)
                    if gi == gj:
                        continue
                    cost = current[gi].nrows + current[gj].nrows
                    if best is None or cost < best[0]:
                        best = (cost, k, gi, gj)
                if best is None:
                    kind, gi, gj = "cross", *sorted(
                        groups, key=lambda g: current[g].nrows
                    )[:2]
                else:
                    kind, gi, gj = "edge", best[2], best[3]
                steps.append((kind, gi, gj))
            if kind == "cross":
                # disconnected components: cross join smallest two groups
                joined = self._join(
                    current[gi], current[gj], "cross", [], [], None
                )
                merged[gj] = gi
                current[gi] = joined
                continue
            # gather ALL edges connecting these two groups as one multi-key join
            lkeys, rkeys = [], []
            rest = []
            for (i, j, le, re_) in edges:
                if {group(i), group(j)} == {gi, gj}:
                    if group(i) == gi:
                        lkeys.append(le)
                        rkeys.append(re_)
                    else:
                        lkeys.append(re_)
                        rkeys.append(le)
                else:
                    rest.append((i, j, le, re_))
            edges = rest
            joined = self._join(
                current[gi], current[gj], "inner", lkeys, rkeys, None,
                spill_parts=spill_parts, node_fp=node_fp,
            )
            merged[gj] = gi
            current[gi] = joined
        if trace is not None and not replay:
            # `trace` may be a join_order_cache entry (steady replays read
            # it from other statements' threads) or a blocked-union
            # context's private memo; both callers guarantee a session
            with self.catalog.session.cache_lock:
                trace["steps"] = steps
        return current[group(0)]

    # ------------------------------------------------------------------
    def _pack_sparse(self, t: Table) -> Table:
        """Compact a deferred-compaction table whose live fraction is small:
        sort/hash consumers scale with CAP, so a 5k-of-131k masked build
        side would pay 26x its packed cost. The count is usually already
        materialized (or long since queued), so this rarely blocks."""
        if t.live is None:
            return t
        if t.nrows < max(t.cap // 8, 1024):
            return t.compacted()
        return t

    def _join(self, left, right, kind, left_keys, right_keys, residual,
              mark_name=None, spill_parts=0, node_fp=None):
        if kind == "cross":
            return self._cross_join(left, right)
        left = self._pack_sparse(left)
        right = self._pack_sparse(right)
        if kind == "right":
            # swap before any matching so the residual is preserved
            return self._join(right, left, "left", right_keys, left_keys,
                              residual, spill_parts=spill_parts,
                              node_fp=node_fp)
        lev = self._evaluator(left)
        rev = self._evaluator(right)
        lcols = [lev.eval(e) for e in left_keys]
        rcols = [rev.eval(e) for e in right_keys]
        lk, lv, rk, rv = [], [], [], []
        aligned = []  # (left Column, right Column) pairs, dtype-unified
        for a, b in zip(lcols, rcols):
            for ca, cb in zip(*self._join_key_pair(a, b)):
                aligned.append((ca, cb))
                lk.append(ca.data)
                lv.append(ca.valid)
                rk.append(cb.data)
                rv.append(cb.valid)
        llive = left.row_mask()
        rlive = right.row_mask()
        fast = self._try_dense_join(
            left, right, kind, lcols, rcols, lk, lv, rk, rv, llive, rlive,
            residual, mark_name,
        )
        if fast is not None:
            return fast
        fast = self._try_exchange_join(
            left, right, kind, left_keys, right_keys,
            lk, lv, rk, rv, llive, rlive, residual, node_fp=node_fp,
        )
        if fast is not None:
            return fast
        fast = self._try_packed_join(
            left, right, kind, aligned, right_keys, llive, rlive, residual,
            mark_name,
        )
        if fast is not None:
            return fast
        if spill_parts > 1 and kind in ("inner", "left"):
            # out-of-core tier: the generic sort join's pair expansion +
            # full-width pair-table gathers are THE additive-HBM shape of
            # build-side-too-big joins; hash-partition both sides, join
            # partition pairs one at a time (probe re-scanned per
            # partition) and stage each partition's output in the host
            # spill pool instead of accumulating it on device
            return self._spilled_join(
                left, right, kind, left_keys, right_keys, residual,
                lk, lv, llive, rk, rv, rlive, spill_parts,
            )
        li, ri, pl, total = K.join_candidates(lk, lv, llive, rk, rv, rlive)
        ok = K.verify_pairs(li, ri, pl, lk, lv, llive, rk, rv, rlive)

        if kind in ("semi", "anti", "mark"):
            if residual is not None:
                ok = self._apply_residual(ok, li, ri, left, right, residual)
            present = K.matched_mask(li, ok, left.cap)
            if kind == "mark":
                out_cols = {
                    n: c.disowned() for n, c in left.columns.items()
                }
                out_cols[mark_name] = Column(present, BOOL)
                return Table(out_cols, left.nrows_lazy, live=left.live)
            mask = (present if kind == "semi" else ~present) & llive
            return self._masked(left, mask)

        count = K.mask_count(ok)
        out_cap = bucket_cap(max(count, 1))
        sel = K.compact_indices(ok, out_cap)
        pli = li[sel]
        pri = ri[sel]
        if residual is not None:
            # build pair table first, filter, recompact
            pair = self._pair_table(left, right, pli, pri, count, rnull=None)
            pmask = self._predicate_mask(pair, residual)
            if kind == "inner":
                return self._masked(pair, pmask)
            # outer joins: surviving pairs only count as matches. Scatter with
            # max, not set: sel's padding duplicates index 0 and a plain set
            # could clobber candidate 0's True with a padded False.
            ok2 = jnp.zeros(ok.shape, bool).at[sel].max(pmask)
            ok = ok & ok2
            count = K.mask_count(ok)
            out_cap = bucket_cap(max(count, 1))
            sel = K.compact_indices(ok, out_cap)
            pli = li[sel]
            pri = ri[sel]

        if kind == "inner":
            return self._pair_table(left, right, pli, pri, count, rnull=None)

        if kind == "left":
            present = K.matched_mask(li, ok, left.cap)
            unmatched = ~present & llive
            n_un = K.mask_count(unmatched)
            total_rows = count + n_un
            cap2 = bucket_cap(max(total_rows, 1))
            un_idx = K.compact_indices(unmatched, bucket_cap(max(n_un, 1)))
            all_li = jnp.concatenate([pli[:count] if count else pli[:0], un_idx[:n_un]])
            all_li = jnp.pad(all_li, (0, cap2 - all_li.shape[0]))
            all_ri = jnp.concatenate(
                [pri[:count] if count else pri[:0], jnp.zeros(n_un, jnp.int32)]
            )
            all_ri = jnp.pad(all_ri, (0, cap2 - all_ri.shape[0]))
            rnull = jnp.arange(cap2) >= count  # right side null for appended rows
            return self._pair_table(left, right, all_li, all_ri, total_rows, rnull)

        if kind == "full":
            lpresent = K.matched_mask(li, ok, left.cap)
            rpresent = K.matched_mask(ri, ok, right.cap)
            lun = ~lpresent & llive
            run = ~rpresent & rlive
            n_lu = K.mask_count(lun)
            n_ru = K.mask_count(run)
            total_rows = count + n_lu + n_ru
            cap2 = bucket_cap(max(total_rows, 1))
            lu_idx = K.compact_indices(lun, bucket_cap(max(n_lu, 1)))[:n_lu]
            ru_idx = K.compact_indices(run, bucket_cap(max(n_ru, 1)))[:n_ru]
            all_li = jnp.concatenate(
                [pli[:count], lu_idx, jnp.zeros(n_ru, jnp.int32)]
            )
            all_ri = jnp.concatenate(
                [pri[:count], jnp.zeros(n_lu, jnp.int32), ru_idx]
            )
            all_li = jnp.pad(all_li, (0, cap2 - all_li.shape[0]))
            all_ri = jnp.pad(all_ri, (0, cap2 - all_ri.shape[0]))
            pos = jnp.arange(cap2)
            rnull = (pos >= count) & (pos < count + n_lu)
            lnull = pos >= count + n_lu
            return self._pair_table(
                left, right, all_li, all_ri, total_rows, rnull, lnull
            )
        raise ExecError(f"join kind {kind}")

    # -- dense-domain star-join fast path --------------------------------
    # TPC-DS fact->dim joins hit this: single int key whose build-side
    # domain is dense (surrogate keys). Probes are elementwise gathers, so
    # the fact side never sorts, and under a mesh the probe stays local per
    # chip (build side replicated). Falls back to the sort join otherwise.
    # Plan choice is driven purely by catalog-load ColStats — zero device
    # round-trips here (the round-2 per-join masked_min_max/counts.max()
    # syncs were the 2x single-chip regression).
    _DENSE_MAX_DOMAIN = 1 << 22

    def _try_dense_join(
        self, left, right, kind, lcols, rcols, lk, lv, rk, rv, llive, rlive,
        residual, mark_name,
    ):
        if len(lk) != 1:
            return None
        if kind not in ("inner", "left", "semi", "anti", "mark"):
            return None
        if kind in ("semi", "anti", "mark") and residual is not None:
            return None
        if kind == "left" and residual is not None:
            return None
        # int-like keys on both sides only: stats exist for these alone, and
        # the gate keeps float/decimal keys (value-changing casts) off the
        # dense path entirely
        for c in (lcols[0], rcols[0]):
            if c.dtype.kind not in ("int32", "int64", "date"):
                return None
        rst = rcols[0].stats
        if rst is None:
            return None
        if kind in ("inner", "left") and not rst.unique:
            # inner/left must not expand output per probe row; without a
            # uniqueness guarantee from base-table stats, use the sort join
            return None
        rmin, rmax = rst.vmin, rst.vmax
        domain = rmax - rmin + 1
        # bound the lookup table by the BASE table's size (bounds are base-
        # table-wide even when the build side is already filtered down)
        if domain > min(
            self._DENSE_MAX_DOMAIN, max(1 << 14, 8 * max(rst.base_rows, right.cap))
        ):
            return None
        rnn = K._all_valid([rv[0]], rlive)
        rkey = rk[0].astype(jnp.int64)
        table_cap = bucket_cap(domain)
        presence, rows = self._dense_build_route(rkey, rnn, rmin, table_cap)
        lnn = K._all_valid([lv[0]], llive)
        matched, ri = K.dense_probe(
            lk[0].astype(jnp.int64), lnn, rmin, presence, rows, table_cap
        )
        return self._augment_join_output(
            left, right, kind, matched, ri, llive, residual, mark_name
        )

    def _augment_join_output(
        self, left, right, kind, matched, ri, llive, residual, mark_name,
    ):
        """Left-aligned join output for probe-style paths (dense, packed):
        matched rows live in place, right columns gathered alongside — no
        count sync, no compaction gathers."""
        if kind in ("semi", "anti", "mark"):
            if kind == "mark":
                out_cols = {
                    n: c.disowned() for n, c in left.columns.items()
                }
                out_cols[mark_name] = Column(matched, BOOL)
                return Table(
                    out_cols, left.nrows_lazy, live=left.live,
                    unique_key=left.unique_key,
                )
            mask = (matched if kind == "semi" else ~matched) & llive
            return self._masked(left, mask)
        if kind == "inner":
            # LEFT columns pass through by reference and are DISOWNED: the
            # left table may be a CTE/plan-cache-retained result (e.g. the
            # first relation of a MultiJoin), and a passthrough that kept
            # owned=True would let a downstream donating pipeline free
            # buffers that cached table still reads. Right-side gathers
            # are fresh buffers owned by this output alone.
            out_cols = {n: c.disowned() for n, c in left.columns.items()}
            ri_safe = jnp.where(matched, ri, 0)
            for name, c in right.columns.items():
                valid = None if c.valid is None else c.valid[ri_safe]
                out_cols[name] = Column(
                    c.data[ri_safe], c.dtype, valid, c.dictionary,
                    c.gather_stats(), owned=True,
                )
            pair = Table(
                dict(out_cols), jnp.sum(matched, dtype=jnp.int32),
                live=matched, unique_key=left.unique_key,
            )
            if residual is not None:
                # pair is a function-local transient: its freshly minted
                # right-side gathers stay owned through the masked view
                return self._masked(
                    pair, self._predicate_mask(pair, residual),
                    transient=True,
                )
            return pair
        # left join: left-aligned output, unmatched rows null on the right
        out_cols = {n: c.disowned() for n, c in left.columns.items()}
        ri_safe = jnp.where(matched, ri, 0)
        for name, c in right.columns.items():
            valid = c.valid[ri_safe] if c.valid is not None else jnp.ones(left.cap, bool)
            out_cols[name] = Column(
                c.data[ri_safe], c.dtype, valid & matched, c.dictionary,
                c.gather_stats(),
            )
        return Table(
            out_cols, left.nrows_lazy, live=left.live,
            unique_key=left.unique_key,
        )

    # -- packed-word sort-lookup join ------------------------------------
    # Exact int64 packing of the (possibly composite) join key using host-
    # known bounds (ColStats riding on columns, dictionary sizes for
    # strings): collision-free by construction, so membership needs no
    # verification and no candidate expansion. semi/anti/mark become a
    # sort + lookup regardless of right-side multiplicity; inner/left take
    # the same left-aligned augment output as the dense path when the
    # right side is known-unique on the join key from plan metadata
    # (Table.unique_key, set by group-by/distinct outputs). Zero device
    # syncs either way. The cuDF analogue is the mixed-join distinct-hash-
    # join split; this is its sort-based TPU shape.

    def _pack_key_words(self, aligned):
        """Exact int64 word per side for aligned join-key Column pairs, or
        None when bounds are unknown or exceed 62 bits (the packing itself
        is K.pack_key_words, shared with the catalog's PK verification).
        Nulls never match anyway — masked by not-null liveness — but the
        dedicated 0 slot keeps dead-row words in range."""
        bounds = []
        for ca, cb in aligned:
            if ca.dtype.is_string and cb.dtype.is_string:
                if ca.dictionary is None or cb.dictionary is None:
                    return None
                if ca.dictionary is not cb.dictionary:
                    return None  # _join_key_pair unifies; anything else bails
                bounds.append((0, max(len(ca.dictionary) - 1, 0)))
            elif ca.dtype.kind in ("int32", "int64", "date") and cb.dtype.kind in (
                "int32", "int64", "date",
            ):
                sa, sb = ca.subset_stats(), cb.subset_stats()
                if sa is None or sb is None:
                    return None
                bounds.append(
                    (min(sa.vmin, sb.vmin), max(sa.vmax, sb.vmax))
                )
            else:
                return None
        return K.pack_key_words(
            [
                [(ca.data, ca.valid) for ca, _ in aligned],
                [(cb.data, cb.valid) for _, cb in aligned],
            ],
            bounds,
        )

    def _try_packed_join(
        self, left, right, kind, aligned, right_keys, llive, rlive,
        residual, mark_name,
    ):
        if not aligned:
            return None
        if kind not in ("inner", "left", "semi", "anti", "mark"):
            return None
        if kind in ("semi", "anti", "mark", "left") and residual is not None:
            return None
        if kind in ("inner", "left"):
            # the augment output keeps one row per left row, so the right
            # side must be known-unique on the join key (plan metadata from
            # group-by/distinct); duplicated right keys are the general
            # sort join's business. Checked from metadata, never probed at
            # runtime — a wasted sort + sync on the fallback path costs
            # more than the fast path saves.
            uk = right.unique_key
            if uk is None or not uk <= _plain_col_names(right_keys, right):
                return None
        words = self._pack_key_words(aligned)
        if words is None:
            return None
        lwords, rwords = words
        lnn = K._all_valid([c.valid for c, _ in aligned], llive)
        rnn = K._all_valid([c.valid for _, c in aligned], rlive)
        found, ri = K.member_lookup(lwords, lnn, rwords, rnn)
        return self._augment_join_output(
            left, right, kind, found, ri, llive, residual, mark_name
        )

    # -- distributed fact-fact hash join ---------------------------------
    # When both join inputs are large under a mesh, neither fits the
    # dense/replicated star path; hash-partition both sides over ICI with
    # all_to_all and join each partition locally (the reference's Spark
    # shuffle join, rebuilt on XLA collectives: nds_tpu/parallel/dist.py).
    # Capacity overflows retry with doubled caps and emit a task-failure
    # event, so the harness reports CompletedWithTaskFailures; an overflow
    # that persists past the retries (single-key-scale skew a hash
    # partitioning cannot split) tiers through the PR-9 host spill pool
    # instead of falling back to the all-gathering sort join. Default
    # threshold derives PER DEVICE — see _DIST_SORT_MIN_ROWS_PER_DEV.
    _EXCHANGE_MIN_ROWS_PER_DEV = 256
    _EXCHANGE_MAX_ATTEMPTS = 5

    def _try_exchange_join(
        self, left, right, kind, left_keys, right_keys,
        lk, lv, rk, rv, llive, rlive, residual, node_fp=None,
    ):
        mesh = getattr(self.catalog, "session", None)
        mesh = getattr(mesh, "mesh", None)
        if mesh is None or kind not in ("inner", "left"):
            return None
        if kind == "left" and residual is not None:
            # a residual LEFT needs the direct path's match-after-filter
            # recount; decline rather than re-derive it over the exchange
            return None
        if getattr(self, "_exchange_disabled", False):
            # inside a spilled-join partition loop: those partitions exist
            # because an exchange already overflowed — re-entering the
            # exchange per partition could recurse under single-key skew
            return None
        session = self.catalog.session
        n_dev = mesh.devices.size
        min_rows = self._mesh_min_rows(
            session, "engine.exchange_min_rows",
            self._EXCHANGE_MIN_ROWS_PER_DEV, n_dev,
        )
        if left.nrows < min_rows or right.nrows < min_rows:
            return None
        if left.cap % n_dev or right.cap % n_dev:
            return None
        # mesh-only cold path (see _try_dist_sort)
        # nds-lint: disable=local-import
        from ..parallel.dist import get_exchange_hash_join

        lnn = K._all_valid(lv, llive)
        rnn = K._all_valid(rv, rlive)
        lh = K.hash_columns(lk, lv)
        rh = K.hash_columns(rk, rv)

        def ship(table):
            # data buffers for every column, then ONLY the real validity
            # masks — null-free columns don't pay for an all-True mask
            # through the two all_to_all exchanges
            datas = [c.data for c in table.columns.values()]
            masks = [
                c.valid for c in table.columns.values() if c.valid is not None
            ]
            return datas, masks

        l_datas, l_masks = ship(left)
        r_datas, r_masks = ship(right)
        l_ship = l_datas + l_masks
        r_ship = r_datas + r_masks
        n_lc = len(l_ship)
        n_rc = len(r_ship)
        # per-(source, destination) bucket: each device's shard holds
        # ~nrows/n_dev rows spread over n_dev destinations, so balanced
        # sizing is 2*nrows/n_dev^2 — post-exchange each device then holds
        # ~2x its SHARD (n_dev * cap), not 2x the global table; skew is
        # covered by the overflow-retry doubling below
        cap_l = bucket_cap(max(1, (2 * left.nrows) // (n_dev * n_dev)))
        cap_r = bucket_cap(max(1, (2 * right.nrows) // (n_dev * n_dev)))
        pair_cap = bucket_cap(
            max(1, 2 * max(left.nrows, right.nrows) // n_dev)
        )
        # feedback skew seeding (analysis/feedback.py, plan_feedback=on):
        # a recorded received-row skew for THIS plan node scales the
        # balanced capacity guess up front, so a known-hot key fits on
        # attempt 1 instead of rediscovering the imbalance through the
        # overflow-retry doubling ladder below
        seed = self._feedback_skew_seed(node_fp, n_dev)
        if seed > 1:
            cap_l = bucket_cap(cap_l * seed)
            cap_r = bucket_cap(cap_r * seed)
            pair_cap = bucket_cap(pair_cap * seed)
        retries = 0
        rest = None
        used_l, used_r = cap_l, cap_r  # caps the LAST attempt shipped with
        ex_t0 = _perf()
        for _attempt in range(self._EXCHANGE_MAX_ATTEMPTS):
            fn = get_exchange_hash_join(
                mesh, len(lk), n_lc, n_rc, cap_l, cap_r, pair_cap, kind
            )
            out = fn(
                (lh, lnn, *lk, *l_ship),
                (rh, rnn, *rk, *r_ship),
            )
            ok, rest = out[0], out[1:]
            used_l, used_r = cap_l, cap_r
            overflow = int(rest[-1])
            if overflow == 0:
                break
            retries += 1
            self.on_task_failure(
                f"task retry: exchange join capacity overflow "
                f"({overflow} rows); doubling caps"
            )
            cap_l *= 2
            cap_r *= 2
            pair_cap *= 2
        else:
            # persistent overflow: the hot destination cannot fit a fixed
            # per-device capacity (a single key owning most of the rows
            # never splits under hash partitioning). Planned degradation
            # composes with scale-out: join through the host spill pool —
            # partition outputs stage host-side, only one partition pair
            # is ever live in HBM — instead of aborting the stream or
            # all-gathering through the generic sort join.
            if rest is not None:
                self._emit_exchange(
                    "join", n_dev,
                    self._exchange_bytes(n_dev, used_l, used_r,
                                         lh, lk, l_ship, rh, rk, r_ship),
                    rest[-2], retries, dur_ms=(_perf() - ex_t0) * 1000.0,
                    node_fp=node_fp,
                )
            if str(session.conf.get("engine.spill", "auto")).lower() == "off":
                return None  # out-of-core disabled: legacy sort-join fallback
            self.on_task_failure(
                "exchange join capacity overflow persists after "
                f"{retries} retries; tiering through the host spill pool"
            )
            parts = max(self._SPILL_FORCE_PARTS, n_dev)
            self._exchange_disabled = True
            try:
                return self._spilled_join(
                    left, right, kind, left_keys, right_keys, residual,
                    lk, lv, llive, rk, rv, rlive, parts,
                )
            finally:
                self._exchange_disabled = False
        self._emit_exchange(
            "join", n_dev,
            self._exchange_bytes(n_dev, used_l, used_r,
                                 lh, lk, l_ship, rh, rk, r_ship),
            rest[-2], retries, dur_ms=(_perf() - ex_t0) * 1000.0,
            node_fp=node_fp,
        )
        l_out = rest[:n_lc]
        r_out = rest[n_lc:n_lc + n_rc]
        nl = len(left.columns)
        nr = len(right.columns)
        cols = {}
        mi = nl
        for i, (name, c) in enumerate(left.columns.items()):
            valid = None
            if c.valid is not None:
                valid = l_out[mi] & ok
                mi += 1
            cols[name] = Column(
                l_out[i], c.dtype, valid, c.dictionary, c.gather_stats(),
                owned=True,
            )
        mi = nr
        for i, (name, c) in enumerate(right.columns.items()):
            valid = None
            if c.valid is not None:
                valid = r_out[mi] & ok
                mi += 1
            cols[name] = Column(
                r_out[i], c.dtype, valid, c.dictionary, c.gather_stats(),
                owned=True,
            )
        # compacting by the pair mask keeps exactly the verified pairs; the
        # gathered (shipped_valid & ok) buffers equal shipped_valid on every
        # surviving row, so per-column nullability is preserved
        pair = Table(cols, ok.shape[0])
        result = self._compact(pair, ok)
        if residual is not None:
            result = self._compact(
                result, self._predicate_mask(result, residual)
            )
        if kind == "left":
            # LEFT completion: (a) shipped-but-unmatched rows, read back
            # from the received left partition (matched is per-received-row
            # exact — every row with the same key landed on one device);
            # (b) null-keyed live rows, which never routed (live=lnn dead
            # through the exchange) and null-extend from the local shard —
            # exactly the direct path's treatment of them
            base = n_lc + n_rc
            lrecv_live = rest[base]
            lmatched = rest[base + 1]
            lrecv = rest[base + 2:base + 2 + n_lc]
            ucols = {}
            mi = nl
            for i, (name, c) in enumerate(left.columns.items()):
                valid = None
                if c.valid is not None:
                    valid = lrecv[mi]
                    mi += 1
                ucols[name] = Column(
                    lrecv[i], c.dtype, valid, c.dictionary,
                    c.gather_stats(), owned=True,
                )
            un = self._compact(
                Table(ucols, lrecv_live.shape[0]), lrecv_live & ~lmatched
            )
            result = self._concat(result, self._null_extend_right(un, right))
            if any(v is not None for v in lv):
                nk = self._compact(left, llive & ~lnn)
                result = self._concat(
                    result, self._null_extend_right(nk, right)
                )
        return result

    def _exchange_bytes(self, n_dev, cap_l, cap_r,
                        lh, lk, l_ship, rh, rk, r_ship) -> int:
        """Interconnect traffic of one exchange-join attempt: every device
        ships n_dev buckets of cap rows per shipped array (padded-capacity
        measure — what the collective actually moves, not just live rows),
        plus one byte per row of live mask."""
        per_l = 1 + sum(
            int(a.dtype.itemsize) for a in [lh, *lk, *l_ship]
        )
        per_r = 1 + sum(
            int(a.dtype.itemsize) for a in [rh, *rk, *r_ship]
        )
        return n_dev * n_dev * (per_l * cap_l + per_r * cap_r)

    def _null_extend_right(self, t: Table, right: Table) -> Table:
        """Append all-null right-side columns to a left-rows-only table
        (the LEFT-join null extension), dtype/dictionary-aligned with the
        real right columns so a later concat unifies cleanly."""
        cols = dict(t.columns)
        for name, c in right.columns.items():
            cols[name] = Column(
                jnp.zeros(t.cap, c.data.dtype), c.dtype,
                jnp.zeros(t.cap, bool), c.dictionary,
            )
        return Table(cols, t.nrows_lazy, live=t.live)

    def _apply_residual(self, ok, li, ri, left, right, residual):
        count = K.mask_count(ok)
        cap = bucket_cap(max(count, 1))
        sel = K.compact_indices(ok, cap)
        pair = self._pair_table(left, right, li[sel], ri[sel], count, None)
        pmask = self._predicate_mask(pair, residual)
        # max-scatter: sel's padding duplicates index 0 (see _join residual)
        return ok & jnp.zeros(ok.shape, bool).at[sel].max(pmask)

    def _predicate_mask(self, table: Table, predicate) -> jnp.ndarray:
        """SQL WHERE semantics: TRUE rows only (NULL/UNKNOWN filtered),
        restricted to live rows."""
        pr = self._evaluator(table).eval(predicate)
        mask = pr.data.astype(bool)
        if pr.valid is not None:
            mask = mask & pr.valid
        return mask & table.row_mask()

    def _join_key_pair(self, a: Column, b: Column):
        """Align join key dtypes (incl. cross-dictionary string unification).
        Returns ([left_cols], [right_cols]) — one column pair for most
        types; float64 keys expand to an exact (exponent, mantissa) pair
        (bitcast on s64 does not compile on this TPU toolchain, and a
        single int64 word cannot hold a float64 injectively)."""
        if a.dtype.is_string != b.dtype.is_string:
            # implicit coercion (Spark casts the string side): parse the
            # string key as the other side's type, e.g. invn_date = d_date
            # in the LF_I maintenance function
            if a.dtype.is_string:
                a = _cast_column(a, b.dtype, a.data.shape[0])
            else:
                b = _cast_column(b, a.dtype, b.data.shape[0])
        if a.dtype.is_string or b.dtype.is_string:
            ca, cb, uni = unify_dictionaries(a, b)
            return (
                [Column(ca, a.dtype, a.valid, uni)],
                [Column(cb, b.dtype, b.valid, uni)],
            )
        if a.dtype.is_decimal or b.dtype.is_decimal:
            s = max(a.dtype.scale if a.dtype.is_decimal else 0,
                    b.dtype.scale if b.dtype.is_decimal else 0)
            target = DType("decimal", 38, s)
            return (
                [_cast_column(a, target, a.data.shape[0])],
                [_cast_column(b, target, b.data.shape[0])],
            )
        if a.dtype.kind == "float64" or b.dtype.kind == "float64":
            # kernels compare keys as int64, which would truncate floats
            def as_keys(c):
                f = _cast_column(c, FLOAT64, c.data.shape[0])
                ew, mw = K.float_key_words(f.data)
                return [Column(ew, INT64, f.valid), Column(mw, INT64, f.valid)]

            return as_keys(a), as_keys(b)

        def as_i64(c):
            out = _cast_column(c, INT64, c.data.shape[0])
            if (
                out.stats is None
                and c.stats is not None
                and c.dtype.kind in ("int32", "int64", "date", "bool")
            ):
                # value-preserving widening: bounds and uniqueness survive,
                # and the packed-join path depends on them downstream
                out = _dc_replace(out, stats=c.subset_stats())
            return out

        return [as_i64(a)], [as_i64(b)]

    def _pair_table(self, left, right, li, ri, nrows, rnull, lnull=None):
        # join-output gather can repeat rows: bounds survive, uniqueness
        # dies. Every buffer below is a fresh gather output owned by this
        # table alone — marked owned so a downstream fused pipeline may
        # donate it (engine/fuse.py:_donate_slots)
        cols = {}
        for name, c in left.columns.items():
            data = c.data[li]
            valid = None if c.valid is None else c.valid[li]
            if lnull is not None:
                v = valid if valid is not None else jnp.ones(li.shape[0], bool)
                valid = v & ~lnull
            cols[name] = Column(data, c.dtype, valid, c.dictionary,
                                c.gather_stats(), owned=True)
        for name, c in right.columns.items():
            data = c.data[ri]
            valid = None if c.valid is None else c.valid[ri]
            if rnull is not None:
                v = valid if valid is not None else jnp.ones(ri.shape[0], bool)
                valid = v & ~rnull
            cols[name] = Column(data, c.dtype, valid, c.dictionary,
                                c.gather_stats(), owned=True)
        return Table(cols, nrows)

    def _cross_join(self, left, right):
        # position arithmetic below assumes packed rows
        left = left.compacted()
        right = right.compacted()
        ln, rn = left.nrows, right.nrows
        total = ln * rn
        cap = bucket_cap(max(total, 1))
        p = jnp.arange(cap)
        li = (p // max(rn, 1)).astype(jnp.int32)
        ri = (p % max(rn, 1)).astype(jnp.int32)
        li = jnp.clip(li, 0, max(left.cap - 1, 0))
        return self._pair_table(left, right, li, ri, total, None)

    # ------------------------------------------------------------------
    # ------------------------------------------------------------------
    def _exec_aggregate(self, node: P.Aggregate) -> Table:
        blocked = self._blocked_union_ctx(node) if node.blocked_union else None
        if node.grouping_sets is None:
            if blocked is not None:
                return self._finish_blocked_union(node, blocked)
            child, live, nlive = self._agg_input(node)
            return self._aggregate_once(
                node.keys, node.aggs, None, child, live, nlive
            )
        if blocked is not None:
            # ROLLUP over a union (the query5 shape): from-scratch levels
            # run windowed; cascade levels re-aggregate small group tables
            # as usual — the full union concat never materializes
            child = live = nlive = None
        else:
            child, live, nlive = self._agg_input(node)
        # ROLLUP: concat incrementally and never retain the per-set parts
        # (q67's nine sets at fact-scale group caps held several GB), then
        # pack the masked concat chain before downstream windows/sorts —
        # a hard device OOM is UNRECOVERABLE on this backend (the axon
        # terminal stays poisoned even after every buffer is freed and the
        # client is re-created), so peak memory is a correctness concern.
        #
        # Cascade: when every aggregate decomposes (sum/min/max/count) and
        # the sets chain by inclusion (ROLLUP prefixes do), each coarser
        # level re-aggregates the PREVIOUS level's output — one pass over
        # the fact-scale input instead of one per set (q67: nine 8.8M-row
        # passes became one + eight over <=2M group rows).
        base_aggs, avg_items = _rollup_base_aggs(node.aggs)
        casc_aggs = _cascade_agg_items(base_aggs) if base_aggs else None
        out = None
        prev = None
        prev_set = None
        sets = sorted(node.grouping_sets, key=len, reverse=True)
        for s in sets:
            if (
                prev is not None
                and casc_aggs is not None
                and set(s) <= set(prev_set)
            ):
                key_items2 = [
                    (E.Col(name), name) for (_, name) in node.keys
                ]
                part = self._aggregate_once(
                    key_items2, casc_aggs, s, prev, prev.row_mask(),
                    prev.nrows_known,
                )
            elif blocked is not None:
                part = self._blocked_union_once(node, blocked, s)
            else:
                part = self._aggregate_once(
                    node.keys, base_aggs or node.aggs, s, child, live, nlive
                )
            part = _derive_rollup_avgs(part, avg_items)
            prev, prev_set = part, s
            out = part if out is None else self._concat(out, part)
        if avg_items:
            out = Table(
                {
                    n: c
                    for n, c in out.columns.items()
                    if not n.startswith("__cs_") and not n.startswith("__cc_")
                },
                out.nrows_lazy,
                live=out.live,
            )
        if blocked is not None:
            self._annotate_blocked(node, blocked)
        return out.compacted()

    # -- blocked (morsel-style) union-aggregation -------------------------
    # A union_all feeding an aggregate (directly, through Project/Filter
    # wrappers, or as one relation of an inner MultiJoin) never
    # materializes the full concat: each branch is evaluated in bounded row
    # windows, every window is (joined against the other relations, then)
    # partially aggregated with the rollup cascade's decomposable-aggregate
    # machinery (sum/min/max/count, avg via hidden sum+count), and partials
    # merge incrementally — peak live HBM is O(window + group rows) instead
    # of O(total union rows). This is what breaks the SF10 single-chip
    # ceiling: query5's per-channel sales+returns union is a fact-scale
    # concat (~32M rows x ~6 columns per channel at SF10) joined to
    # date_dim/store before aggregation — it hard-OOMs (and irrecoverably
    # poisons) the device on the unblocked path (bench.py).

    def _blocked_union_ctx(self, node: P.Aggregate):
        """Prepare windowed execution of a blocked-union aggregate: execute
        + align the union branches, execute the non-union join relations
        once, and size the window. Returns a context dict, or None when the
        shape/aggregates/size rule the blocked path out (callers fall
        through to the unblocked path)."""
        shape = P.union_agg_shape(node)
        if shape is None:
            return None
        session = getattr(self.catalog, "session", None)
        if session is None:
            return None  # no budget tracking: stay on the unblocked path
        base_aggs, avg_items = _rollup_base_aggs(node.aggs)
        if base_aggs is None:
            return None  # non-decomposable aggregate (distinct, stddev...)
        casc_aggs = _cascade_agg_items(base_aggs)
        if casc_aggs is None:
            return None
        outer, join, inner, branch_plans = shape
        branches = self._execute_relations_batched(branch_plans)
        total_rows = sum(t.nrows for t in branches)
        row_bytes = max(
            sum(
                int(c.data.dtype.itemsize) + 1  # data + validity byte
                for c in branches[0].columns.values()
            ),
            1,
        )
        # the plan budgeter's statically chosen window (budget_window_rows
        # annotation) wins over the runtime derivation; explicit conf/env
        # still win over both (session.union_agg_window_rows)
        wrows = session.union_agg_window_rows(
            row_bytes,
            static_rows=getattr(node, "budget_window_rows", None),
        )
        if total_rows <= wrows:
            # single window: the unblocked path is equivalent. Cheap bail —
            # the branch tables just executed are id-cached in _cte_cache,
            # so the fall-through SetOp execution reuses them directly.
            return None
        join_ctx = None
        if join is not None:
            mj, uidx = join
            # the dimension-side relations execute ONCE and are reused by
            # every window's join
            others = self._execute_relations_batched(
                [r for i, r in enumerate(mj.relations) if i != uidx]
            )
            it = iter(others)
            tables = [
                None if i == uidx else next(it)
                for i in range(len(mj.relations))
            ]
            join_ctx = (mj.edges, uidx, tables)
        branches = [t.compacted() for t in branches]
        aligners = self._union_branch_aligners(branches)
        # mark the blocked path as ENTERED before any window executes: an
        # OOM raised mid-window must still be attributable to a blocked
        # plan (bench.py's poisoned-backend bail exempts those), so the
        # marker cannot wait for successful completion in _annotate_blocked
        self.last_blocked_union = {
            "windows": 0,
            "window_rows": wrows,
            "window_cap": bucket_cap(wrows),
            "total_rows": total_rows,
            "max_table_cap": 0,
        }
        session.last_blocked_union = self.last_blocked_union
        return {
            "outer_wrappers": outer,
            "join": join_ctx,
            "join_trace": {},  # first window records the order, rest replay
            "inner_wrappers": inner,
            "branches": branches,
            "aligners": aligners,
            "base_aggs": base_aggs,
            "avg_items": avg_items,
            "casc_aggs": casc_aggs,
            "window_rows": wrows,
            "window_cap": bucket_cap(wrows),
            "total_rows": total_rows,
            "windows": 0,  # accumulated across aggregation levels
            "max_table_cap": 0,
        }

    def _apply_wrappers(self, t: Table, wrappers) -> Table:
        for w in reversed(wrappers):  # innermost wrapper first
            if isinstance(w, P.Filter):
                t = self._masked(t, self._predicate_mask(t, w.predicate))
            else:
                t = self._project_table(t, w.items)
        return t

    def _apply_wrappers_fused(self, t: Table, wrappers, memo) -> Table:
        """Apply a top-down wrapper list through ONE fused executable when
        the chain traces — the blocked union-aggregation per-window path:
        every window of a branch shares the same shape bucket and input
        signature, so the first window builds the executable and the other
        N-1 windows ride the exec cache instead of paying an eager dispatch
        per wrapper per window. `memo` (per-blocked-context dict) caches
        the detached stage list + fingerprint per wrapper chain. Falls back
        to the exact eager per-wrapper path whenever fusion is off, the
        chain has an unfusible stage, or the build failed."""
        if not wrappers:
            return t
        session = getattr(self.catalog, "session", None)
        if (
            session is None
            or session.conf.get("engine.fuse", "on") == "off"
            or not t.columns
            or t.cap == 0
        ):
            return self._apply_wrappers(t, wrappers)
        key = tuple(id(w) for w in wrappers)
        info = memo.get(key)
        if info is None:
            stages = []
            for w in reversed(wrappers):  # execution order
                if not fuse._stage_fusible(w):
                    stages = None
                    break
                if isinstance(w, P.Filter):
                    stages.append(P.Filter(predicate=w.predicate, child=None))
                else:
                    stages.append(P.Project(items=list(w.items), child=None))
            if stages and not fuse._chain_worth_fusing(stages):
                # pure rename/subset wrappers: the eager path reuses the
                # window's column objects outright — a compiled dispatch
                # per window would only add copies (same gate as
                # mark_pipelines)
                stages = None
            fp = (
                P.fingerprint(P.Pipeline(stages=stages, child=None))
                if stages
                else None
            )
            info = memo[key] = (fp, stages)
        fp, stages = info
        if fp is None:
            return self._apply_wrappers(t, wrappers)
        sig = fuse.input_signature(t)
        aot, conf_sig = self._aot_build_args(session)
        with session.cache_lock:
            entry, hit = session.exec_cache.lookup(
                fp, sig, t.cap,
                lambda: fuse.FusedPipeline(
                    stages, t, aot=aot, fp=fp, conf_sig=conf_sig
                ),
            )
        if self.tracer is not None:
            self.tracer.emit(
                "exec_cache", pipeline=fp[:12], bucket=t.cap, hit=hit,
                fused=entry is not None,
            )
        if entry is None:
            return self._apply_wrappers(t, wrappers)
        try:
            return entry.call(t, False)  # windows alias branch buffers
        except Exception as exc:
            with session.cache_lock:
                session.exec_cache.map[(fp, sig)] = None
            self.on_task_failure(
                f"window fuse fallback: {str(exc)[:120]}"
            )
            return self._apply_wrappers(t, wrappers)

    def _blocked_union_once(self, node: P.Aggregate, ctx, subset):
        """One aggregation level (grouping-set `subset`, or None for the
        plain shape) over the union input, evaluated window by window with
        incremental partial merging. Returns the same table an unblocked
        _aggregate_once would (hidden avg sum/count columns included)."""
        key_merge = [(E.Col(name), name) for _, name in node.keys]
        merged = None
        empty_partial = None
        session = getattr(self.catalog, "session", None)
        for b, aligner in zip(ctx["branches"], ctx["aligners"]):
            start = 0
            while start < b.nrows:
                wcap = ctx["window_cap"]
                if (
                    session is not None
                    and getattr(session, "_mem_pressure", False)
                    and wcap > 4096
                ):
                    # host-RSS watermark pre-emption (report.py via
                    # obs.memwatch): shrink the REMAINING windows before
                    # the allocator fails. Halving a power-of-two cap
                    # keeps `start` aligned (start is a multiple of every
                    # previous cap, all powers of two >= the new one).
                    session._mem_pressure = False
                    wcap = ctx["window_cap"] = max(wcap // 2, 4096)
                    self.on_task_failure(
                        f"host memory watermark: blocked-union window "
                        f"shrunk to {wcap} rows mid-query"
                    )
                w = window_slice(b, start, wcap)
                start += wcap
                ctx["windows"] += 1
                ctx["max_table_cap"] = max(ctx["max_table_cap"], w.cap)
                # branch-to-union alignment (rename/cast/dictionary remap)
                # applies per window: only O(window) aligned copies live
                wcols = list(w.columns.values())
                t = Table(
                    {
                        name: fn(wcols[ci])
                        for ci, (name, fn) in enumerate(aligner)
                    },
                    w.nrows_lazy,
                    live=w.live,
                )
                t = self._apply_wrappers_fused(
                    t, ctx["inner_wrappers"],
                    ctx.setdefault("wrapper_memo", {}),
                )
                if ctx["join"] is not None:
                    edges, uidx, others = ctx["join"]
                    t = self._multijoin_over_tables(
                        [t if i == uidx else o for i, o in enumerate(others)],
                        edges,
                        trace=ctx["join_trace"],
                    )
                    ctx["max_table_cap"] = max(ctx["max_table_cap"], t.cap)
                t = self._apply_wrappers_fused(
                    t, ctx["outer_wrappers"],
                    ctx.setdefault("wrapper_memo", {}),
                )
                part = self._aggregate_once(
                    node.keys, ctx["base_aggs"], subset, t, t.row_mask(),
                    t.nrows_known,
                )
                if part.nrows_known == 0:
                    # keep one empty partial: its columns carry the same
                    # stub dtypes the unblocked empty-aggregate output uses
                    empty_partial = part
                    continue
                if merged is None:
                    merged = part
                else:
                    cat = self._concat(merged, part)
                    ctx["max_table_cap"] = max(
                        ctx["max_table_cap"], cat.cap
                    )
                    merged = self._aggregate_once(
                        key_merge, ctx["casc_aggs"], None, cat,
                        cat.row_mask(), cat.nrows_known,
                    )
        if merged is None:
            merged = empty_partial  # every window filtered to nothing
        return merged

    def _finish_blocked_union(self, node: P.Aggregate, ctx) -> Table:
        """The plain (non-grouping-sets) blocked aggregate: one windowed
        level, visible avgs derived, declared column order restored."""
        merged = self._blocked_union_once(node, ctx, None)
        out = _derive_rollup_avgs(merged, ctx["avg_items"])
        # restore the declared output column order (and drop the hidden
        # __cs_/__cc_ avg-decomposition columns)
        out = out.select(
            [n for _, n in node.keys]
            + [n for _, n in node.aggs if n in out.columns]
        )
        self._annotate_blocked(node, ctx)
        return out

    def _annotate_blocked(self, node: P.Aggregate, ctx):
        # plan-introspection aids (tests/tools): window count and the peak
        # per-window table capacity actually touched, which must stay
        # bounded by the window bucket — never by the total union rows
        node.blocked_windows = ctx["windows"]
        node.blocked_stats = self.last_blocked_union = {
            "windows": ctx["windows"],
            "window_rows": ctx["window_rows"],
            "window_cap": ctx["window_cap"],
            "total_rows": ctx["total_rows"],
            "max_table_cap": ctx["max_table_cap"],
        }
        # session-level marker: harness loops (bench.py) read this to tell
        # whether the statement they just ran routed through the blocked
        # path (they reset it before each statement)
        session = getattr(self.catalog, "session", None)
        if session is not None:
            session.last_blocked_union = self.last_blocked_union
        if self.tracer is not None:
            self.tracer.emit("blocked_union", **self.last_blocked_union)

    def _union_branch_aligners(self, tables):
        """Per-branch WINDOW aligners: unify column names (leftmost branch
        wins, as in SetOp output), dtypes (common promotion) and string
        dictionaries across union branches, mirroring _concat's per-pair
        unification so windowed evaluation sees the same values the
        unblocked concat chain would. The cast/remap itself is deferred to
        each window slice — aligning the full branches up front would
        allocate branch-scale copies and reintroduce exactly the peak-HBM
        spike the blocked path exists to avoid; only dictionary-sized remap
        tables are built here. Returns one [(out_name, fn(Column)->Column)]
        list per branch, positionally aligned with the branch's columns."""
        names = list(tables[0].columns)
        per_table = [list(t.columns.values()) for t in tables]
        aligners = [[] for _ in tables]
        for ci, name in enumerate(names):
            cols = [cols_t[ci] for cols_t in per_table]
            if any(c.dtype.is_string for c in cols):
                dicts = [
                    (
                        c.dictionary
                        if c.dictionary is not None
                        else pa.array([], pa.string())
                    ).cast(pa.string())
                    for c in cols
                ]
                unified = pc.unique(pa.concat_arrays(dicts))
                for bi, d in enumerate(dicts):
                    if len(d) == 0:

                        def fn(col, _u=unified):
                            return Column(col.data, col.dtype, col.valid, _u)

                    else:
                        remap = jnp.asarray(
                            pc.index_in(d, unified)
                            .to_numpy(zero_copy_only=False)
                            .astype(np.int32)
                        )

                        def fn(col, _r=remap, _u=unified, _n=len(d)):
                            return Column(
                                _r[jnp.clip(col.data, 0, _n - 1)],
                                col.dtype,
                                col.valid,
                                _u,
                            )

                    aligners[bi].append((name, fn))
            else:
                dt = _common_dtype([c.dtype for c in cols])

                def fn(col, _dt=dt):
                    return _cast_column(col, _dt, col.data.shape[0])

                for bi in range(len(tables)):
                    aligners[bi].append((name, fn))
        return aligners

    def _agg_input(self, node: P.Aggregate):
        """Aggregation input as (table, live mask, known row count|None).
        Filters/dense joins produce deferred-compaction tables, so e.g.
        the q9 shape (15 scalar subqueries, each a global aggregate over a
        filtered fact scan) runs entirely async on device."""
        t = self.execute(node.child)
        return t, t.row_mask(), t.nrows_known

    def _aggregate_once(self, key_items, agg_items, subset, child, live,
                        nlive):
        # stash grouping state for grouping()/distinct-agg helpers, saving
        # the previous values: a scalar subquery inside an aggregate
        # argument re-enters _aggregate_once and must not clobber the
        # outer aggregation's state
        prev = (
            getattr(self, "_current_agg_keys", None),
            getattr(self, "_current_agg_live", None),
            getattr(self, "_current_agg_nlive", None),
        )
        self._current_agg_keys = key_items
        self._current_agg_live = live
        self._current_agg_nlive = nlive
        try:
            return self._aggregate_once_inner(
                key_items, agg_items, subset, child, live, nlive
            )
        finally:
            (
                self._current_agg_keys,
                self._current_agg_live,
                self._current_agg_nlive,
            ) = prev

    def _aggregate_once_inner(self, key_items, agg_items, subset, child,
                              live, nlive):
        ev = self._evaluator(child)
        key_cols = []
        for i, (e, name) in enumerate(key_items):
            if subset is not None and i not in subset:
                key_cols.append(None)
            else:
                key_cols.append(ev.eval(e))
        active = [c for c in key_cols if c is not None]

        if active and (nlive is None or nlive > 0):
            direct = self._try_direct_agg(
                child, key_items, key_cols, agg_items, subset, ev, live
            )
            if direct is not None:
                return direct

        words = None
        if active:
            words = self._group_words(active, live)
            # nlive None (fused filter mask): group_by_words syncs the count
            order, gid, ngroups = K.group_by_words(words, live, nlive)
        else:
            # single global group: segment reductions are order-independent,
            # so no sort at all — identity order, weight = live mask. SQL
            # yields exactly one row even over empty input (weights produce
            # the NULL/0 aggregate values).
            order = None
            gid = jnp.zeros(child.cap, jnp.int32)
            ngroups = 1
        if ngroups == 0:
            if active:
                # empty input, grouped agg -> empty result
                return self._agg_output(
                    child, key_items, key_cols, agg_items, subset,
                    None, None, 0, ev,
                )
            ngroups = 1  # global agg over empty input yields one row
        gcap = bucket_cap(ngroups)
        live_sorted = live if order is None else live[order]
        return self._agg_output(
            child, key_items, key_cols, agg_items, subset,
            order, gid, ngroups, ev, gcap, live_sorted, words,
        )

    # -- direct (sort-free) aggregation ----------------------------------
    # When the combined group-key domain is small (the TPC-DS norm), group
    # ids are computed elementwise as mixed-radix codes and every aggregate
    # is one scatter-add — no sort of the fact table. Under a mesh the
    # scatter-add over row-sharded input lowers to per-chip partial
    # aggregation + a cross-chip reduction of the small group table.
    _DIRECT_AGG_MAX_DOMAIN = 1 << 22

    def _try_direct_agg(
        self, child, key_items, key_cols, agg_items, subset, ev, live
    ):
        if any(agg.distinct for agg, _ in agg_items):
            return None
        active = [(i, c) for i, c in enumerate(key_cols) if c is not None]
        datas, valids, mins, ranges = [], [], [], []
        domain = 1
        for _, c in active:
            # key bounds come from catalog ColStats (or are statically known
            # for dictionary codes / bools) — never from a device round-trip;
            # keys without bounds fall back to the sort-based aggregation
            if c.dtype.is_string:
                if c.dictionary is None or len(c.dictionary) == 0:
                    return None
                kmin, kmax = 0, len(c.dictionary) - 1
            elif c.dtype.kind == "bool":
                kmin, kmax = 0, 1
            elif c.dtype.kind in ("int32", "int64", "date"):
                if c.stats is None:
                    return None
                kmin, kmax = c.stats.vmin, c.stats.vmax
            else:
                return None
            data = c.data
            if data.dtype == jnp.bool_:
                data = data.astype(jnp.int32)
            krange = kmax - kmin + 1 + (1 if c.valid is not None else 0)
            domain *= krange
            if domain > self._DIRECT_AGG_MAX_DOMAIN:
                return None
            datas.append(data)
            valids.append(c.valid)
            mins.append(kmin)
            ranges.append(krange)
        domain_cap = bucket_cap(domain)
        gid = K.direct_gid(datas, valids, mins, ranges, live)
        occ, dense = K.occupancy_map(gid, live, domain_cap)
        ngroups = K.mask_count(occ)
        if ngroups == 0:
            return None
        gcap = bucket_cap(ngroups)
        gid_dense = jnp.clip(dense[gid], 0)
        occ_cells = K.compact_indices(occ, gcap).astype(jnp.int64)

        # reconstruct key columns from the occupied cell codes (reverse
        # mixed-radix decomposition; last key is least significant)
        codes = []
        rem = occ_cells
        for krange in reversed(ranges):
            codes.append(rem % krange)
            rem = rem // krange
        codes.reverse()
        cols = {}
        ai = 0
        for i, ((e, name), c) in enumerate(zip(key_items, key_cols)):
            if c is None:
                base = ev.eval(key_items[i][0])
                cols[name] = Column(
                    jnp.zeros(gcap, base.dtype.device_np_dtype()),
                    base.dtype,
                    jnp.zeros(gcap, bool),
                    base.dictionary,
                )
                continue
            code = codes[ai]
            kmin = mins[ai]
            ai += 1
            if c.valid is not None:
                valid = code != 0
                value = jnp.where(valid, kmin + code - 1, 0)
            else:
                valid = None
                value = kmin + code
            out_dtype = c.dtype.device_np_dtype()
            data = value.astype(out_dtype)
            cols[name] = Column(
                data, c.dtype, valid, c.dictionary,
                _group_key_stats(c, len(active)),
            )
        for agg, name in agg_items:
            cols[name] = self._eval_agg(
                agg, ev, None, gid_dense, gcap, live, ngroups, child, subset,
                key_cols,
            )
        return Table(cols, ngroups, unique_key=_active_key_names(key_items, key_cols))

    def _agg_output(
        self, child, key_items, key_cols, agg_items, subset,
        order, gid, ngroups, ev, gcap=None, live_sorted=None,
        key_words=None,
    ):
        if ngroups == 0:
            cols = {}
            for (e, name), c in zip(key_items, key_cols):
                dtype = c.dtype if c is not None else INT64
                cols[name] = Column(
                    jnp.zeros(1, dtype.device_np_dtype()), dtype,
                    jnp.zeros(1, bool),
                    c.dictionary if c is not None else None,
                )
            for agg, name in agg_items:
                cols[name] = Column(jnp.zeros(1, jnp.int64), INT64, jnp.zeros(1, bool))
            return Table(cols, 0)
        first_rows = None
        if order is not None:
            first_idx = K.segment_starts(gid, gcap)
            first_rows = order[jnp.clip(first_idx, 0, child.cap - 1)]
        cols = {}
        for i, ((e, name), c) in enumerate(zip(key_items, key_cols)):
            if c is None:
                # rolled-up key: all null
                base = ev.eval(key_items[i][0])
                cols[name] = Column(
                    jnp.zeros(gcap, base.dtype.device_np_dtype()),
                    base.dtype,
                    jnp.zeros(gcap, bool),
                    base.dictionary,
                )
            else:
                data = c.data[first_rows]
                valid = None if c.valid is None else c.valid[first_rows]
                cols[name] = Column(
                    data, c.dtype, valid, c.dictionary,
                    _group_key_stats(
                        c, sum(1 for kc in key_cols if kc is not None)
                    ),
                )
        for agg, name in agg_items:
            cols[name] = self._eval_agg(
                agg, ev, order, gid, gcap, live_sorted, ngroups, child, subset,
                key_cols, key_words,
            )
        return Table(cols, ngroups, unique_key=_active_key_names(key_items, key_cols))

    def _eval_agg(
        self, agg: E.Agg, ev, order, gid, gcap, live_sorted, ngroups, child,
        subset, key_cols, key_words=None,
    ) -> Column:
        fn = agg.fn
        if fn == "grouping":
            # grouping(key) = 1 when the key is rolled away in this set.
            # The binder left grouping()'s arg as the raw key expr; the arg
            # was rewritten to the key's output Col by the post-agg rewrite,
            # so match either form against the Aggregate node's key items.
            idx = None
            for i, (ke, kn) in enumerate(self._current_agg_keys):
                if agg.arg == ke or agg.arg == E.Col(kn):
                    idx = i
                    break
            rolled = subset is not None and idx is not None and idx not in subset
            v = jnp.full(gcap, 1 if rolled else 0, jnp.int32)
            return Column(v, DType("int32"))
        if agg.distinct:
            return self._eval_distinct_agg(
                agg, ev, child, subset, key_cols, gcap, ngroups, key_words
            )
        if fn == "count" and agg.arg is None:
            counts = K.segment_reduce(
                live_sorted.astype(jnp.int64), gid, live_sorted, gcap, "count"
            )
            return Column(counts.astype(jnp.int64), INT64)
        c = ev.eval(agg.arg)
        weight = live_sorted
        # order=None: direct (unsorted) aggregation — gid/live are row-aligned
        sdata = c.data if order is None else c.data[order]
        if c.valid is not None:
            weight = weight & (c.valid if order is None else c.valid[order])
        if c.dtype.is_string:
            rank, sorted_dict = sort_dictionary(c)
            sdata = rank if order is None else rank[order]
            if fn in ("min", "max"):
                red, counts = K.segment_reduce_with_count(
                    sdata, gid, weight, gcap, fn
                )
                return Column(
                    red.astype(jnp.int32), c.dtype, counts > 0, sorted_dict
                )
            raise ExecError(f"agg {fn} on string column")
        if fn == "count":
            counts = K.segment_reduce(sdata, gid, weight, gcap, "count")
            return Column(counts.astype(jnp.int64), INT64)
        if fn in ("sum", "min", "max"):
            pall = self._pallas_segment_route(fn, c, sdata, gid, weight, gcap)
            if pall is not None:
                return pall
            red, counts = K.segment_reduce_with_count(
                sdata, gid, weight, gcap, fn
            )
            dtype = c.dtype
            if fn == "sum" and dtype.kind == "int32":
                dtype = INT64
                red = red.astype(jnp.int64)
            return Column(red, dtype, counts > 0)
        if fn == "avg":
            s, n = K.segment_reduce_with_count(
                sdata, gid, weight, gcap, "sum"
            )
            nz = jnp.maximum(n, 1)
            if c.dtype.is_decimal:
                val = s.astype(jnp.float64) / (10**c.dtype.scale) / nz
            else:
                val = s.astype(jnp.float64) / nz
            return Column(val, FLOAT64, n > 0)
        if fn in ("stddev_samp", "var_samp"):
            x = sdata.astype(jnp.float64)
            if c.dtype.is_decimal:
                x = x / 10**c.dtype.scale
            s = K.segment_reduce(x, gid, weight, gcap, "sum")
            sq = K.segment_reduce(x, gid, weight, gcap, "sumsq")
            n = K.segment_reduce(x, gid, weight, gcap, "count").astype(jnp.float64)
            nz = jnp.maximum(n, 2)
            var = (sq - s * s / jnp.maximum(n, 1)) / (nz - 1)
            var = jnp.maximum(var, 0.0)
            out = jnp.sqrt(var) if fn == "stddev_samp" else var
            return Column(out, FLOAT64, n > 1)
        raise ExecError(f"aggregate {fn}")

    def _pallas_segment_route(self, fn, c, sdata, gid, weight, gcap):
        """Opt-in Pallas segment-reduce promotion for float64 measures.

        `engine.pallas_agg`: `off` (default) — the jnp/XLA scatter path;
        `on` — always route sum/min/max through the Pallas tile kernels
        (ops/pallas_kernels.py: one-hot MXU matmul for sum, VPU tile
        min/max); `auto` — MEASURED promotion: the first call at each
        (fn, input cap, group cap) shape times both paths (post-warmup, so
        compile cost is excluded) and promotes only when Pallas actually
        wins on this backend, recording both measurements as `kernel_span`
        events — promotion on data, not faith. All modes are float32
        accumulation (the reference's --floats tolerance), so float64
        measures only; exact int64/decimal reductions never route here."""
        mode = self._pallas_mode()
        if mode not in ("on", "auto") or c.dtype.kind != "float64":
            return None
        # opt-in backend: the Pallas import compiles Mosaic machinery the
        # default path never needs
        # nds-lint: disable=local-import
        from ..ops import pallas_kernels as PK

        interpret = jax.devices()[0].platform != "tpu"
        pgid = jnp.where(weight, gid, -1).astype(jnp.int32)
        # mask dead/null lanes: a zero one-hot entry does not neutralize
        # NaN garbage (0*NaN=NaN would poison the whole group tile)
        pvals = jnp.where(weight, sdata, 0).astype(jnp.float32)
        if mode == "auto" and not self._pallas_promoted(
            fn, sdata, gid, weight, gcap, pvals, pgid, interpret
        ):
            return None
        if fn == "sum":
            s, n = PK.segment_sums_pallas(
                pvals, pgid, gcap, interpret=interpret
            )
        else:
            s, n = PK.segment_extreme_pallas(
                pvals, pgid, gcap, fn == "max", interpret=interpret
            )
        return Column(s.astype(jnp.float64), c.dtype, n > 0)

    def _pallas_mode(self) -> str:
        session = getattr(self.catalog, "session", None)
        if session is None:
            return "off"
        return str(session.conf.get("engine.pallas_agg", "off")).lower()

    def _sort_perm_route(self, words):
        """ORDER BY permutation with optional Pallas counting-sort
        promotion (`engine.pallas_sort`): `off` (default) — the canonical
        kv-sort kernel; `on` — route eligible words through the Pallas
        counting sort (ops/pallas_kernels.sort_perm_pallas, identical
        stable ascending permutation by construction); `auto` — the same
        measured per-shape A/B as the aggregate/join routes, memoized on
        `Session.pallas_promotions` AND the persistent promotion store
        under key ("sort_perm", rows, domain). Eligible: exactly one sort
        word whose value span fits the counting domain (the span probe is
        one fused dispatch + one host sync, paid only in on/auto modes) —
        everything else stays on the canonical kernel unconditionally."""
        session = getattr(self.catalog, "session", None)
        mode = (
            str(session.conf.get("engine.pallas_sort", "off")).lower()
            if session is not None
            else "off"
        )
        if mode not in ("on", "auto") or len(words) != 1:
            return K.sort_by_words(words)
        # opt-in backend: the Pallas import compiles Mosaic machinery the
        # default path never needs — it must stay BEHIND the mode gate
        # nds-lint: disable=local-import
        from ..ops import pallas_kernels as PK

        if int(words[0].shape[0]) > PK.SORT_MAX_ROWS:
            return K.sort_by_words(words)
        w = words[0]
        lo, hi = (int(x) for x in jax.device_get(K.word_span(w)))
        if lo < 0 or hi >= PK.SORT_MAX_DOMAIN:
            return K.sort_by_words(words)
        # 128-aligned domain so near-identical spans share one compiled
        # kernel (and one promotion verdict)
        domain = -(-(hi + 1) // 128) * 128
        interpret = jax.devices()[0].platform != "tpu"
        if mode == "auto":
            key = ("sort_perm", int(w.shape[0]), int(domain))
            rec = self._promotion_rec(key)
            if rec is None:
                rec = self._measure_promotion(
                    key,
                    lambda: K.sort_by_words(words),
                    lambda: PK.sort_perm_pallas(
                        w, domain, interpret=interpret
                    ),
                    "sort_perm",
                )
            if not rec["use"]:
                return K.sort_by_words(words)
        return PK.sort_perm_pallas(w, domain, interpret=interpret)

    def _dense_build_route(self, rkey, rnn, rmin, table_cap):
        """Join-candidate build-table promotion (`engine.pallas_join`):
        `off` — the jnp scatter-max pair; `on` — the Pallas one-hot tile
        kernel (exact integer maxima, no numeric caveat); `auto` — the
        same measured per-shape A/B as the aggregate route, recorded as
        `kernel_span` evidence and memoized on `Session.pallas_promotions`
        under key ("dense_build", rows, table_cap)."""
        session = getattr(self.catalog, "session", None)
        mode = (
            str(session.conf.get("engine.pallas_join", "off")).lower()
            if session is not None
            else "off"
        )
        if mode not in ("on", "auto"):
            return K.dense_build(rkey, rnn, rmin, table_cap)
        # opt-in backend: the Pallas import compiles Mosaic machinery the
        # default path never needs
        # nds-lint: disable=local-import
        from ..ops import pallas_kernels as PK

        interpret = jax.devices()[0].platform != "tpu"
        if mode == "auto":
            key = ("dense_build", int(rkey.shape[0]), int(table_cap))
            rec = self._promotion_rec(key)
            if rec is None:
                rec = self._measure_promotion(
                    key,
                    lambda: K.dense_build(rkey, rnn, rmin, table_cap),
                    lambda: PK.dense_build_pallas(
                        rkey, rnn, rmin, table_cap, interpret=interpret
                    ),
                    "dense_build",
                )
            if not rec["use"]:
                return K.dense_build(rkey, rnn, rmin, table_cap)
        return PK.dense_build_pallas(
            rkey, rnn, rmin, table_cap, interpret=interpret
        )

    def _promotion_rec(self, key):
        """The memoized promotion verdict for `key`: the session memo
        first, then the PERSISTENT store (engine/aotcache.py
        PromotionStore — verdicts measured by any previous process on
        this backend environment), loaded into the memo on hit so a fleet
        measures each (kernel, shape) once, ever. None = unmeasured."""
        session = self.catalog.session
        rec = session.pallas_promotions.get(key)
        if rec is not None:
            return rec
        store = getattr(session, "promotion_store", None)
        if store is None:
            return None
        rec = store.get(AOTC.promotion_key_str(key))
        if rec is not None and "use" in rec:
            with session.cache_lock:
                session.pallas_promotions[key] = rec
            return rec
        return None

    def _measure_promotion(self, key, run_jnp, run_pallas, kname):
        """One-time measured A/B for a (kernel, shape) promotion slot:
        warm both paths (compiles land in the jit caches either way), time
        one synchronized call each, memoize the winner on the session
        (and in the persistent promotion store when one is configured)
        and emit both measurements as `kernel_span` events."""
        session = self.catalog.session

        def timed(run):
            jax.block_until_ready(run())  # warmup: exclude compile
            t0 = _perf()
            jax.block_until_ready(run())
            return (_perf() - t0) * 1000.0

        jnp_ms = timed(run_jnp)
        try:
            pallas_ms = timed(run_pallas)
        except Exception:
            pallas_ms = float("inf")  # no Pallas lowering: never promote
        with session.cache_lock:
            rec = session.pallas_promotions[key] = {
                "jnp_ms": round(jnp_ms, 3),
                "pallas_ms": (
                    round(pallas_ms, 3) if pallas_ms != float("inf") else None
                ),
                "use": pallas_ms < jnp_ms,
            }
        store = getattr(session, "promotion_store", None)
        if store is not None:
            # measure once, reuse forever: the verdict (keyed with the
            # backend environment) outlives this process. The store is
            # internally locked, but the mutation holds the session lock
            # anyway — the cache-lock-discipline contract all session
            # caches share
            with session.cache_lock:
                store.record(AOTC.promotion_key_str(key), rec)
        if self.tracer is not None:
            self.tracer.emit(
                "kernel_span", kernel=f"{kname}:jnp",
                dur_ms=rec["jnp_ms"], n=key[1],
            )
            if rec["pallas_ms"] is not None:
                self.tracer.emit(
                    "kernel_span", kernel=f"{kname}:pallas",
                    dur_ms=rec["pallas_ms"], n=key[1],
                )
        return rec

    def _pallas_promoted(
        self, fn, sdata, gid, weight, gcap, pvals, pgid, interpret
    ) -> bool:
        """One-time measured A/B per (fn, rows-bucket, group-bucket) shape,
        memoized on the session (`Session.pallas_promotions`): warm both
        paths (executables land in the jit caches either way), then time
        one synchronized call each; the Pallas route is used only where it
        measured faster. Both measurements emit `kernel_span` events so
        `profile` can show the promotion evidence per shape."""
        key = (fn, int(sdata.shape[0]), int(gcap))
        rec = self._promotion_rec(key)
        if rec is None:
            # nds-lint: disable=local-import
            from ..ops import pallas_kernels as PK

            def run_jnp():
                return K.segment_reduce_with_count(
                    sdata, gid, weight, gcap, fn
                )

            if fn == "sum":
                def run_pallas():
                    return PK.segment_sums_pallas(
                        pvals, pgid, gcap, interpret=interpret
                    )
            else:
                def run_pallas():
                    return PK.segment_extreme_pallas(
                        pvals, pgid, gcap, fn == "max", interpret=interpret
                    )

            rec = self._measure_promotion(
                key, run_jnp, run_pallas, f"segment_{fn}"
            )
        return rec["use"]

    def _eval_distinct_agg(self, agg, ev, child, subset, key_cols, gcap,
                           ngroups, key_words=None):
        """count(distinct x) / sum(distinct x): two-level grouping.

        Null values of x stay live through both passes (so every outer group
        survives and positions align with the main aggregation pass, which
        enumerates groups in the same sorted-key order) but carry zero weight
        in the final reduction (distinct aggs ignore nulls)."""
        c = ev.eval(agg.arg)
        live = self._current_agg_live
        d = c.data
        if d.dtype == jnp.bool_:
            d = d.astype(jnp.int32)
        # the main pass's outer-key words: monotone codes keep group
        # enumeration order identical across passes, so positions align
        gwords = list(key_words) if key_words else []
        if gwords:
            vwords = self._sort_words(
                [(d, c.valid, True, True)], [c], live, include_live=False
            )
            words2 = gwords + vwords
        else:
            words2 = self._sort_words([(d, c.valid, True, True)], [c], live)
        order2, gid2, ng2 = K.group_by_words(
            words2, live, self._current_agg_nlive
        )
        g2cap = bucket_cap(max(ng2, 1))
        first2 = K.segment_starts(gid2, g2cap)
        rows2 = order2[jnp.clip(first2, 0, child.cap - 1)]
        live2 = jnp.arange(g2cap) < ng2
        cvalid2 = None if c.valid is None else c.valid[rows2]
        # re-group the distinct rows by the outer keys only. A fresh live2
        # word leads: the gathered words' embedded live bit reflects the
        # ORIGINAL rows' liveness, not the distinct slots' (dead slots gather
        # an arbitrary live row when the table has no dead tail).
        if gwords:
            okeys = [jnp.where(live2, jnp.int64(0), jnp.int64(1))]
            okeys += [w[rows2] for w in gwords]
            order3, gid3, ng3 = K.group_by_words(okeys, live2)
        else:
            # global distinct: reductions are order-independent
            order3 = jnp.arange(g2cap, dtype=jnp.int32)
            gid3 = jnp.zeros(g2cap, jnp.int32)
            ng3 = 1 if ng2 > 0 else 0
        if ng3 == 0:
            ng3 = 1
        g3cap = bucket_cap(ng3)
        w3 = live2[order3]
        if cvalid2 is not None:
            w3 = w3 & cvalid2[order3]
        vals = c.data[rows2][order3]
        if agg.fn == "count":
            out = K.segment_reduce(vals, gid3, w3, g3cap, "count")
            col = Column(out.astype(jnp.int64), INT64)
        elif agg.fn == "sum":
            out, n = K.segment_reduce_with_count(vals, gid3, w3, g3cap, "sum")
            col = Column(out, c.dtype if c.dtype.kind != "int32" else INT64, n > 0)
        elif agg.fn == "avg":
            s, n = K.segment_reduce_with_count(vals, gid3, w3, g3cap, "sum")
            v = s.astype(jnp.float64) / jnp.maximum(n, 1)
            if c.dtype.is_decimal:
                v = v / 10**c.dtype.scale
            col = Column(v, FLOAT64, n > 0)
        else:
            raise ExecError(f"distinct agg {agg.fn}")
        return col

    # ------------------------------------------------------------------
    def _exec_window(self, node: P.Window) -> Table:
        # windows sort and scan several word/rank arrays at the input cap:
        # always pack masked inputs first (memory AND time win)
        child = self.execute(node.child).compacted()
        out_cols = {n: c.disowned() for n, c in child.columns.items()}
        for wf, name in node.fns:
            out_cols[name] = self._eval_window(child, wf)
        return Table(out_cols, child.nrows_lazy, live=child.live)

    def _eval_window(self, child: Table, wf: E.WindowFn) -> Column:
        ev = self._evaluator(child)
        live = child.row_mask()
        pkeys = []
        pcols = []
        for e in wf.partition_by:
            c = ev.eval(e)
            d = c.data.astype(jnp.int32) if c.data.dtype == jnp.bool_ else c.data
            pkeys.append((d, c.valid, True, True))
            pcols.append(c)
        okeys = []
        ocols = []
        for e, asc in wf.order_by:
            c = ev.eval(e)
            d = c.data
            if c.dtype.is_string:
                d, _ = sort_dictionary(c)
            if d.dtype == jnp.bool_:
                d = d.astype(jnp.int32)
            okeys.append((d, c.valid, asc, asc))
            ocols.append(c)
        # partition words carry the live bit (dead rows last); order words
        # are a separate list so partition boundaries can be read off the
        # sorted partition words alone
        pwords = self._sort_words(pkeys, pcols, live)
        owords = self._sort_words(okeys, ocols, live, include_live=False)
        order = K.sort_by_words(pwords + owords)
        sorted_ow = [w[order] for w in owords]
        # partition group ids over sorted rows
        if pkeys:
            sorted_p = [w[order] for w in pwords]
            flags = K._word_flags(sorted_p)
            gid = K.fast_cumsum(flags.astype(jnp.int32)) - 1
            nlive = child.nrows
            ng = int(gid[nlive - 1]) + 1 if nlive else 0
        else:
            gid = jnp.zeros(child.cap, jnp.int32)
            ng = 1 if child.nrows else 0
        gcap = bucket_cap(max(ng, 1))
        inv = jnp.zeros(child.cap, jnp.int32).at[order].set(
            jnp.arange(child.cap, dtype=jnp.int32)
        )

        fn = wf.fn
        if fn in ("rank", "dense_rank", "row_number"):
            pos = K.running_position(gid)
            if fn == "row_number":
                vals = pos + 1
            else:
                # order-group boundaries within partitions (ties share a rank)
                oflags = K._word_flags([gid] + sorted_ow)
                ogid = K.fast_cumsum(oflags.astype(jnp.int32)) - 1
                part_first = K.segment_starts(gid, gcap)
                if fn == "dense_rank":
                    # count of order-group starts since the partition start
                    cums = K.fast_cumsum(oflags.astype(jnp.int32))
                    base = cums[jnp.clip(part_first, 0, child.cap - 1)]
                    vals = cums - base[gid] + 1
                else:
                    # rank: 1 + rows before the first row of this order-group
                    n_og = int(ogid[child.nrows - 1]) + 1 if child.nrows else 1
                    og_first_pos = K.segment_starts(ogid, bucket_cap(max(n_og, 1)))
                    vals = og_first_pos[ogid] - part_first[gid] + 1
            out_sorted = vals.astype(jnp.int64)
            data = out_sorted[inv]
            return Column(data.astype(jnp.int64), INT64, None)

        # aggregate-over-partition functions
        if fn not in ("sum", "avg", "min", "max", "count"):
            raise ExecError(f"window fn {fn}")
        if wf.arg is None and fn == "count":
            c = None
            sdata = jnp.ones(child.cap, jnp.int64)[order]
            w = live[order]
            dtype = INT64
        else:
            c = ev.eval(wf.arg)
            if c.dtype.is_string and fn in ("min", "max"):
                # rank-transform codes so min/max compares lexicographically
                # (raw dictionary codes are in encounter order)
                ranks, sorted_dict = sort_dictionary(c)
                c = Column(ranks, c.dtype, c.valid, sorted_dict)
            sdata = c.data[order]
            w = live[order]
            if c.valid is not None:
                w = w & c.valid[order]
            dtype = c.dtype

        # Classify the frame. SQL default: whole partition without ORDER BY,
        # RANGE UNBOUNDED PRECEDING..CURRENT ROW (including peers) with it.
        frame = wf.frame
        whole = (not wf.order_by and frame is None) or frame == (
            ("unbounded", "preceding"),
            ("unbounded", "following"),
        )
        if whole:
            red_map = {"sum": "sum", "min": "min", "max": "max",
                       "count": "count", "avg": "sum"}
            red, counts = K.segment_reduce_with_count(
                sdata, gid, w, gcap, red_map[fn]
            )
            return self._window_result(
                fn, red[gid][inv], counts[gid][inv], c, dtype
            )

        if fn in ("min", "max"):
            # running min/max (q51: `rows unbounded preceding..current row`)
            # via rank-transform + native cummax (exact; see
            # K.segmented_running_extreme — a flag-carrying
            # lax.associative_scan compiled for minutes at fact shapes)
            if frame not in (
                (("unbounded", "preceding"), ("current", None)),
                None,
            ):
                raise ExecError(f"window {fn} over frame {frame}")
            sorted_vals, rank = K.value_rank(sdata)
            scanned = K.segmented_running_extreme(
                sorted_vals, rank, gid, w, fn == "max"
            )
            cnt_run = _segment_cumsum(w.astype(jnp.int64), gid)
            if frame is None:
                # RANGE default: current row's peers (equal order keys) are
                # in-frame, so read the running value at the peer-group end
                oflags = K._word_flags([gid] + sorted_ow)
                ogid = K.fast_cumsum(oflags.astype(jnp.int32)) - 1
                n_og = int(ogid[child.nrows - 1]) + 1 if child.nrows else 1
                ogcap = bucket_cap(max(n_og, 1))
                og_first = K.segment_starts(ogid, ogcap)
                og_count = K.segment_reduce(
                    jnp.ones_like(ogid, jnp.int64), ogid,
                    jnp.ones(ogid.shape, bool), ogcap, "count",
                )
                og_end = (og_first.astype(jnp.int64) + og_count - 1)[ogid]
                og_end = jnp.clip(og_end, 0, child.cap - 1).astype(jnp.int32)
                scanned = scanned[og_end]
                cnt_run = cnt_run[og_end]
            return self._window_result(
                fn, scanned[inv], cnt_run[inv], c, dtype
            )

        x = jnp.where(w, sdata, jnp.zeros((), sdata.dtype))
        if jnp.issubdtype(x.dtype, jnp.integer):
            x = x.astype(jnp.int64)
        csum = _segment_cumsum(x, gid)
        cnt = _segment_cumsum(w.astype(jnp.int64), gid)

        if frame is None or frame == (("unbounded", "preceding"), ("current", None)):
            if frame is None:
                # RANGE: current row's peers (equal order keys) are included,
                # so take the cumulative value at the END of the peer group
                oflags = K._word_flags([gid] + sorted_ow)
                ogid = K.fast_cumsum(oflags.astype(jnp.int32)) - 1
                n_og = int(ogid[child.nrows - 1]) + 1 if child.nrows else 1
                ogcap = bucket_cap(max(n_og, 1))
                og_first = K.segment_starts(ogid, ogcap)
                og_count = K.segment_reduce(
                    jnp.ones_like(ogid, jnp.int64), ogid,
                    jnp.ones(ogid.shape, bool), ogcap, "count",
                )
                og_end = (og_first.astype(jnp.int64) + og_count - 1)[ogid]
                og_end = jnp.clip(og_end, 0, child.cap - 1).astype(jnp.int32)
                s_out = csum[og_end]
                c_out = cnt[og_end]
            else:
                s_out = csum
                c_out = cnt
            return self._window_result(
                fn, s_out[inv],
                c_out[inv], c, dtype,
            )

        # bounded ROWS frame: sum over [pos-a, pos+b] via cumsum differences
        (lo_n, lo_u), (hi_n, hi_u) = frame
        part_first = K.segment_starts(gid, gcap)
        pos = jnp.arange(child.cap, dtype=jnp.int64)
        start_of_part = part_first[gid].astype(jnp.int64)
        part_count = K.segment_reduce(
            jnp.ones(child.cap, jnp.int64), gid, live[order], gcap, "count"
        )
        end_of_part = start_of_part + part_count[gid] - 1

        def bound_lo_raw():
            if (lo_n, lo_u) == ("unbounded", "preceding"):
                return start_of_part
            if (lo_n, lo_u) == ("current", None):
                return pos
            if lo_u == "preceding":
                return pos - int(lo_n)
            return pos + int(lo_n)  # N following

        def bound_hi_raw():
            if (hi_n, hi_u) == ("unbounded", "following"):
                return end_of_part
            if (hi_n, hi_u) == ("current", None):
                return pos
            if hi_u == "following":
                return pos + int(hi_n)
            return pos - int(hi_n)  # N preceding

        lo_raw = bound_lo_raw()
        hi_raw = bound_hi_raw()
        # the true frame is [lo_raw, hi_raw] intersected with the partition;
        # it can be EMPTY (e.g. `2 preceding and 1 preceding` at the first
        # row) — clamping alone would fake a one-row frame
        empty = (hi_raw < lo_raw) | (hi_raw < start_of_part) | (lo_raw > end_of_part)
        lo = jnp.clip(
            jnp.maximum(lo_raw, start_of_part), 0, child.cap - 1
        ).astype(jnp.int32)
        hi = jnp.clip(
            jnp.minimum(hi_raw, end_of_part), 0, child.cap - 1
        ).astype(jnp.int32)
        s_hi = csum[hi]
        c_hi = cnt[hi]
        s_lo = jnp.where(lo > 0, csum[jnp.maximum(lo - 1, 0)], jnp.zeros((), csum.dtype))
        c_lo = jnp.where(lo > 0, cnt[jnp.maximum(lo - 1, 0)], 0)
        # _segment_cumsum restarts at partition bounds: when lo is the
        # partition start, lo-1 points into the previous partition, so the
        # baseline is 0, not csum[lo-1]
        at_start = lo == start_of_part.astype(jnp.int32)
        s_lo = jnp.where(at_start, jnp.zeros((), csum.dtype), s_lo)
        c_lo = jnp.where(at_start, 0, c_lo)
        s_out = jnp.where(empty, jnp.zeros((), csum.dtype), s_hi - s_lo)
        c_out = jnp.where(empty, 0, c_hi - c_lo)
        return self._window_result(fn, s_out[inv], c_out[inv], c, dtype)

    def _window_result(self, fn, red, counts, c, dtype):
        if fn == "count":
            return Column(counts.astype(jnp.int64), INT64)
        if fn == "avg":
            vals = red.astype(jnp.float64) / jnp.maximum(counts, 1)
            if c is not None and c.dtype.is_decimal:
                vals = vals / 10**c.dtype.scale
            return Column(vals, FLOAT64, counts > 0)
        if fn in ("min", "max"):
            return Column(red, dtype, counts > 0, None if c is None else c.dictionary)
        # sum
        out_dtype = dtype
        if dtype.kind == "int32":
            out_dtype = INT64
        return Column(red, out_dtype, counts > 0)

    # ------------------------------------------------------------------
    # shared helpers
    def _evaluator(self, table: Table) -> Evaluator:
        ex = self

        class _Ev(Evaluator):
            def _eval_scalarsubquery(self, e):
                val, dtype, dictionary = ex._scalar_value(e)
                cap = self.table.cap
                if val is None:
                    return Column(
                        jnp.zeros(cap, dtype.device_np_dtype()),
                        dtype,
                        jnp.zeros(cap, bool),
                        dictionary,
                    )
                return Column(
                    jnp.full(cap, val, dtype.device_np_dtype()),
                    dtype,
                    None,
                    dictionary,
                )

        return _Ev(table)

    def _scalar_value(self, e: E.ScalarSubquery):
        key = id(e.plan)
        if key not in self._scalar_cache:
            cache = self._session_cache()
            if cache is not None:
                fp = self._fp(e.plan) + ":" + e.out_name
                hit = cache.scalars.get(fp)
                if hit is not None:
                    self._scalar_cache[key] = hit
                    return hit
            # the plan may yield a deferred-compaction table whose single
            # live row is NOT at index 0 — pack before slicing
            t = self.execute(e.plan).compacted()
            col = t.columns[e.out_name]
            if t.nrows == 0:
                self._scalar_cache[key] = (None, col.dtype, col.dictionary)
            else:
                # one batched transfer for value + validity (vs two RTTs)
                fetch = [col.data[:1]]
                if col.valid is not None:
                    fetch.append(col.valid[:1])
                got = jax.device_get(fetch)
                v = got[0][0]
                valid = True if col.valid is None else bool(got[1][0])
                self._scalar_cache[key] = (
                    v if valid else None,
                    col.dtype,
                    col.dictionary,
                )
            cache = self._session_cache()
            if cache is not None:
                cache.scalars[self._fp(e.plan) + ":" + e.out_name] = (
                    self._scalar_cache[key]
                )
        return self._scalar_cache[key]

    def _masked(self, table: Table, mask, transient: bool = False) -> Table:
        """Deferred compaction: keep rows in place under a live mask, with
        the count queued asynchronously (device->host syncs cost ~90 ms on
        the bench tunnel; a full compaction also pays one gather per
        column). Downstream operators consume row_mask() directly; packing
        happens lazily at collect()/limit via Table.compacted().

        Columns are shared by reference, so ownership is stripped unless
        the caller passes `transient=True` to assert `table` is a
        function-local temporary no cache or second consumer retains
        (e.g. a join's just-minted pair table under a residual filter)."""
        cols = (
            dict(table.columns)
            if transient
            else {n: c.disowned() for n, c in table.columns.items()}
        )
        return Table(
            cols, jnp.sum(mask, dtype=jnp.int32), live=mask,
            unique_key=table.unique_key,
        )

    def _compact(self, table: Table, mask) -> Table:
        count = K.mask_count(mask)
        cap = bucket_cap(max(count, 1))
        idx = K.compact_indices(mask, cap)
        return self._take(table, idx, count)

    def _take(self, table: Table, idx, nrows) -> Table:
        # idx is a permutation or de-duplicated subset of live rows
        # (sort order / compact indices), so base-table stats stay valid;
        # gather outputs are fresh owned buffers
        cols = {}
        for name, c in table.columns.items():
            cols[name] = Column(
                c.data[idx],
                c.dtype,
                None if c.valid is None else c.valid[idx],
                c.dictionary,
                c.subset_stats(),
                owned=True,
            )
        return Table(cols, nrows)

    def _distinct_table(self, t: Table, spill_parts=0) -> Table:
        t = self._pack_sparse(t)
        if spill_parts > 1 and t.columns:
            out = self._spilled_distinct(t, spill_parts)
            if out is not None:
                return out
        live = t.row_mask()
        words = self._group_words(list(t.columns.values()), live)
        order, gid, ng = K.group_by_words(words, live, t.nrows)
        gcap = bucket_cap(max(ng, 1))
        first = K.segment_starts(gid, gcap)
        rows = order[jnp.clip(first, 0, t.cap - 1)]
        out = self._take(t, rows, ng)
        out.unique_key = frozenset(out.columns)
        return out

    # -- out-of-core (spilled) execution --------------------------------
    # The host-RAM spill pool tier (engine/spill.py): when a plan's peak
    # materialization cannot fit HBM, the three remaining additive-capacity
    # shapes — build-side-too-big hash joins, full-table sorts, whole-input
    # distinct — run partitioned/windowed with intermediates staged in the
    # budgeted host pool (disk-backed past its budget). Engagement:
    # `engine.spill` off|auto|force — `auto` (default) spills exactly the
    # nodes the static plan budgeter annotated with `spill_partitions`
    # (verdict `spill`, analysis/budget.py); `force` (set by the report
    # ladder's spill_retry rung after an unpredicted device OOM) routes
    # every eligible node. Results are identical to the direct paths:
    # the external sort reuses the direct path's exact permutation, and
    # hash partitioning is value-exact for joins/distinct (SQL leaves
    # their row order undefined; only the partition-major order differs).

    #: partitions used under `engine.spill=force` when no explicit
    #: `engine.spill_partitions` is set (the spill_retry rung sets one)
    _SPILL_FORCE_PARTS = SP.DEFAULT_FORCE_PARTITIONS

    def _spill_parts_for(self, node) -> int:
        """Partition/run count for out-of-core execution of `node`, or 0
        for the direct path. Annotation-driven in `auto` mode so unspilled
        plans pay one getattr; `force` spills every eligible node."""
        session = getattr(self.catalog, "session", None)
        if session is None:
            return 0
        mode = str(session.conf.get("engine.spill", "auto")).lower()
        if mode == "off":
            return 0
        if mode == "force":
            try:
                p = int(session.conf.get("engine.spill_partitions", 0) or 0)
            except (TypeError, ValueError):
                p = 0
            return p if p > 1 else self._SPILL_FORCE_PARTS
        try:
            return int(getattr(node, "spill_partitions", 0) or 0)
        except (TypeError, ValueError):
            return 0

    def _spill_finish(self, op, parts, pool, before, segments,
                      t0=None) -> Table:
        """Assemble a spilled op's segments into one device table, record
        the statement-level spill evidence (executor + session markers,
        `spill` trace event — with the out-of-core step's measured wall
        when the caller timed it, the critical-path spill-io cause) and
        release the segments."""
        try:
            out = SP.assemble_segments(pool, segments)
        finally:
            pool.release(segments)
        delta = {
            k: pool.stats[k] - before.get(k, 0)
            for k in ("bytes_in", "bytes_out", "evictions")
        }
        note = self.last_spill or {
            "ops": 0, "partitions": 0, "bytes_in": 0, "bytes_out": 0,
            "evictions": 0,
        }
        note["ops"] += 1
        note["partitions"] = max(note["partitions"], parts)
        for k in ("bytes_in", "bytes_out", "evictions"):
            note[k] += delta[k]
        self.last_spill = note
        session = getattr(self.catalog, "session", None)
        if session is not None:
            session.last_spill = note
        if self.tracer is not None:
            self.tracer.emit(
                "spill", op=op, partitions=parts,
                bytes_in=delta["bytes_in"], bytes_out=delta["bytes_out"],
                evictions=delta["evictions"], rows=out.nrows_known,
                **({"dur_ms": round((_perf() - t0) * 1000.0, 3)}
                   if t0 is not None else {}),
            )
        return out

    def _spilled_join(self, left, right, kind, left_keys, right_keys,
                      residual, lk, lv, llive, rk, rv, rlive, parts) -> Table:
        """Partitioned (Grace-style) hash join through the spill pool: both
        sides hash-partition on the join key, each partition pair joins
        with the regular engine paths (keys/residual re-evaluated over the
        compacted partitions), and each partition's output spills to the
        host pool so only one partition's pair table is ever live in HBM.
        Exact: equal keys share a partition, so the union of per-partition
        join results is the direct join result (null-keyed left rows land
        in some partition, never match, and null-extend under LEFT —
        exactly as the direct path treats them)."""
        session = self.catalog.session
        pool = session.spill_pool
        before = dict(pool.stats)
        sp_t0 = _perf()
        lp = K.hash_columns(lk, lv) % parts
        rp = K.hash_columns(rk, rv) % parts
        segments = []
        was_disabled = self._exchange_disabled
        self._exchange_disabled = True
        try:
            for p in range(parts):
                lpart = self._compact(left, (lp == p) & llive)
                if lpart.nrows == 0 and segments:
                    continue  # empty probe side: this partition is empty
                rpart = self._compact(right, (rp == p) & rlive)
                if kind == "inner" and rpart.nrows == 0 and segments:
                    continue  # (LEFT must still null-extend its rows)
                out = self._join(
                    lpart, rpart, kind, left_keys, right_keys, residual
                )
                segments.append(pool.put(out))
                session.spill_progress()
            return self._spill_finish("join", parts, pool, before, segments,
                                      t0=sp_t0)
        except BaseException:
            pool.release(segments)
            raise
        finally:
            self._exchange_disabled = was_disabled

    def _spilled_take(self, child: Table, order, parts, op="sort"):
        """External sort tail: gather the sorted output in bounded windows
        of the direct path's OWN permutation, staging each sorted run in
        the host pool, then upload the assembled result once per column —
        peak device transient is O(window x width) instead of every
        column's full-capacity gather at once. Returns None when the input
        is too small to window (callers fall through to the direct take).
        Bit-identical to the direct path: same `order`, same row order."""
        wcap = bucket_cap(max(child.cap // parts, 1))
        if wcap >= child.cap:
            return None
        session = self.catalog.session
        pool = session.spill_pool
        before = dict(pool.stats)
        sp_t0 = _perf()
        nrows = child.nrows
        segments = []
        try:
            for start in range(0, child.cap, wcap):
                n_w = min(max(nrows - start, 0), wcap)
                if n_w <= 0 and segments:
                    break
                idx = _dyn_slice(order, start, wcap)
                cols = {
                    name: Column(
                        c.data[idx], c.dtype,
                        None if c.valid is None else c.valid[idx],
                        c.dictionary,
                    )
                    for name, c in child.columns.items()
                }
                segments.append(pool.put(Table(cols, n_w)))
                session.spill_progress()
            return self._spill_finish(op, parts, pool, before, segments,
                                      t0=sp_t0)
        except BaseException:
            pool.release(segments)
            raise

    def _spilled_distinct(self, t: Table, parts):
        """Spilling distinct: partition-hash dedup. Rows hash-partition
        over ALL columns (valid flags folded in, so NULLs — which distinct
        treats as equal — colocate), each partition dedups with the direct
        sort-word machinery, and partition results stage in the host pool.
        Exact as a row set: equal rows share a partition, partitions are
        disjoint. Returns None for empty input (direct path handles it)."""
        t = t.compacted()
        if t.nrows == 0:
            return None
        session = self.catalog.session
        pool = session.spill_pool
        before = dict(pool.stats)
        sp_t0 = _perf()
        live = t.row_mask()
        h = K.hash_columns(
            [c.data for c in t.columns.values()],
            [c.valid for c in t.columns.values()],
        ) % parts
        segments = []
        try:
            for p in range(parts):
                part = self._compact(t, (h == p) & live)
                if part.nrows == 0:
                    if not segments:
                        segments.append(pool.put(part))  # schema carrier
                    continue
                segments.append(pool.put(self._distinct_table(part)))
                session.spill_progress()
            out = self._spill_finish("distinct", parts, pool, before,
                                     segments, t0=sp_t0)
        except BaseException:
            pool.release(segments)
            raise
        out.unique_key = frozenset(out.columns)
        return out

    def _concat(self, a: Table, b: Table) -> Table:
        """Masked concatenation: columns append at full capacity (padded to
        a power-of-two bucket) under a combined live mask — no repacking
        gathers and no count syncs (union chains were paying both per
        level)."""
        names = list(a.columns)
        bnames = list(b.columns)
        cap = bucket_cap(max(a.cap + b.cap, 1))
        pad_n = cap - a.cap - b.cap
        live = jnp.pad(
            jnp.concatenate([a.row_mask(), b.row_mask()]), (0, pad_n)
        )
        n_lazy = (
            a.nrows_lazy + b.nrows_lazy
        )  # int + int stays host; device scalars stay lazy
        cols = {}
        for an, bn in zip(names, bnames):
            ca, cb = a.columns[an], b.columns[bn]
            # unify dtypes
            if ca.dtype.is_string or cb.dtype.is_string:
                (da, db), uni = _share_dictionary([ca, cb])
                dtype = ca.dtype
                dictionary = uni
            else:
                dtype = _common_dtype([ca.dtype, cb.dtype])
                da = _cast_column(ca, dtype, ca.data.shape[0])
                db = _cast_column(cb, dtype, cb.data.shape[0])
                dictionary = None
            data = jnp.pad(jnp.concatenate([da.data, db.data]), (0, pad_n))
            if da.valid is None and db.valid is None:
                valid = None
            else:
                va = da.valid if da.valid is not None else jnp.ones(a.cap, bool)
                vb = db.valid if db.valid is not None else jnp.ones(b.cap, bool)
                valid = jnp.pad(jnp.concatenate([va, vb]), (0, pad_n))
            cols[an] = Column(data, dtype, valid, dictionary)
        return Table(cols, n_lazy, live=live)


def _segment_cumsum(x, gid):
    """Cumulative sum within segments (gid sorted ascending)."""
    total = K.fast_cumsum(x)
    n = x.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    is_start = jnp.zeros(n, bool).at[0].set(True).at[1:].max(gid[1:] != gid[:-1])
    # propagate each row's own segment-start index forward. Native cummax,
    # NOT associative_scan: the generic log-depth scan construction
    # compiles for minutes at fact shapes on this toolchain.
    seg_start = K.fast_cummax(jnp.where(is_start, idx, 0))
    base = jnp.where(
        seg_start > 0, total[jnp.maximum(seg_start - 1, 0)], jnp.zeros((), total.dtype)
    )
    return total - base
