"""Binder/analyzer: SQL AST -> logical plan.

Responsibilities: name resolution over nested scopes, `*` expansion, CTE
registration (shared-identity plans so multiply-referenced CTEs materialize
once), predicate classification (pushdown / equi-join edges / residual),
subquery transformation (uncorrelated scalar -> cached broadcast; IN/EXISTS ->
semi/anti join; correlated scalar -> group-aggregate + left join, the standard
decorrelation for TPC-DS q1-style subqueries), aggregate/window extraction and
post-aggregation expression rewriting, ROLLUP grouping sets.

Counterpart of Spark Catalyst's analyzer, which the reference relies on via
`spark.sql(...)` (reference: nds/nds_power.py:125-135).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from . import expr as E
from . import plan as P
from .sql import ast as A


class BindError(Exception):
    pass


class Relation:
    """A FROM-item bound to a plan: output columns are qualified names."""

    def __init__(self, plan, alias, columns):
        self.plan = plan
        self.alias = alias  # may be None for joined compounds
        self.columns = columns  # list of (qualified_name, bare_name, rel_alias)

    def find(self, name, qualifier=None):
        out = []
        for qn, bare, ra in self.columns:
            if bare == name and (qualifier is None or ra == qualifier):
                out.append(qn)
        return out


class Scope:
    def __init__(self, relations, parent=None, aliases=None):
        self.relations = relations  # list[Relation]
        self.parent = parent
        self.aliases = aliases or {}  # select-item alias -> Expr

    def resolve(self, name, qualifier=None):
        """Returns (qualified_name, is_outer)."""
        hits = []
        for r in self.relations:
            hits += r.find(name, qualifier)
        if len(hits) == 1:
            return hits[0], False
        if len(hits) > 1:
            # same qualified name reachable through several compound relations
            if all(h == hits[0] for h in hits):
                return hits[0], False
            raise BindError(f"ambiguous column {qualifier+'.' if qualifier else ''}{name}: {hits}")
        if self.parent is not None:
            qn, _ = self.parent.resolve(name, qualifier)
            return qn, True
        raise BindError(f"cannot resolve column {qualifier+'.' if qualifier else ''}{name}")


class Binder:
    def __init__(self, catalog):
        self.catalog = catalog  # object with .schema(name) -> Schema | None
        self._counter = 0
        self._cte_plans = {}  # name -> (plan, columns) registered per bind
        self._subquery_residual = None  # set by _CorrelatedBinder.run
        # evidence log of LEFT->INNER promotions this bind performed:
        # {"conjunct": raw AST conjunct, "refs": promoted-side columns}.
        # The plan verifier (analysis/verifier.py) re-derives the
        # null-rejecting shape of each recorded conjunct — a promotion
        # from a null-tolerant predicate silently drops the outer join's
        # null-extended rows (the PR-1 wrong-LEFT->INNER bug class).
        self.promotions = []

    def fresh(self, prefix="_c"):
        self._counter += 1
        return f"{prefix}{self._counter}"

    # ------------------------------------------------------------------
    def bind(self, stmt: A.SelectStmt) -> P.PlanNode:
        plan, _cols = self.bind_select(stmt, None, {})
        return plan

    # ------------------------------------------------------------------
    def bind_select(self, stmt: A.SelectStmt, outer: Optional[Scope], views):
        """Returns (plan, out_columns [(out_name, alias)])."""
        views = dict(views)
        for name, sub in stmt.ctes:
            sub_plan, sub_cols = self.bind_select(sub, None, views)
            views[name.lower()] = (sub_plan, sub_cols)

        plan, cols = self._bind_core(stmt, outer, views)

        for op, rhs in stmt.set_ops:
            rplan, rcols = (
                self.bind_select(rhs, outer, views)
                if (rhs.ctes or rhs.set_ops)
                else self._bind_core(rhs, outer, views)
            )
            if len(rcols) != len(cols):
                raise BindError("set operation column count mismatch")
            # align rhs output names to lhs
            rplan = P.Project(
                [(E.Col(rn), ln) for (rn, _), (ln, _) in zip(rcols, cols)], rplan
            )
            kind = {"union all": "union_all", "union": "union",
                    "intersect": "intersect", "except": "except"}[op]
            plan = P.SetOp(kind, plan, rplan)

        if stmt.set_ops and (stmt.order_by or stmt.limit is not None):
            # outer ORDER BY binds to the unioned output columns
            out_aliases = {a: E.Col(n) for n, a in cols if a}
            for n, a in cols:
                out_aliases.setdefault(n, E.Col(n))
            if stmt.order_by:
                skeys = []
                for it in stmt.order_by:
                    e = it.expr
                    if isinstance(e, E.Lit) and isinstance(e.value, int):
                        e = E.Col(cols[e.value - 1][0])
                    elif isinstance(e, E.Col) and e.table is None and e.name in out_aliases:
                        e = out_aliases[e.name]
                    else:
                        e = self._bind_expr(e, Scope([Relation(None, None, [(n, a or n, None) for n, a in cols])]), views)
                    skeys.append((e, it.ascending, it.nulls_first))
                plan = P.Sort(skeys, plan)
            if stmt.limit is not None:
                plan = P.Limit(stmt.limit, plan)
        return plan, cols

    # ------------------------------------------------------------------
    def _bind_core(self, stmt: A.SelectStmt, outer, views):
        # 1. FROM — flatten explicit INNER JOIN ... ON chains into the same
        # relation list + conjunct pool as comma-FROM/WHERE queries, so ON
        # equalities become MultiJoin edges the greedy order optimizer can
        # reorder and merge into multi-key joins. Left-deep binary execution
        # of the q72 shape (catalog_sales JOIN inventory ON item_sk alone,
        # week/date constraints arriving only via later date_dim joins)
        # otherwise materializes a ~1e9-pair candidate table. LEFT JOINs are
        # held back as pending joins applied after the inner core (they
        # commute with inner joins on preserved-side columns); other kinds
        # (right/full/semi/anti) stay opaque binary trees.
        relations = []
        # (conjunct AST, visible relation range [lo, hi)): an ON clause
        # sees exactly its join's operands — the relations flattened under
        # that JoinClause — not sibling FROM items, so each conjunct
        # remembers its operand range and is later bound in that narrowed
        # scope (a bare column ambiguous against a sibling's column, or a
        # forward reference, must behave as it did under binary binding)
        on_conjuncts = []
        pending_left = []  # (relation index, raw ON AST, visible-from idx)
        outer_idx = set()  # relation indices held out of the MultiJoin

        def flatten(item):
            if isinstance(item, A.JoinClause) and item.kind in (
                "inner", "cross",
            ):
                lo = len(relations)
                flatten(item.left)
                flatten(item.right)
                if item.on is not None:
                    hi = len(relations)
                    on_conjuncts.extend(
                        (c, lo, hi) for c in _conjuncts(item.on)
                    )
                return
            if isinstance(item, A.JoinClause) and item.kind == "left":
                lo = len(relations)
                flatten(item.left)
                r = self._bind_from_item(item.right, outer, views)
                relations.append(r)
                outer_idx.add(len(relations) - 1)
                pending_left.append((len(relations) - 1, item.on, lo))
                return
            relations.append(self._bind_from_item(item, outer, views))

        if stmt.from_items:
            for item in stmt.from_items:
                flatten(item)
        else:
            # FROM-less SELECT: single-row dummy relation
            relations.append(Relation(P.MaterializedScan("__dual__"), "__dual__", []))
        scope = Scope(relations, outer)

        # 2. WHERE + flattened-ON classification
        filters_per_rel = {i: [] for i in range(len(relations))}
        edges = []
        residual = []
        post_join_subqueries = []  # (kind, ...) applied after MultiJoin
        # pool entries are (conjunct, binding scope): ON conjuncts bind in
        # their join's operand range, WHERE conjuncts in the full scope
        raw_conjuncts = []
        for c, lo, hi in on_conjuncts:
            cscope = Scope(relations[lo:hi], outer)
            # factor conjuncts common to every OR branch so join keys
            # buried in disjunctions (TPC-DS q13/q48 shape) become edges
            # instead of forcing a cross join
            raw_conjuncts.extend((f, cscope) for f in _factor_or(c))
        if stmt.where is not None:
            for raw in _conjuncts(stmt.where):
                raw_conjuncts.extend((f, scope) for f in _factor_or(raw))

        # Null-rejection promotion: a strict comparison in the conjunct pool
        # that references a pending LEFT JOIN's right side filters out that
        # join's null-extended rows, so the join is semantically INNER.
        # Promote it into the MultiJoin core — otherwise its equalities are
        # unusable as edges and the core disconnects into a cross join
        # (TPC-DS q93: `ss LEFT JOIN sr ON ..., reason WHERE
        # sr_reason_sk = r_reason_sk` would execute store_sales x reason).
        rel_cols = [{qn for qn, _, _ in r.columns} for r in relations]
        work = list(raw_conjuncts)
        while work and outer_idx:
            conj, cscope = work.pop()
            if not _null_rejecting_shape(conj):
                continue
            try:
                refs = _refs(self._bind_expr(conj, cscope, views))
            except BindError:
                continue
            for idx in sorted(outer_idx):
                if refs & rel_cols[idx]:
                    outer_idx.discard(idx)
                    self.promotions.append({
                        "conjunct": conj,
                        "refs": sorted(refs & rel_cols[idx]),
                    })
                    for pi, (pidx, on_ast, plo) in enumerate(pending_left):
                        if pidx == idx:
                            pending_left.pop(pi)
                            if on_ast is not None:
                                pscope = Scope(
                                    relations[plo:pidx + 1], outer
                                )
                                newc = [
                                    (c, pscope)
                                    for r_ in _conjuncts(on_ast)
                                    for c in _factor_or(r_)
                                ]
                                raw_conjuncts.extend(newc)
                                work.extend(newc)
                            break

        for conj, cscope in raw_conjuncts:
            self._classify_conjunct(
                conj, cscope, relations, views,
                filters_per_rel, edges, residual, post_join_subqueries,
                joinable=set(range(len(relations))) - outer_idx,
            )

        # 3. assemble join tree: inner MultiJoin core, then pending LEFT
        # joins in FROM order, then the residual filter (WHERE applies
        # after all FROM joins)
        inner_order = [i for i in range(len(relations)) if i not in outer_idx]
        remap = {i: pos for pos, i in enumerate(inner_order)}
        rel_plans = []
        for i in inner_order:
            p = relations[i].plan
            preds = filters_per_rel[i]
            if preds:
                p = P.Filter(_conjoin(preds), p)
            rel_plans.append(p)
        if len(rel_plans) == 1 and not edges:
            base = rel_plans[0]
        else:
            base = P.MultiJoin(
                rel_plans,
                [(remap[i], remap[j], le, re_) for (i, j, le, re_) in edges],
                None,
            )
        applied_cols = set()
        for i in inner_order:
            applied_cols |= rel_cols[i]
        for idx, on_ast, plo in pending_left:
            r = relations[idx]
            rcols = rel_cols[idx]
            lkeys, rkeys, jres = [], [], []
            if on_ast is not None:
                cond = self._bind_expr(
                    on_ast, Scope(relations[plo:idx + 1], outer), views
                )
                lkeys, rkeys, jres = _split_equi_conjuncts(
                    _conjuncts(cond), applied_cols, rcols
                )
            base = P.Join(
                "left", base, r.plan, lkeys, rkeys, _conjoin_ast(jres)
            )
            applied_cols |= rcols
        if residual:
            base = P.Filter(_conjoin(residual), base)
        # semi/anti/scalar-correlated joins after the main join
        for entry in post_join_subqueries:
            base = entry(base)

        # 4. select items: expand *, name them
        items = []  # (raw Expr (bound), out_name, alias_for_user)
        for sexpr, alias in stmt.select_items:
            if sexpr == "*":
                qual = alias  # ('*', qualifier) packs qualifier in alias slot
                for r in relations:
                    for qn, bare, ra in r.columns:
                        if qual is None or ra == qual:
                            items.append((E.Col(qn), bare))
            else:
                bound = self._bind_expr(sexpr, scope, views)
                items.append((bound, alias))
        named_items = []
        for bound, alias in items:
            if alias is None:
                if isinstance(bound, E.Col):
                    alias = bound.name.split(".")[-1]
                else:
                    alias = self.fresh("_c")
            named_items.append((bound, alias))
        scope.aliases = {a: e for e, a in named_items}

        having = (
            self._bind_expr(stmt.having, scope, views)
            if stmt.having is not None
            else None
        )
        order_exprs = []
        for it in stmt.order_by:
            e = it.expr
            if isinstance(e, E.Lit) and isinstance(e.value, int):
                e = named_items[e.value - 1][0]
            elif (
                isinstance(e, E.Col)
                and e.table is None
                and e.name in scope.aliases
            ):
                e = scope.aliases[e.name]
            else:
                try:
                    e = self._bind_expr(e, scope, views)
                except BindError:
                    # select aliases are visible inside ORDER BY expressions
                    # (q36/q70/q86: `case when lochierarchy = 0 then ...`).
                    # Alias exprs are already bound: shield them behind
                    # placeholders while the rest of the expression binds.
                    placeholders = {}

                    def sub_alias(x):
                        if (
                            isinstance(x, E.Col)
                            and x.table is None
                            and x.name in scope.aliases
                        ):
                            ph = E.Col(self.fresh("_ob"))
                            placeholders[ph.name] = scope.aliases[x.name]
                            return ph
                        return _rewrite_children(x, sub_alias)

                    e = self._bind_expr_partial(
                        sub_alias(e), scope, views, skip=set(placeholders)
                    )
                    for name, repl in placeholders.items():
                        e = _replace_node(e, E.Col(name), repl)
            order_exprs.append((e, it.ascending, it.nulls_first))

        group_exprs = []
        for g in stmt.group_by:
            if isinstance(g, E.Lit) and isinstance(g.value, int):
                group_exprs.append(named_items[g.value - 1][0])
            elif isinstance(g, E.Col) and g.table is None:
                # alias takes precedence only if not a real column
                try:
                    group_exprs.append(self._bind_expr(g, scope, views))
                except BindError:
                    if g.name in scope.aliases:
                        group_exprs.append(scope.aliases[g.name])
                    else:
                        raise
            else:
                group_exprs.append(self._bind_expr(g, scope, views))

        has_agg = (
            bool(group_exprs)
            or any(E.contains_agg(e) for e, _ in named_items)
            or (having is not None and E.contains_agg(having))
            or any(E.contains_agg(e) for e, _, _ in order_exprs)
        )

        if has_agg:
            base, rewrite = self._plan_aggregate(
                base, stmt, group_exprs, named_items, having, order_exprs
            )
            named_items = [(rewrite(e), a) for e, a in named_items]
            having = rewrite(having) if having is not None else None
            order_exprs = [(rewrite(e), asc, nf) for e, asc, nf in order_exprs]

        if having is not None:
            base = P.Filter(having, base)

        # 5. window functions (evaluated over the post-agg relation)
        win_fns = []

        def extract_windows(e):
            if isinstance(e, E.WindowFn):
                for wf, nm in win_fns:
                    if wf == e:
                        return E.Col(nm)
                nm = self.fresh("_w")
                win_fns.append((e, nm))
                return E.Col(nm)
            return _rewrite_children(e, extract_windows)

        named_items = [(extract_windows(e), a) for e, a in named_items]
        order_exprs = [(extract_windows(e), asc, nf) for e, asc, nf in order_exprs]
        if win_fns:
            base = P.Window(win_fns, base)

        # 6. projection (+ hidden sort keys), distinct, sort, limit, prune
        proj_items = []
        out_cols = []
        used = set()
        for e, a in named_items:
            out = a
            while out in used:
                out = self.fresh(a + "_")
            used.add(out)
            proj_items.append((e, out))
            out_cols.append((out, a))
        sort_keys = []
        for e, asc, nf in order_exprs:
            found = None
            for pe, on in proj_items:
                if pe == e:
                    found = on
                    break
            if found is None:
                hn = self.fresh("_s")
                proj_items.append((e, hn))
                found = hn
            sort_keys.append((E.Col(found), asc, nf))

        plan = P.Project(proj_items, base)
        if stmt.distinct:
            plan = P.Distinct(plan)
        if sort_keys and not stmt.set_ops:
            plan = P.Sort(sort_keys, plan)
        if len(proj_items) > len(out_cols):
            plan = P.Project(
                [(E.Col(on), on) for on, _ in out_cols], plan
            )
        if stmt.limit is not None and not stmt.set_ops:
            plan = P.Limit(stmt.limit, plan)
        return plan, out_cols

    # ------------------------------------------------------------------
    def _plan_aggregate(self, base, stmt, group_exprs, named_items, having, order_exprs):
        keys = []
        for g in group_exprs:
            keys.append((g, self.fresh("_g")))
        aggs = []

        def collect(e):
            if isinstance(e, E.Agg):
                for ag, nm in aggs:
                    if ag == e:
                        return
                aggs.append((e, self.fresh("_a")))
                return
            for c in e.children():
                collect(c)

        for e, _ in named_items:
            collect(e)
        if having is not None:
            collect(having)
        for e, _, _ in order_exprs:
            collect(e)
        for e in [e for e, _ in named_items]:
            for w in E.walk(e):
                if isinstance(w, E.WindowFn):
                    for c in w.children():
                        collect(c)

        grouping_sets = None
        if stmt.rollup:
            grouping_sets = [list(range(k)) for k in range(len(keys), -1, -1)]
        elif stmt.grouping_sets is not None:
            # map each raw set member onto the bound group key by structure
            grouping_sets = []
            for s in stmt.grouping_sets:
                idxs = []
                for e in s:
                    for i, g in enumerate(group_exprs):
                        if self._structurally_same(e, g):
                            idxs.append(i)
                            break
                grouping_sets.append(idxs)

        node = P.Aggregate(keys, aggs, base, grouping_sets)

        def rewrite(e):
            if e is None:
                return None
            for g, nm in keys:
                if e == g:
                    return E.Col(nm)
            if isinstance(e, E.Agg):
                for ag, nm in aggs:
                    if ag == e:
                        return E.Col(nm)
                raise BindError(f"unregistered aggregate {e}")
            if isinstance(e, E.WindowFn):
                return dataclasses.replace(
                    e,
                    arg=rewrite(e.arg) if e.arg is not None else None,
                    partition_by=tuple(rewrite(x) for x in e.partition_by),
                    order_by=tuple((rewrite(x), asc) for x, asc in e.order_by),
                )
            if isinstance(e, E.Col):
                raise BindError(
                    f"column {e} is neither grouped nor aggregated"
                )
            return _rewrite_children(e, rewrite)

        return node, rewrite

    def _structurally_same(self, raw, bound):
        # grouping-set member exprs are simple columns in TPC-DS; compare by
        # terminal name
        if isinstance(raw, E.Col) and isinstance(bound, E.Col):
            return bound.name.split(".")[-1] == raw.name or bound.name == raw.name
        return raw == bound

    # ------------------------------------------------------------------
    def _bind_from_item(self, item, outer, views) -> Relation:
        if isinstance(item, A.TableRef):
            name = item.name.lower()
            alias = item.alias or name
            if name in views:
                vplan, vcols = views[name]
                cols = [(qn, a, alias) for qn, a in vcols]
                # re-qualify through a projection so alias.col resolves
                proj = P.Project(
                    [(E.Col(qn), f"{alias}.{a}") for qn, a in vcols], vplan
                )
                return Relation(
                    proj, alias, [(f"{alias}.{a}", a, alias) for _, a in vcols]
                )
            schema = self.catalog.schema(name)
            if schema is None:
                raise BindError(f"unknown table {item.name}")
            cols = [(f"{alias}.{f.name}", f.name, alias) for f in schema]
            return Relation(P.Scan(name, alias), alias, cols)
        if isinstance(item, A.SubqueryRef):
            sub_plan, sub_cols = self.bind_select(item.query, outer, views)
            alias = item.alias
            proj = P.Project(
                [(E.Col(on), f"{alias}.{a}") for on, a in sub_cols], sub_plan
            )
            return Relation(
                proj, alias, [(f"{alias}.{a}", a, alias) for _, a in sub_cols]
            )
        if isinstance(item, A.JoinClause):
            return self._bind_join_clause(item, outer, views)
        raise BindError(f"unsupported FROM item {item}")

    def _bind_join_clause(self, jc: A.JoinClause, outer, views) -> Relation:
        left = self._bind_from_item(jc.left, outer, views)
        right = self._bind_from_item(jc.right, outer, views)
        scope = Scope([left, right], outer)
        lcols = {qn for qn, _, _ in left.columns}
        rcols = {qn for qn, _, _ in right.columns}
        if jc.on is not None:
            cond = self._bind_expr(jc.on, scope, views)
            lkeys, rkeys, residual = _split_equi_conjuncts(
                _conjuncts(cond), lcols, rcols
            )
            res = _conjoin_ast(residual)
        else:
            lkeys, rkeys = [], []
            res = None
        kind = jc.kind
        node = P.Join(kind, left.plan, right.plan, lkeys, rkeys, res)
        cols = list(left.columns) + (
            [] if kind in ("semi", "anti") else list(right.columns)
        )
        return Relation(node, None, cols)

    # ------------------------------------------------------------------
    def _classify_conjunct(
        self, conj, scope, relations, views,
        filters_per_rel, edges, residual, post_join, joinable=None,
    ):
        if joinable is None:
            joinable = set(range(len(relations)))
        # subquery predicates
        subs = [x for x in E.walk(conj) if isinstance(x, E.SubqueryExpr)]
        if subs:
            if len(subs) == 1 and _is_simple_subquery_conjunct(conj, subs[0]):
                post_join.append(
                    self._plan_subquery_conjunct(conj, subs[0], scope, views)
                )
            else:
                # subqueries under OR / multiple per conjunct (TPC-DS q10/q35
                # `exists(...) or exists(...)`): mark joins compute a bool
                # "has match" column per subquery, then the rewritten
                # predicate filters on the marks
                post_join.append(
                    self._plan_marked_conjunct(conj, subs, scope, views)
                )
            return
        bound = self._bind_expr(conj, scope, views)
        refs = _refs(bound)
        rel_sets = [
            {qn for qn, _, _ in r.columns} for r in relations
        ]
        touching = [i for i, s in enumerate(rel_sets) if refs & s]
        if len(touching) <= 1:
            i = touching[0] if touching else 0
            if touching and i not in joinable:
                # references a pending LEFT JOIN's right side: must apply
                # after that join (a WHERE filter on null-extended columns
                # does not commute with the outer join)
                residual.append(bound)
                return
            filters_per_rel[i].append(bound)
            return
        if (
            isinstance(bound, E.BinOp)
            and bound.op == "="
            and len(touching) == 2
            and all(i in joinable for i in touching)
        ):
            i, j = touching
            le, re_ = bound.left, bound.right
            if _refs(le) <= rel_sets[i] and _refs(re_) <= rel_sets[j]:
                edges.append((i, j, le, re_))
                return
            if _refs(le) <= rel_sets[j] and _refs(re_) <= rel_sets[i]:
                edges.append((i, j, re_, le))
                return
        residual.append(bound)

    # ------------------------------------------------------------------
    def _plan_subquery_conjunct(self, conj, sub: E.SubqueryExpr, scope, views):
        """Returns fn(base_plan) -> new_plan implementing the predicate."""
        if sub.kind == "exists":
            inner_plan, joins = self._bind_correlated(sub.query, scope, views)
            resid = self._subquery_residual
            if resid is not None and not joins:
                raise BindError(
                    "correlated non-equi subquery predicate needs at least "
                    "one equi correlation to join on"
                )
            kind = "anti" if _under_not(conj, sub) else "semi"
            lkeys = [o for o, _ in joins]
            rkeys = [i for _, i in joins]
            return lambda base: P.Join(
                kind, base, inner_plan, lkeys, rkeys, resid
            )
        if sub.kind == "in":
            operand = self._bind_expr(sub.operand, scope, views)
            inner_plan, joins = self._bind_correlated(
                sub.query, scope, views
            )
            resid = self._subquery_residual
            sub_cols = self._subquery_out_cols
            negated = sub.negated or _under_not(conj, sub)
            if not negated:
                lkeys = [operand] + [o for o, _ in joins]
                rkeys = [E.Col(sub_cols[0][0])] + [i for _, i in joins]
                return lambda base: P.Join(
                    "semi", base, inner_plan, lkeys, rkeys, resid
                )
            if resid is not None:
                raise BindError(
                    "correlated non-equi predicate under NOT IN is not "
                    "supported"
                )
            mark_specs, pred = self._not_in_lowering(
                operand, inner_plan, joins, sub_cols
            )

            def apply_not_in(base):
                for plan, lk, rk, name in mark_specs:
                    base = P.Join("mark", base, plan, lk, rk, mark_name=name)
                return P.Filter(pred, base)

            return apply_not_in
        if sub.kind == "scalar":
            # conj is CMP(expr, subquery) possibly correlated. Use a unique
            # placeholder for the subquery value so an outer column sharing
            # the subquery's output alias can't collide during binding.
            inner_plan, joins = self._bind_correlated(sub.query, scope, views)
            if self._subquery_residual is not None:
                # the left-join decorrelation below has nowhere to evaluate a
                # non-equi correlated predicate; refuse rather than drop it
                raise BindError(
                    "correlated non-equi predicate in a scalar subquery is "
                    "not supported"
                )
            sub_cols = self._subquery_out_cols
            placeholder = E.Col(self.fresh("_sqv"))
            cmp = _replace_node(conj, sub, placeholder)
            cmp = self._bind_expr_partial(cmp, scope, views, skip={placeholder.name})
            if not joins:
                # uncorrelated: broadcast scalar
                sc = E.ScalarSubquery(plan=inner_plan, out_name=sub_cols[0][0])
                cmp2 = _replace_node(cmp, placeholder, sc)
                return lambda base: P.Filter(cmp2, base)
            cmp = _replace_node(cmp, placeholder, E.Col(sub_cols[0][0]))
            lkeys = [o for o, _ in joins]
            rkeys = [i for _, i in joins]

            def apply(base):
                j = P.Join("left", base, inner_plan, lkeys, rkeys)
                return P.Filter(cmp, j)

            return apply
        raise BindError(f"unsupported subquery kind {sub.kind}")

    def _not_in_lowering(self, operand, inner_plan, joins, sub_cols):
        """3VL-correct NOT IN as mark joins + a boolean predicate.

        `x NOT IN (subquery)` is TRUE iff no inner row (of this row's
        correlation group) equals x, no inner row of the group has a NULL
        value, and either x is non-null or the group is empty. Returns
        (mark_specs, predicate): mark_specs are (plan, lkeys, rkeys, name)
        mark joins to apply to the base, predicate is the replacement expr.
        Group-scoped marks fix the classic global-null-count bug; scalar
        counts are only used when uncorrelated (group == whole subquery)."""
        val = E.Col(sub_cols[0][0])
        lcorr = [o for o, _ in joins]
        rcorr = [i for _, i in joins]
        m_match = self.fresh("_m")
        specs = [(inner_plan, [operand] + lcorr, [val] + rcorr, m_match)]
        null_rows = P.Filter(E.UnaryOp("isnull", val), inner_plan)
        if joins:
            m_null = self.fresh("_m")
            m_any = self.fresh("_m")
            specs.append((null_rows, lcorr, rcorr, m_null))
            specs.append((inner_plan, lcorr, rcorr, m_any))
            has_null = E.Col(m_null)
            has_any = E.Col(m_any)
        else:
            null_cnt = P.Aggregate(
                keys=[], aggs=[(E.Agg("count", None), "_nn")], child=null_rows
            )
            any_cnt = P.Aggregate(
                keys=[], aggs=[(E.Agg("count", None), "_na")], child=inner_plan
            )
            has_null = E.BinOp(
                ">", E.ScalarSubquery(plan=null_cnt, out_name="_nn"), E.Lit(0)
            )
            has_any = E.BinOp(
                ">", E.ScalarSubquery(plan=any_cnt, out_name="_na"), E.Lit(0)
            )
        pred = E.BinOp(
            "and",
            E.BinOp(
                "and",
                E.UnaryOp("not", E.Col(m_match)),
                E.UnaryOp("not", has_null),
            ),
            E.BinOp(
                "or",
                E.UnaryOp("isnotnull", operand),
                E.UnaryOp("not", has_any),
            ),
        )
        return specs, pred

    def _plan_marked_conjunct(self, conj, subs, scope, views):
        """Mark-join lowering for subqueries in arbitrary boolean context."""
        mark_joins = []  # (inner_plan, lkeys, rkeys, mark_name)
        rewritten = conj
        marks = set()
        # local, not instance state: binding an inner subquery below can
        # re-enter this method, which must not drain the outer call's
        # pending placeholder substitutions
        marked_replacements = {}
        for sub in subs:
            if sub.kind == "scalar":
                inner_plan, joins = self._bind_correlated(sub.query, scope, views)
                if joins or self._subquery_residual is not None:
                    raise BindError(
                        "correlated scalar subquery under OR is not supported"
                    )
                # uncorrelated: inline as a broadcast scalar (pre-bound, so
                # protect it behind a placeholder like the NOT IN lowering)
                sc = E.ScalarSubquery(
                    plan=inner_plan, out_name=self._subquery_out_cols[0][0]
                )
                placeholder = E.Col(self.fresh("_sqv"))
                marked_replacements[placeholder.name] = sc
                marks.add(placeholder.name)
                rewritten = _replace_node(rewritten, sub, placeholder)
                continue
            inner_plan, joins = self._bind_correlated(sub.query, scope, views)
            sub_cols = self._subquery_out_cols
            if sub.kind == "in" and sub.negated:
                operand = self._bind_expr(sub.operand, scope, views)
                specs, repl = self._not_in_lowering(
                    operand, inner_plan, joins, sub_cols
                )
                for plan, lk, rk, name in specs:
                    marks.add(name)
                    mark_joins.append((plan, lk, rk, name, None))
                # repl is fully bound already; protect it from re-binding
                placeholder = E.Col(self.fresh("_nip"))
                marked_replacements[placeholder.name] = repl
                marks.add(placeholder.name)
                rewritten = _replace_node(rewritten, sub, placeholder)
                continue
            mark = self.fresh("_m")
            marks.add(mark)
            lkeys = [o for o, _ in joins]
            rkeys = [i for _, i in joins]
            if sub.kind == "in":
                operand = self._bind_expr(sub.operand, scope, views)
                lkeys = [operand] + lkeys
                rkeys = [E.Col(sub_cols[0][0])] + rkeys
            repl = E.Col(mark)
            rewritten = _replace_node(rewritten, sub, repl)
            mark_joins.append(
                (inner_plan, lkeys, rkeys, mark, self._subquery_residual)
            )
        pred = self._bind_expr_partial(rewritten, scope, views, skip=marks)
        for name, repl in marked_replacements.items():
            pred = _replace_node(pred, E.Col(name), repl)

        def apply(base):
            for inner_plan, lkeys, rkeys, mark, resid in mark_joins:
                base = P.Join(
                    "mark", base, inner_plan, lkeys, rkeys, resid,
                    mark_name=mark,
                )
            return P.Filter(pred, base)

        return apply

    def _bind_correlated(self, query: A.SelectStmt, scope, views):
        """Bind a (possibly correlated) subquery.

        Correlated equi-conjuncts referencing the outer scope are stripped
        from the subquery and returned as join pairs (outer_expr, inner_col).
        If the subquery is a scalar aggregate, the correlation columns become
        its GROUP BY keys (classic decorrelation)."""
        corr = []

        sub_binder = _CorrelatedBinder(self, scope, corr, views)
        plan, cols = sub_binder.run(query)
        self._subquery_out_cols = cols
        return plan, corr

    # ------------------------------------------------------------------
    # expression binding
    def _bind_expr(self, e, scope: Scope, views):
        return self._bind_expr_partial(e, scope, views, skip=set())

    def _bind_expr_partial(self, e, scope, views, skip):
        def rec(x):
            if isinstance(x, E.Col):
                if x.name in skip:
                    return x
                qn, _outer = scope.resolve(x.name, x.table)
                return E.Col(qn)
            if isinstance(x, E.SubqueryExpr):
                if x.kind != "scalar":
                    raise BindError(
                        "IN/EXISTS subquery only supported in WHERE conjuncts"
                    )
                inner_plan, joins = self._bind_correlated(x.query, scope, views)
                if joins:
                    raise BindError(
                        "correlated scalar subquery only supported as a "
                        "WHERE comparison"
                    )
                cols = self._subquery_out_cols
                return E.ScalarSubquery(plan=inner_plan, out_name=cols[0][0])
            if isinstance(x, E.ScalarSubquery):
                return x
            return _rewrite_children(x, rec)

        return rec(e)


class _CorrelatedBinder:
    """Binds a subquery, stripping outer-referencing equi-conjuncts into
    correlation join pairs; adds correlation columns to GROUP BY for scalar
    aggregate subqueries."""

    def __init__(self, binder: Binder, outer_scope: Scope, corr_out: list, views=None):
        self.binder = binder
        self.outer = outer_scope
        self.corr = corr_out
        self.views = views or {}

    def run(self, query: A.SelectStmt):
        q = dataclasses.replace(query)
        # Pre-scan WHERE conjuncts for outer references
        inner_probe, _ = _probe_scope(self.binder, q, self.outer, self.views)
        kept = []
        corr_inner_exprs = []
        residual_conjs = []  # correlated NON-equi conjuncts (q16/q94 `<>`)
        if q.where is not None:
            for conj in _conjuncts(q.where):
                pair = self._try_correlated_equi(conj, inner_probe)
                if pair is not None:
                    outer_e, inner_e = pair
                    self.corr.append((outer_e, inner_e))
                    corr_inner_exprs.append(inner_e)
                elif self._refs_outer(conj, inner_probe):
                    residual_conjs.append(conj)
                else:
                    kept.append(conj)
            q.where = _conjoin_ast(kept)
        # binder._subquery_residual is set fresh on every return path below:
        # nested subqueries bound inside bind_select re-enter this method and
        # would otherwise leak their residual onto the enclosing join
        if (self.corr or residual_conjs) and _is_scalar_agg(q):
            if residual_conjs:
                raise BindError(
                    "correlated non-equi predicate in a scalar subquery is "
                    "not supported"
                )
            # group the aggregate by the correlation keys
            q = dataclasses.replace(q, group_by=list(q.group_by))
            plan, cols = self._bind_grouped_scalar(q, corr_inner_exprs)
            self.binder._subquery_residual = None
            return plan, cols
        if self.corr or residual_conjs:
            # expose the inner correlation keys (and any inner columns the
            # non-equi residual needs) through the subquery's own projection
            # (binding them in the subquery scope, where they resolve
            # correctly). The residual itself becomes a join residual on the
            # semi/anti/mark join, evaluated over the pair table where both
            # sides' columns coexist.
            binder = self.binder
            res_inner = []  # raw (name, table) inner Col refs of the residual
            for conj in residual_conjs:
                for x in E.walk(conj):
                    if isinstance(x, E.Col) and self._is_inner(x, inner_probe):
                        key = (x.name, x.table)
                        if key not in [(c.name, c.table) for c in res_inner]:
                            res_inner.append(x)
            extra = list(corr_inner_exprs) + list(res_inner)
            key_aliases = [binder.fresh("_ck") for _ in extra]
            q = dataclasses.replace(
                q,
                select_items=list(q.select_items)
                + [(e, a) for e, a in zip(extra, key_aliases)],
            )
            plan, cols = binder.bind_select(q, self.outer, self.views)
            nk = len(extra)
            val_cols, key_cols = cols[:-nk], cols[-nk:]
            ncorr = len(corr_inner_exprs)
            self.corr[:] = [
                (o, E.Col(kc[0]))
                for (o, _), kc in zip(self.corr, key_cols[:ncorr])
            ]
            bound_residual = None
            if residual_conjs:
                # bind each residual conjunct: inner cols -> their exposed
                # output columns; everything else -> the outer scope
                inner_map = {
                    (c.name, c.table): E.Col(kc[0])
                    for c, kc in zip(res_inner, key_cols[ncorr:])
                }

                def bind_residual(x):
                    if isinstance(x, E.Col):
                        if (x.name, x.table) in inner_map and self._is_inner(
                            x, inner_probe
                        ):
                            return inner_map[(x.name, x.table)]
                        qn, _ = self.outer.resolve(x.name, x.table)
                        return E.Col(qn)
                    return _rewrite_children(x, bind_residual)

                bound_residual = _conjoin(
                    [bind_residual(c) for c in residual_conjs]
                )
            binder._subquery_residual = bound_residual
            binder._subquery_out_cols = val_cols
            return plan, val_cols
        plan, cols = self.binder.bind_select(q, self.outer, self.views)
        self.binder._subquery_residual = None
        return plan, cols

    def _is_inner(self, col: E.Col, inner_probe) -> bool:
        try:
            inner_probe.resolve(col.name, col.table)
            return True
        except BindError:
            return False

    def _refs_outer(self, conj, inner_probe) -> bool:
        """True if the conjunct references at least one outer column."""
        for x in E.walk(conj):
            if isinstance(x, E.Col) and not self._is_inner(x, inner_probe):
                try:
                    self.outer.resolve(x.name, x.table)
                    return True
                except BindError:
                    pass
        return False

    def _bind_grouped_scalar(self, q, corr_inner_exprs):
        binder = self.binder
        # bind the scalar aggregate subquery with corr keys added as group
        # keys and projected out
        plan, cols = binder.bind_select(
            dataclasses.replace(
                q,
                select_items=list(q.select_items)
                + [(e, binder.fresh("_ck")) for e in corr_inner_exprs],
                group_by=list(q.group_by) + list(corr_inner_exprs),
            ),
            None,
            self.views,
        )
        n_keys = len(corr_inner_exprs)
        val_cols = cols[:-n_keys] if n_keys else cols
        key_cols = cols[-n_keys:] if n_keys else []
        self.corr[:] = [
            (o, E.Col(kc[0])) for (o, _), kc in zip(self.corr, key_cols)
        ]
        self.binder._subquery_out_cols = val_cols
        return plan, val_cols

    def _try_correlated_equi(self, conj, inner_probe):
        """If conj is outer_expr = inner_expr, return (bound_outer, raw_inner)."""
        if not (isinstance(conj, E.BinOp) and conj.op == "="):
            return None
        for a, b in ((conj.left, conj.right), (conj.right, conj.left)):
            if not isinstance(a, E.Col):
                continue
            try:
                inner_probe.resolve(a.name, a.table)
                continue  # resolves internally -> not an outer ref
            except BindError:
                pass
            try:
                qn, _ = self.outer.resolve(a.name, a.table)
            except BindError:
                continue
            return (E.Col(qn), b)
        return None


def _probe_scope(binder, q, outer, views=None):
    """Build a name-resolution-only scope for the subquery's FROM items,
    flattening joins and covering base tables, CTE views, and derived
    tables alike (misses here misclassify inner columns as correlations)."""
    views = views or {}
    flat = []
    stack = list(q.from_items)
    while stack:
        it = stack.pop()
        if isinstance(it, A.JoinClause):
            stack += [it.left, it.right]
        else:
            flat.append(it)
    rels = []
    for item in flat:
        if isinstance(item, A.TableRef):
            name = item.name.lower()
            alias = item.alias or name
            if name in views:
                _vplan, vcols = views[name]
                rels.append(
                    Relation(None, alias, [(f"{alias}.{a}", a, alias) for _, a in vcols])
                )
                continue
            schema = binder.catalog.schema(name)
            if schema is None:
                rels.append(Relation(None, alias, []))
            else:
                rels.append(
                    Relation(
                        None,
                        alias,
                        [(f"{alias}.{f.name}", f.name, alias) for f in schema],
                    )
                )
        elif isinstance(item, A.SubqueryRef):
            # approximate: output columns from its select list aliases
            cols = []
            for e, a in item.query.select_items:
                if a:
                    cols.append((f"{item.alias}.{a}", a, item.alias))
                elif isinstance(e, E.Col):
                    cols.append((f"{item.alias}.{e.name}", e.name, item.alias))
            rels.append(Relation(None, item.alias, cols))
    return Scope(rels, None), rels


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _conjuncts(e):
    if isinstance(e, E.BinOp) and e.op == "and":
        return _conjuncts(e.left) + _conjuncts(e.right)
    return [e]


def _disjuncts(e):
    if isinstance(e, E.BinOp) and e.op == "or":
        return _disjuncts(e.left) + _disjuncts(e.right)
    return [e]


def _factor_or(e):
    """(A and P1) or (A and P2) -> [A, (P1 or P2)]; identity otherwise."""
    if not (isinstance(e, E.BinOp) and e.op == "or"):
        return [e]
    branch_conjs = [_conjuncts(d) for d in _disjuncts(e)]
    common = [
        c
        for c in branch_conjs[0]
        if all(any(c == x for x in s) for s in branch_conjs[1:])
    ]
    if not common:
        return [e]
    remaining = []
    for s in branch_conjs:
        rest = [x for x in s if not any(x == c for c in common)]
        if not rest:
            return list(common)  # one branch is fully covered: OR is vacuous
        remaining.append(_conjoin(rest))
    out = list(common)
    disj = remaining[0]
    for r in remaining[1:]:
        disj = E.BinOp("or", disj, r)
    out.append(disj)
    return out


def _conjoin(preds):
    out = preds[0]
    for p in preds[1:]:
        out = E.BinOp("and", out, p)
    return out


def _conjoin_ast(preds):
    if not preds:
        return None
    return _conjoin(preds)


def _refs(e):
    return {x.name for x in E.walk(e) if isinstance(x, E.Col)}


def _null_rejecting_shape(conj):
    """True if the (unbound) conjunct is a comparison that is strict in its
    column references: any NULL operand makes it NULL, i.e. it filters out
    null-extended rows of every relation it touches. Conservative — any
    null-tolerant wrapper (IS NULL, CASE, coalesce) or subquery disqualifies.
    Drives LEFT-JOIN -> INNER promotion in _bind_core."""
    if not (
        isinstance(conj, E.BinOp)
        and conj.op in ("=", "<", ">", "<=", ">=", "<>", "!=")
    ):
        return False
    for x in E.walk(conj):
        if isinstance(x, E.UnaryOp) and x.op in ("isnull", "isnotnull"):
            return False
        if isinstance(x, (E.Case, E.SubqueryExpr, E.ScalarSubquery)):
            return False
        if isinstance(x, E.Func) and x.name.lower() in (
            "coalesce", "ifnull", "nvl",
        ):
            return False
        # null-tolerant boolean connectives nested inside an operand:
        # `a.x = (b.y OR TRUE)` is TRUE even when b.y is NULL, so the
        # comparison is NOT strict in b's columns (three-valued logic lets
        # AND/OR absorb a NULL input)
        if x is not conj and isinstance(x, E.BinOp) and x.op in (
            "and", "or",
        ):
            return False
    return True


def _split_equi_conjuncts(conjuncts, lcols, rcols):
    """Partition bound ON conjuncts into equi-key pairs (left expr over
    lcols, right expr over rcols) and a residual list. Shared by the binary
    join path and the pending-LEFT-JOIN assembly so the two stay in
    lockstep."""
    lkeys, rkeys, residual = [], [], []
    for conj in conjuncts:
        if isinstance(conj, E.BinOp) and conj.op == "=":
            le, re_ = conj.left, conj.right
            refs_l, refs_r = _refs(le), _refs(re_)
            if refs_l and refs_r:
                if refs_l <= lcols and refs_r <= rcols:
                    lkeys.append(le)
                    rkeys.append(re_)
                    continue
                if refs_l <= rcols and refs_r <= lcols:
                    lkeys.append(re_)
                    rkeys.append(le)
                    continue
        residual.append(conj)
    return lkeys, rkeys, residual


def _rewrite_children(e, fn):
    if isinstance(e, E.BinOp):
        return E.BinOp(e.op, fn(e.left), fn(e.right))
    if isinstance(e, E.UnaryOp):
        return E.UnaryOp(e.op, fn(e.operand))
    if isinstance(e, E.Between):
        return E.Between(fn(e.operand), fn(e.low), fn(e.high), e.negated)
    if isinstance(e, E.InList):
        return E.InList(fn(e.operand), e.values, e.negated)
    if isinstance(e, E.Like):
        return E.Like(fn(e.operand), e.pattern, e.negated)
    if isinstance(e, E.Case):
        return E.Case(
            tuple((fn(c), fn(v)) for c, v in e.branches),
            fn(e.default) if e.default is not None else None,
        )
    if isinstance(e, E.Cast):
        return E.Cast(fn(e.operand), e.target)
    if isinstance(e, E.Func):
        return E.Func(e.name, tuple(fn(a) for a in e.args))
    if isinstance(e, E.Agg):
        return E.Agg(e.fn, fn(e.arg) if e.arg is not None else None, e.distinct)
    if isinstance(e, E.WindowFn):
        return E.WindowFn(
            e.fn,
            fn(e.arg) if e.arg is not None else None,
            tuple(fn(x) for x in e.partition_by),
            tuple((fn(x), asc) for x, asc in e.order_by),
            e.frame,
        )
    return e


def _is_simple_subquery_conjunct(conj, sub):
    """True when replacing the whole conjunct by a join is semantics-preserving:
    the subquery is the entire conjunct (under optional NOT) for EXISTS/IN,
    or any shape for scalar (the scalar path filters the full rewritten
    predicate, so OR contexts stay correct)."""
    if sub.kind == "scalar":
        return True
    e = conj
    while isinstance(e, E.UnaryOp) and e.op == "not":
        e = e.operand
    return e is sub


def _find_subquery(e):
    for x in E.walk(e):
        if isinstance(x, E.SubqueryExpr):
            return x
    return None


def _under_not(conj, sub):
    """True if the subquery appears under a NOT (NOT EXISTS ...)."""
    def rec(e, neg):
        if e is sub:
            return neg
        if isinstance(e, E.UnaryOp) and e.op == "not":
            return rec(e.operand, not neg)
        for c in e.children():
            r = rec(c, neg)
            if r is not None:
                return r
        return None

    r = rec(conj, False)
    return bool(r)


def _replace_node(e, target, replacement):
    if e is target or e == target:
        return replacement

    def fn(x):
        return _replace_node(x, target, replacement)

    return _rewrite_children(e, fn)


def _is_scalar_agg(q: A.SelectStmt) -> bool:
    return (
        len(q.select_items) == 1
        and q.select_items[0][0] != "*"
        and E.contains_agg(q.select_items[0][0])
        and not q.group_by
    )
