"""tsan-lite runtime lock sanitizer (`engine.lock_debug`, off by default).

The static half (analysis/concurrency.py) pins the tree's canonical lock
order in anchors/lock_order.golden. This module is the runtime half: with
`engine.lock_debug` / NDS_LOCK_DEBUG on, `make_lock` wraps each named lock
in an order-recording proxy that

  * asserts the pinned static order on every live acquisition — taking a
    lock ranked BELOW one already held by this thread raises
    LockOrderError with both stacks' worth of context (the inversion a
    chaos gate can only witness; the proxy makes it deterministic);
  * emits a `lock_contention` event (and the `nds_lock_*` metric
    families) when an acquisition waited longer than
    `engine.lock_contention_ms`;
  * runs a watchdog that, when any lock is held past
    `engine.lock_hold_budget_s`, dumps every thread's stack plus the
    held-lock table into a flight-recorder bundle (obs/flight.py) —
    the post-hoc artifact for a suspected deadlock.

Off (the default), `make_lock` returns a plain threading.Lock/RLock: the
hot path pays nothing. Lock sites opt in by constructing through
`make_lock("Class.attr", conf)` instead of `threading.Lock()` — the name
must match the static model's (`ClassName.attr` for instance locks,
`relpath:NAME` for module-level ones) or order assertions are skipped
for it.
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback

__all__ = (
    "LockOrderError", "make_lock", "resolve_lock_debug",
    "resolve_contention_ms", "resolve_hold_budget_s", "held_locks",
    "check_holds", "reset_for_tests",
)


def resolve_lock_debug(conf: dict | None = None) -> bool:
    """`engine.lock_debug` / NDS_LOCK_DEBUG; off by default."""
    v = None
    if conf:
        v = conf.get("engine.lock_debug")
    if v is None:
        v = os.environ.get("NDS_LOCK_DEBUG")
    if v is None:
        return False
    return str(v).strip().lower() in ("1", "true", "yes", "on")


def resolve_contention_ms(conf: dict | None = None) -> float:
    """`engine.lock_contention_ms` / NDS_LOCK_CONTENTION_MS: acquisition
    waits at or above this emit `lock_contention` (default 50ms)."""
    v = None
    if conf:
        v = conf.get("engine.lock_contention_ms")
    if v is None:
        v = os.environ.get("NDS_LOCK_CONTENTION_MS")
    try:
        return max(float(v), 0.0) if v is not None and v != "" else 50.0
    except (TypeError, ValueError):
        return 50.0


def resolve_hold_budget_s(conf: dict | None = None) -> float:
    """`engine.lock_hold_budget_s` / NDS_LOCK_HOLD_BUDGET_S: a lock held
    past this is a suspected deadlock — the watchdog dumps all-thread
    stacks + the held-lock table to the flight recorder (default 30s;
    0 disables the watchdog)."""
    v = None
    if conf:
        v = conf.get("engine.lock_hold_budget_s")
    if v is None:
        v = os.environ.get("NDS_LOCK_HOLD_BUDGET_S")
    try:
        return max(float(v), 0.0) if v is not None and v != "" else 30.0
    except (TypeError, ValueError):
        return 30.0


class LockOrderError(RuntimeError):
    """A live acquisition inverted the pinned static lock order."""


# per-thread stack of currently-held DebugLocks (innermost last) and the
# re-entrancy latch that keeps the sanitizer's own telemetry (which may
# take a wrapped Tracer/Metrics lock) out of its own order checks
_tls = threading.local()

# process-wide held-lock registry for the watchdog/deadlock dump, keyed
# by id(proxy) — two Sessions share the NAME "Session.cache_lock" but
# are distinct locks. Guarded by a PLAIN lock on purpose: the registry
# must never recurse into its own instrumentation.
_REG_LOCK = threading.Lock()
# id(DebugLock) -> {"name","thread","since"}; process-wide BY DESIGN —
# the watchdog and the deadlock dump must see every session's holds
_HELD = {}  # nds-lint: disable=mutable-module-global

_rank_cache = None  # {lock name -> rank}, lazily loaded pinned order
_watchdog = None  # singleton watchdog thread handle
# (id, since) holds already bundled — dump once each (process-wide for
# the same reason as _HELD)
_dumped = set()  # nds-lint: disable=mutable-module-global


def _ranks() -> dict:
    """The pinned canonical order, name -> position. Loaded lazily from
    anchors/lock_order.golden via the static model; an unreadable golden
    disables order assertions (never takes the workload down)."""
    # process-wide memo of one immutable golden — not per-stream state
    global _rank_cache  # nds-lint: disable=mutable-module-global
    if _rank_cache is None:
        try:
            from ..analysis import concurrency

            _rank_cache = concurrency.load_pinned_order()
        except Exception:
            _rank_cache = {}
    return _rank_cache


def _held_stack():
    st = getattr(_tls, "held", None)
    if st is None:
        st = _tls.held = []
    return st


def _in_hook() -> bool:
    return getattr(_tls, "hook", False)


class _HookScope:
    def __enter__(self):
        self._prev = getattr(_tls, "hook", False)
        _tls.hook = True

    def __exit__(self, *exc):
        _tls.hook = self._prev
        return False


def _emit_contention(name: str, wait_ms: float):
    # the tracer's own lock may be a DebugLock: latch the hook flag so
    # this emission is exempt from order checks and wait accounting
    with _HookScope():
        try:
            from ..obs import trace as obs_trace

            tr = obs_trace.current()
            if tr is not None:
                tr.emit("lock_contention", lock=name, wait_ms=wait_ms)
        except Exception:
            pass  # telemetry must never take the workload down


class DebugLock:
    """Order-recording proxy over one named lock (see module docstring).
    Context-manager + acquire/release compatible with threading.Lock."""

    def __init__(self, name: str, inner, contention_ms: float,
                 hold_budget_s: float):
        self.name = str(name)
        self._inner = inner
        self._contention_ms = float(contention_ms)
        self._hold_budget_s = float(hold_budget_s)
        self._depth = 0  # re-entrant holds by the owning thread

    # -- order assertion -------------------------------------------------
    def _assert_order(self):
        ranks = _ranks()
        mine = ranks.get(self.name)
        if mine is None:
            return
        for held in _held_stack():
            if held is self:
                continue  # re-entrant re-acquire of an RLock
            r = ranks.get(held.name)
            if r is not None and r > mine:
                raise LockOrderError(
                    f"lock order inversion: acquiring {self.name!r} "
                    f"(rank {mine}) while holding {held.name!r} (rank "
                    f"{r}); the pinned order (anchors/lock_order.golden) "
                    f"requires {self.name!r} first. Fix the nesting or "
                    f"re-pin the order after review."
                )

    # -- bookkeeping -----------------------------------------------------
    def _on_acquired(self, waited_s: float):
        _held_stack().append(self)
        self._depth += 1
        if self._depth == 1:
            with _REG_LOCK:
                _HELD[id(self)] = {
                    "name": self.name,
                    "thread": threading.current_thread().name,
                    "since": time.monotonic(),
                }
        wait_ms = waited_s * 1000.0
        if self._contention_ms and wait_ms >= self._contention_ms:
            _emit_contention(self.name, round(wait_ms, 1))

    def _on_released(self):
        st = _held_stack()
        # release order may differ from acquire order under explicit
        # acquire()/release() pairs: drop the newest entry for self
        for i in range(len(st) - 1, -1, -1):
            if st[i] is self:
                del st[i]
                break
        self._depth -= 1
        if self._depth <= 0:
            self._depth = 0
            with _REG_LOCK:
                _HELD.pop(id(self), None)

    # -- lock protocol ---------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        if _in_hook():
            return self._inner.acquire(blocking, timeout)
        self._assert_order()
        t0 = time.monotonic()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._on_acquired(time.monotonic() - t0)
        return ok

    def release(self):
        if _in_hook():
            return self._inner.release()
        self._inner.release()
        self._on_released()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        fn = getattr(self._inner, "locked", None)  # RLock lacks it pre-3.14
        return fn() if fn is not None else self._depth > 0

    def __repr__(self):
        return f"<DebugLock {self.name!r} depth={self._depth}>"


def make_lock(name: str, conf: dict | None = None, reentrant: bool = False):
    """The named-lock factory every shared-state lock site constructs
    through. Debug off (default): a plain Lock/RLock, zero overhead.
    Debug on: a DebugLock asserting the pinned order (module docstring).
    Module-level locks (created at import, no conf in scope) resolve the
    knob from the environment only."""
    inner = threading.RLock() if reentrant else threading.Lock()
    if not resolve_lock_debug(conf):
        return inner
    lock = DebugLock(
        name, inner,
        contention_ms=resolve_contention_ms(conf),
        hold_budget_s=resolve_hold_budget_s(conf),
    )
    _ensure_watchdog(resolve_hold_budget_s(conf))
    return lock


# ---------------------------------------------------------------------------
# watchdog: suspected-deadlock dump
# ---------------------------------------------------------------------------


def held_locks() -> list:
    """The held-lock table (name, owning thread, held-for seconds),
    oldest hold first — the bundle's `threads.locks` section."""
    now = time.monotonic()
    with _REG_LOCK:
        rows = [
            {
                "lock": rec["name"],
                "thread": rec["thread"],
                "held_s": round(now - rec["since"], 3),
            }
            for rec in _HELD.values()
        ]
    rows.sort(key=lambda r: -r["held_s"])
    return rows


def _thread_stacks() -> dict:
    names = {t.ident: t.name for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        label = f"{names.get(ident, 'unknown')}-{ident}"
        out[label] = traceback.format_stack(frame)
    return out


def _dump_suspected_deadlock(over: list):
    """Bundle all-thread stacks + the held-lock table into the flight
    recorder (PR-14): the post-hoc artifact for a hold past budget."""
    with _HookScope():
        try:
            from ..obs import flight

            names = ", ".join(sorted(r["lock"] for r in over))
            flight.recorder().flush(
                reason=f"lock hold budget exceeded: {names}",
                threads={"stacks": _thread_stacks(), "locks": held_locks()},
            )
        except Exception:
            pass  # forensics must never take the workload down


def check_holds(now: float | None = None, budget_s: float | None = None):
    """One watchdog sweep, separable for tests: returns the over-budget
    held-lock rows (and bundles them once per hold when any exist)."""
    if now is None:
        now = time.monotonic()
    over, fresh = [], []
    with _REG_LOCK:
        for key, rec in _HELD.items():
            budget = budget_s
            if budget is None:
                budget = resolve_hold_budget_s()
            if budget and now - rec["since"] >= budget:
                row = {
                    "lock": rec["name"],
                    "thread": rec["thread"],
                    "held_s": round(now - rec["since"], 3),
                }
                over.append(row)
                if (key, rec["since"]) not in _dumped:
                    _dumped.add((key, rec["since"]))
                    fresh.append(row)
    if fresh:
        _dump_suspected_deadlock(fresh)
    return over


def _watchdog_loop(budget_s: float):
    interval = min(1.0, max(budget_s / 4.0, 0.05))
    while True:
        time.sleep(interval)
        try:
            check_holds(budget_s=budget_s)
        except Exception:
            pass  # the sweeper must never die loudly mid-run


def _ensure_watchdog(budget_s: float):
    # one sweeper per process, whichever session arms it first
    global _watchdog  # nds-lint: disable=mutable-module-global
    if not budget_s or _watchdog is not None:
        return
    with _REG_LOCK:
        if _watchdog is None:
            t = threading.Thread(
                target=_watchdog_loop, args=(budget_s,),
                name="nds-lockdebug-watchdog", daemon=True,
            )
            t.start()
            _watchdog = t


def reset_for_tests():
    """Drop the lazy rank cache + held/dumped registries (unit tests
    flip the golden and the knob between cases)."""
    global _rank_cache  # nds-lint: disable=mutable-module-global
    _rank_cache = None
    with _REG_LOCK:
        _HELD.clear()
        _dumped.clear()
    if getattr(_tls, "held", None):
        _tls.held = []
