"""Persistent on-disk AOT executable cache: compile each pipeline once, EVER.

Round-5 data put cold geomean ~20% above steady, and the gap is 100% XLA
compilation — every SF10 isolation subprocess re-paid every compile from
scratch. The reference harness gets cross-query executable reuse for free
from Spark's long-lived executor JVMs; this engine's equivalent lives here:
`FusedPipeline`/`FusedAggPipeline` dispatch resolves compiled executables
through an `AotCache`, and on a bucket-level compile the executable is
serialized (`jax.experimental.serialize_executable`) into a
fingerprint-keyed entry under `engine.aot_cache_dir` / `NDS_AOT_CACHE_DIR`.
A fresh process's first dispatch then DESERIALIZES instead of recompiling —
cold start collapses to disk-read time, and a fleet serving millions of
users compiles each pipeline once per environment, not once per process.

Key discipline (wrong-load is impossible, mismatch is a clean miss):
every entry is keyed by the full dict of everything that changes compiled
code — pipeline kind + stage fingerprint (plan.fingerprint, stable across
processes), a CONTENT-stable input signature (dtypes, validity, dictionary
content hashes, agg-key stats bounds), the flat argument avals (capacity
bucket included), donation slots, jax + jaxlib + nds_tpu versions, backend
platform + device kind + local device count, the x64 flag, and the
relevant engine conf (fuse_agg / pallas_agg). The key hashes into the
entry filename, but `load` re-verifies the FULL key dict recorded in the
entry header (a filename hash collision reads as a miss, never a wrong
load) and the payload checksum (a torn/corrupt body quarantines the file
and reads as a miss, never a crash).

Entry format: `aot-<sha256[:40]>.bin` = 8-byte magic "NDSAOT1\\n",
8-byte big-endian header length, canonical-JSON header (full key +
payload sha256 + sizes), then the pickled (payload, in_tree, out_tree)
from serialize_executable. Pickle is acceptable here: entries live in a
user-owned cache directory and carry the same trust as the jax
persistent compilation cache (the payload itself is pickle-based).

Production treatment (the spill pool / lakehouse patterns):
  * atomic writes — pid-tempfile sibling + os.replace, so a concurrent
    two-process warm has one winner and a crash leaves only a `.tmp-<pid>-`
    file the orphan sweep removes once the pid is dead;
  * byte budget with LRU eviction — `engine.aot_cache_bytes` /
    NDS_AOT_CACHE_BYTES, default auto-derived as a power-of-two share of
    the cache volume's free disk (analysis/budget.derive_share_bytes, the
    same derivation the union window and spill pool use); hits refresh
    mtime so eviction is least-recently-USED, not least-recently-written;
  * crash-orphan sweep at session start (once per process per directory);
  * `aot_cache` trace events + `nds_aot_cache_*` metric families +
    profiler tallies;
  * `aot:write` / `aot:read` fault-injection sites (io/crash kinds):
    injected faults keep their classifiable identity so the report
    ladder's io_backoff rung covers cache IO, while REAL filesystem
    errors degrade the cache (store disabled / entry quarantined) and
    never fail a query — a broken cache disk costs recompiles, not
    results.

The same directory also persists the Pallas promotion memos
(`PromotionStore`): the measured jnp-vs-Pallas A/B verdicts
(engine.pallas_agg/pallas_join/pallas_sort `auto`) keyed by (kernel,
shape, backend environment), so a fleet measures each shape once.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import threading
import time

from .. import faults
from .. import __version__ as _NDS_VERSION
from .lockdebug import make_lock

_MAGIC = b"NDSAOT1\n"
_ENTRY_PREFIX = "aot-"
_ENTRY_SUFFIX = ".bin"
_QUARANTINE_PREFIX = "quarantine-"
_PROMO_FILE = "promotions.json"

#: auto-budget derivation: 1/16 of the cache volume's free disk, clamped —
#: mirrors the union-window / spill-pool share-of-a-resource sizing
_BUDGET_FRACTION = 16
_BUDGET_LO = 256 << 20
_BUDGET_HI = 32 << 30


def resolve_aot_cache_dir(conf: dict | None = None) -> str | None:
    """Cache directory: conf `engine.aot_cache_dir`, env NDS_AOT_CACHE_DIR,
    else a user-owned XDG default (same /tmp-squatting reasoning as the
    XLA persistent cache in session._enable_persistent_compile_cache).
    Explicit "" / "0" disables the AOT cache."""
    v = None
    if conf:
        v = conf.get("engine.aot_cache_dir")
    if v is None:
        v = os.environ.get("NDS_AOT_CACHE_DIR")
    if v is None:
        return os.path.join(
            os.environ.get("XDG_CACHE_HOME")
            or os.path.join(os.path.expanduser("~"), ".cache"),
            "nds_aot_exec",
        )
    v = str(v)
    return v if v not in ("", "0") else None


def resolve_aot_cache_bytes(conf: dict | None = None,
                            cache_dir: str | None = None) -> int:
    """Entry byte budget: conf `engine.aot_cache_bytes` /
    NDS_AOT_CACHE_BYTES; unset or "auto" derives a power-of-two share of
    the cache volume's free disk (budget.derive_share_bytes — the same
    formula the union window derives from the device budget and the spill
    pool derives from host RAM)."""
    v = None
    if conf:
        v = conf.get("engine.aot_cache_bytes")
    if v is None:
        v = os.environ.get("NDS_AOT_CACHE_BYTES")
    if v is not None and str(v).lower() not in ("", "auto"):
        try:
            return max(int(v), 0)
        except (TypeError, ValueError):
            pass
    from ..analysis.budget import derive_share_bytes

    free = None
    try:
        import shutil

        probe = cache_dir
        while probe and not os.path.isdir(probe):
            parent = os.path.dirname(probe)
            if parent == probe:
                break
            probe = parent
        if probe:
            free = shutil.disk_usage(probe).free
    except OSError:
        free = None
    if not free:
        free = _BUDGET_HI * _BUDGET_FRACTION  # unknown volume: cap at HI
    return derive_share_bytes(free, _BUDGET_FRACTION, _BUDGET_LO, _BUDGET_HI)


def environment_key() -> dict:
    """The environment half of every entry key: everything OUTSIDE the
    pipeline that changes (or invalidates) compiled code. A mismatch in
    any field is a clean miss — a cache dir shared across jax upgrades,
    backend swaps, or device generations can never serve a stale
    executable."""
    import jax
    import jaxlib

    dev = jax.devices()[0]
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "nds": _NDS_VERSION,
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "local_devices": jax.local_device_count(),
        "processes": jax.process_count(),
        "x64": bool(jax.config.jax_enable_x64),
    }


def dictionary_hash(dictionary) -> str:
    """Content hash of a column dictionary (host-side Arrow string array):
    the in-process signature keys dictionaries by id(), which is truthful
    only while the object lives — an on-disk key must survive process
    death, so it hashes the VALUES. Dictionaries are dimension-sized, and
    this only runs at executable-resolution time (compile-level rarity),
    never per dispatch."""
    h = hashlib.sha256()
    try:
        for v in dictionary:
            s = v.as_py() if hasattr(v, "as_py") else v
            h.update(b"\x00" if s is None else str(s).encode("utf-8"))
            h.update(b"\x1f")
    except Exception:
        # unhashable/foreign dictionary object: key on its repr — worst
        # case a conservative extra miss, never a wrong load
        h.update(repr(dictionary).encode("utf-8", "replace"))
    return h.hexdigest()[:24]


def canonical_key_bytes(key: dict) -> bytes:
    return json.dumps(key, sort_keys=True, default=str).encode("utf-8")


def _entry_name(key: dict) -> str:
    digest = hashlib.sha256(canonical_key_bytes(key)).hexdigest()[:40]
    return f"{_ENTRY_PREFIX}{digest}{_ENTRY_SUFFIX}"


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True
    return True


class AotCache:
    """One process's handle on a shared on-disk executable cache.

    Thread-safe (one lock around stats + the dictionary-hash memo; file IO
    runs unlocked — atomicity comes from tempfile+rename, and concurrent
    writers of the SAME key are idempotent last-writer-wins). Cross-process
    safety needs no lock at all: readers only ever see fully-renamed
    entries, and eviction unlinks are tolerated by re-loading as a miss.
    """

    def __init__(self, cache_dir: str, budget_bytes: int,
                 tracer=None):
        self.dir = str(cache_dir)
        self.budget = int(budget_bytes)
        # callable returning the live tracer (a Session's tracer can be
        # swapped mid-run by harness loops; capturing the object would
        # emit into a closed file)
        self._tracer = tracer if callable(tracer) else (lambda: tracer)
        self._lock = make_lock("AotCache._lock")
        self._env = environment_key()
        # bounded LRU: the tuple's strong dictionary ref keeps the id()
        # key truthful, and the cap keeps a long-lived serving session
        # that rotates datasets from pinning every dictionary it ever
        # hashed (a dropped entry just re-hashes, compile-level rarity)
        from collections import OrderedDict

        # id(dic) -> (dic, hash)                 # nds-guarded-by: _lock
        self._dict_hashes = OrderedDict()
        self._dict_hash_cap = 512
        self.stats = {  # nds-guarded-by: _lock
            "lookups": 0, "disk_hits": 0, "misses": 0, "stores": 0,
            "store_failures": 0, "quarantined": 0, "evictions": 0,
        }
        self._store_disabled = False  # nds-guarded-by: _lock

    # -- events ----------------------------------------------------------
    def _emit(self, op: str, result: str, **extra):
        from ..obs import trace as obs_trace

        tracer = obs_trace.current() or self._tracer()
        if tracer is not None:
            tracer.emit("aot_cache", op=op, result=result, **extra)

    # -- keying ----------------------------------------------------------
    def entry_key(self, kind: str, fp: str, content_sig, avals,
                  donate_slots, conf_sig) -> dict:
        """The full key dict for one executable: pipeline identity +
        input layout + capacity-bucketed avals + donation + environment +
        relevant engine conf. See the module docstring for why every
        field is load-bearing."""
        return {
            "kind": kind,
            "fp": fp,
            "sig": list(content_sig),
            "avals": [[list(shape), str(dtype)] for shape, dtype in avals],
            "donate": list(donate_slots),
            "conf": list(conf_sig),
            "env": self._env,
        }

    def content_signature(self, table, with_stats: bool = False):
        """Process-independent analogue of fuse.input_signature: the same
        fields, with each dictionary's id() replaced by a content hash
        (memoized per object — the exec cache pins dictionaries, so the
        id is stable while the memo entry is)."""
        sig = [("live", table.live is not None)]
        for name, c in table.columns.items():
            dh = None
            if c.dictionary is not None:
                with self._lock:
                    hit = self._dict_hashes.get(id(c.dictionary))
                    if hit is not None:
                        self._dict_hashes.move_to_end(id(c.dictionary))
                if hit is not None and hit[0] is c.dictionary:
                    dh = hit[1]
                else:
                    dh = dictionary_hash(c.dictionary)
                    with self._lock:
                        self._dict_hashes[id(c.dictionary)] = (
                            c.dictionary, dh,
                        )
                        while len(self._dict_hashes) > self._dict_hash_cap:
                            self._dict_hashes.popitem(last=False)
            entry = (name, repr(c.dtype), c.valid is not None, dh)
            if with_stats:
                entry = entry + (
                    (int(c.stats.vmin), int(c.stats.vmax))
                    if c.stats is not None
                    else None,
                )
            sig.append(entry)
        return tuple(sig)

    # -- load / store ----------------------------------------------------
    def load(self, key: dict):
        """The deserialized compiled executable for `key`, or None (a
        miss: absent, foreign, corrupt, torn, checksum-failed, or
        environment-mismatched entry — corrupt entries are quarantined).
        Injected `aot:read` faults propagate (classifiable by the report
        ladder); real read errors are a miss."""
        path = os.path.join(self.dir, _entry_name(key))
        with self._lock:
            self.stats["lookups"] += 1
        t0 = time.perf_counter()
        faults.maybe_fire("aot:read", kinds=("io", "crash"))
        try:
            with open(path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            with self._lock:
                self.stats["misses"] += 1
            self._emit("load", "miss")
            return None
        except OSError:
            with self._lock:
                self.stats["misses"] += 1
            self._emit("load", "miss")
            return None
        entry = self._parse_entry(raw, key, path)
        if entry is None:
            with self._lock:
                self.stats["misses"] += 1
            return None
        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = pickle.loads(entry)
            compiled = se.deserialize_and_load(payload, in_tree, out_tree)
        except Exception as exc:
            self._quarantine(path, f"deserialize failed: {exc}")
            with self._lock:
                self.stats["misses"] += 1
            return None
        try:
            os.utime(path)  # LRU: a hit refreshes recency
        except OSError:
            pass
        dur = round((time.perf_counter() - t0) * 1000.0, 3)
        with self._lock:
            self.stats["disk_hits"] += 1
        self._emit(
            "load", "hit", bytes=len(raw), dur_ms=dur, key=_entry_name(key),
        )
        return compiled

    def _parse_entry(self, raw: bytes, key: dict, path: str):
        """Validated pickled blob from one raw entry, or None (quarantined).
        Full-key equality — not just the filename hash — and a payload
        checksum stand between a bad file and a wrong load."""
        try:
            if not raw.startswith(_MAGIC):
                raise ValueError("bad magic")
            off = len(_MAGIC)
            hlen = int.from_bytes(raw[off:off + 8], "big")
            off += 8
            header = json.loads(raw[off:off + hlen].decode("utf-8"))
            off += hlen
            body = raw[off:]
            if header.get("key") != json.loads(
                canonical_key_bytes(key).decode("utf-8")
            ):
                # filename-hash collision or foreign entry: a clean miss,
                # and NOT a quarantine — the entry may be someone else's
                # perfectly valid executable
                self._emit("load", "key_mismatch")
                return None
            if len(body) != int(header.get("body_bytes", -1)) or (
                hashlib.sha256(body).hexdigest() != header.get("body_sha256")
            ):
                raise ValueError("payload checksum mismatch")
            return body
        except Exception as exc:
            self._quarantine(path, str(exc))
            return None

    def _quarantine(self, path: str, reason: str):
        """Move a corrupt/torn/undeserializable entry aside (evidence
        survives for forensics; `cache vacuum` removes quarantines). A
        rename race with another process's quarantine/eviction is fine —
        the file is gone either way."""
        dest = os.path.join(
            self.dir,
            f"{_QUARANTINE_PREFIX}{os.path.basename(path)}.{os.getpid()}",
        )
        try:
            os.replace(path, dest)
        except OSError:
            pass
        with self._lock:
            self.stats["quarantined"] += 1
        self._emit("load", "quarantined", error=reason[:160])

    def store(self, key: dict, compiled) -> bool:
        """Serialize + atomically publish one compiled executable.
        Injected `aot:write` faults propagate (io kinds walk the report
        ladder's backoff rung; crash kinds simulate death mid-write,
        leaving a `.tmp-<pid>-` orphan for the sweep). A REAL filesystem
        failure disables further stores for this process (one warning) —
        a full/broken cache disk must cost recompiles, never queries."""
        if self._store_disabled:
            return False
        t0 = time.perf_counter()
        faults.maybe_fire("aot:write", kinds=("io", "crash"))
        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = se.serialize(compiled)
            # validate BEFORE publishing: an executable that was itself
            # loaded from the XLA persistent compilation cache serializes
            # into a payload whose symbol table cannot reload (observed
            # on jax 0.4.37 CPU: "Symbols not found" at deserialize) —
            # publishing it would make every future process quarantine it
            # on first touch. One extra deserialize per STORE (compile-
            # level rarity) buys "an entry on disk always loads".
            se.deserialize_and_load(payload, in_tree, out_tree)
            body = pickle.dumps((payload, in_tree, out_tree))
        except Exception:
            # unserializable executable (backend without AOT support, or
            # the XLA-cache-loaded case above): not an IO failure — skip
            # quietly, the in-process object still serves this process
            with self._lock:
                self.stats["store_failures"] += 1
            self._emit("store", "unserializable")
            return False
        header = canonical_key_bytes({
            "key": json.loads(canonical_key_bytes(key).decode("utf-8")),
            "body_bytes": len(body),
            "body_sha256": hashlib.sha256(body).hexdigest(),
            "created": int(time.time()),
            "pid": os.getpid(),
        })
        dest = os.path.join(self.dir, _entry_name(key))
        tmp = f"{dest}.tmp-{os.getpid()}-{hashlib.sha256(os.urandom(8)).hexdigest()[:6]}"
        try:
            os.makedirs(self.dir, exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(_MAGIC)
                f.write(len(header).to_bytes(8, "big"))
                f.write(header)
                f.write(body)
            os.replace(tmp, dest)
        except faults.FaultError:
            raise  # injected faults keep their classifiable identity
        except OSError as exc:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            with self._lock:
                self.stats["store_failures"] += 1
                disabled_now = not self._store_disabled
                self._store_disabled = True
            if disabled_now:
                print(f"aot: disabling executable stores ({exc})")
            self._emit("store", "io_error", error=str(exc)[:160])
            return False
        dur = round((time.perf_counter() - t0) * 1000.0, 3)
        with self._lock:
            self.stats["stores"] += 1
        self._emit(
            "store", "stored",
            bytes=len(body) + len(header) + len(_MAGIC) + 8,
            dur_ms=dur, key=_entry_name(key),
        )
        self._enforce_budget(keep=os.path.basename(dest))
        return True

    def quarantine_key(self, key: dict):
        """Quarantine the entry for `key` (a loaded executable that failed
        at call time: keyed correctly but unusable on this runtime)."""
        self._quarantine(
            os.path.join(self.dir, _entry_name(key)), "failed at call time"
        )

    # -- budget / hygiene ------------------------------------------------
    def _entries(self):
        """[(path, size, mtime)] of committed entries (temps, quarantines,
        and the promotion store are not budget-accounted entries)."""
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for name in names:
            if not (
                name.startswith(_ENTRY_PREFIX)
                and name.endswith(_ENTRY_SUFFIX)
            ):
                continue
            path = os.path.join(self.dir, name)
            try:
                st = os.stat(path)
            except OSError:
                continue
            out.append((path, st.st_size, st.st_mtime))
        return out

    def usage(self):
        """(entry count, total bytes) of committed entries."""
        entries = self._entries()
        return len(entries), sum(s for _, s, _ in entries)

    def _enforce_budget(self, keep: str | None = None):
        """LRU eviction to the byte budget: oldest-mtime entries unlink
        first (hits refresh mtime, so this is least-recently-USED). The
        just-written entry is excluded from victimhood — a budget smaller
        than one entry must not evict what it just stored."""
        entries = self._entries()
        total = sum(s for _, s, _ in entries)
        if total <= self.budget:
            return
        victims = sorted(
            (e for e in entries if os.path.basename(e[0]) != keep),
            key=lambda e: e[2],
        )
        evicted = 0
        for path, size, _ in victims:
            if total <= self.budget:
                break
            try:
                os.unlink(path)
            except OSError:
                continue
            total -= size
            evicted += 1
        if evicted:
            with self._lock:
                self.stats["evictions"] += evicted
            self._emit("evict", "evicted", entries=evicted)

    def vacuum(self, drop_all: bool = False):
        """Hygiene pass: dead-pid temp orphans + quarantine files are
        removed, then the budget is enforced (`drop_all` clears every
        committed entry too — the operator reset). Returns the number of
        files removed."""
        removed = sweep_orphans(self.dir)
        try:
            names = os.listdir(self.dir)
        except OSError:
            names = []
        for name in names:
            if name.startswith(_QUARANTINE_PREFIX) or (
                drop_all
                and name.startswith(_ENTRY_PREFIX)
                and name.endswith(_ENTRY_SUFFIX)
            ):
                try:
                    os.unlink(os.path.join(self.dir, name))
                    removed += 1
                except OSError:
                    pass
        if not drop_all:
            self._enforce_budget()
        self._emit("vacuum", "done", removed=removed)
        return removed


# ---------------------------------------------------------------------------
# crash hygiene: orphaned pid-tempfile sweep (the spill-pool pattern)
# ---------------------------------------------------------------------------


def sweep_orphans(cache_dir: str) -> int:
    """Remove `.tmp-<pid>-*` staging files whose owning process is dead —
    a crash mid-store must not accumulate torn temps forever. Only files
    matching the cache's own naming scheme are ever touched; a temp whose
    pid is alive (an in-flight store) is left alone."""
    if not cache_dir or not os.path.isdir(cache_dir):
        return 0
    try:
        names = os.listdir(cache_dir)
    except OSError:
        return 0
    removed = 0
    for name in names:
        if not name.startswith((_ENTRY_PREFIX, _PROMO_FILE)):
            continue
        if ".tmp-" not in name:
            continue
        tail = name.split(".tmp-", 1)[1]
        pid_s = tail.split("-", 1)[0]
        try:
            pid = int(pid_s)
        except ValueError:
            continue
        if pid == os.getpid() or _pid_alive(pid):
            continue
        try:
            os.unlink(os.path.join(cache_dir, name))
            removed += 1
        except OSError:
            pass
    if removed:
        print(f"aot: swept {removed} orphaned temp(s) from {cache_dir}")
    return removed


# one sweep per (process, directory): per-stream Session construction must
# not re-list the cache dir. Process-lifetime once-latch; worst case under
# a race is a second, idempotent sweep.
# nds-lint: disable=mutable-module-global
_SWEPT_DIRS = set()


def sweep_at_session_start(cache_dir: str | None):
    if not cache_dir or cache_dir in _SWEPT_DIRS:
        return
    _SWEPT_DIRS.add(cache_dir)
    sweep_orphans(cache_dir)


# ---------------------------------------------------------------------------
# promotion-memo persistence: measure each (kernel, shape) once per fleet
# ---------------------------------------------------------------------------


def promotion_key_str(key) -> str:
    """The persistent form of a session promotion-memo key: the in-memory
    tuple (kernel, shape dims...) plus the backend environment, because a
    verdict measured on one device generation/jax version says nothing
    about another."""
    env = environment_key()
    parts = [str(p) for p in key] + [
        env["platform"], env["device_kind"], env["jax"],
    ]
    return "|".join(parts)


class PromotionStore:
    """Shared JSON store of measured promotion verdicts
    (`promotions.json` in the AOT cache dir): `get` returns a verdict
    record or None; `record` merges one verdict in atomically
    (read-merge-tempfile-rename; a lost concurrent-writer race drops at
    most one record, which the next session simply re-measures). All IO
    is best-effort — a broken store costs re-measurement, never a query.
    """

    def __init__(self, cache_dir: str):
        self.path = os.path.join(str(cache_dir), _PROMO_FILE)
        self._lock = make_lock("PromotionStore._lock")
        # last-read snapshot (refreshed on record)  # nds-guarded-by: _lock
        self._cache = None

    def _read(self) -> dict:
        try:
            with open(self.path, encoding="utf-8") as f:
                data = json.load(f)
            return data if isinstance(data, dict) else {}
        except (OSError, ValueError):
            return {}

    def get(self, key_str: str):
        with self._lock:
            if self._cache is None:
                self._cache = self._read()
            rec = self._cache.get(key_str)
        if rec is not None and not isinstance(rec, dict):
            return None
        return rec

    def record(self, key_str: str, rec: dict):
        with self._lock:
            data = self._read()
            data[key_str] = rec
            self._cache = data
        # file IO OUTSIDE the lock: `get` is on the planning path and
        # shares it, so a slow store write would convoy every planner
        # behind a syscall (the blocking-under-lock class). Two
        # concurrent record()s may interleave here — last rename wins the
        # whole snapshot, dropping at most one record (the documented
        # race; the next session re-measures). `data` is private to this
        # call: _read() builds a fresh dict and nothing mutates _cache
        # in place.
        tmp = (
            f"{self.path}.tmp-{os.getpid()}-"
            f"{hashlib.sha256(os.urandom(8)).hexdigest()[:6]}"
        )
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(data, f, sort_keys=True)
            os.replace(tmp, self.path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def count(self) -> int:
        return len(self._read())
