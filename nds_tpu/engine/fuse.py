"""Pipeline fusion: whole-chain compilation of Filter/Project pipelines.

The eager executor pays a jit dispatch, an HLO round-trip, and (for
projections) a materialized intermediate per plan node. This module is the
engine's whole-stage-codegen seam (the reference gets the equivalent from
Spark fusing scan->filter->project into one compiled loop): a plan-rewrite
pass (`mark_pipelines`) replaces every maximal linear Filter/Project chain
with a single `plan.Pipeline` node, and the executor compiles that chain
as ONE jitted function over the child's device columns.

Fusion mechanics (correctness by construction):

  * The jitted function traces the SAME `expr.Evaluator` the eager path
    runs, so fused and unfused results are identical by construction —
    bit-exact for integer/decimal/date/string/bool data. Float64
    expressions can differ in the FINAL ULP only: XLA's algebraic
    simplifier sees the whole fused expression and may reassociate
    division chains that eager per-op dispatch rounds individually
    (measured <= 1e-12 relative on the windowed-ratio templates, vs the
    validator's 1e-5 epsilon contract). Host-side work the evaluator does
    over column dictionaries (LIKE lookup tables, IN lists, dictionary
    unification) runs once at trace time and bakes into the executable as
    constants — steady-state calls skip it entirely.
  * Outputs that merely pass an input buffer through (filter stages touch
    no column data; plain-Col projection items) are detected at build time
    by tracer identity and PRUNED from the jit signature: the output Table
    references the input buffers directly, and jax drops the then-unused
    inputs, so a fused filter allocates exactly what the eager
    deferred-compaction path allocates (one mask, one queued count) in one
    dispatch instead of one per plan node and expression op.
  * Masks and compaction stay deferred to the pipeline boundary: the fused
    function folds every filter predicate into a single live mask and
    queues the output count asynchronously, exactly like exec._masked.
  * When the input table has no mask (live=None), the live mask is built
    INSIDE the jit from a scalar row count (`count` mode) — no mask buffer
    crosses the boundary at all. When a mask must be passed and the chain
    consumes it (does not pass it through), `engine.fuse_donate=on`
    donates its buffer to the executable. Donation is opt-in: probe-style
    join outputs alias their left input's live mask across operator
    boundaries, and plan-cached tables outlive the statement, so blanket
    donation can invalidate a buffer another table still references (see
    README "Performance").

Shape-bucketed executable reuse: inputs already ride power-of-two capacity
buckets (columnar.bucket_cap), and jax caches one executable per (traced
function, input shapes). `ExecutableCache` keys the traced function by
(pipeline structure fingerprint, input dtype signature) and tracks the
(key, bucket) pairs already compiled, so steady-state re-runs AND
structurally identical queries across a stream reuse executables; the
hit/miss stream is observable as `exec_cache` trace events and enforced by
ci/tier1-check's microbench guard (`profile --min_exec_cache_hit_rate`).
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict

import jax
import jax.numpy as jnp

from . import expr as E
from . import plan as P
from .columnar import Column, Table
from .expr import Evaluator


# ---------------------------------------------------------------------------
# plan rewrite: absorb Filter/Project chains into Pipeline nodes
# ---------------------------------------------------------------------------

# a pipeline child whose live mask may be donated must be a single-consumer
# intermediate no cache retains AND whose mask it owns: scans alias catalog
# buffers; Aggregate/Distinct/SetOp/Window results live in the session plan
# cache across statements; binary Join outputs alias their LEFT input's
# live mask on the left/mark augment paths (exec._augment_join_output), so
# donating their mask would invalidate a buffer the left table still
# references. MultiJoin stays eligible: its inner/cross steps always mint a
# fresh mask (matched / compacted / residual) owned by the output alone.
_NO_DONATE_CHILD = (P.Scan, P.MaterializedScan, P.Join, P.Aggregate,
                    P.Distinct, P.SetOp, P.Window)


def _expr_fusible(e) -> bool:
    """True when an expression can trace inside one jitted function:
    anything except subqueries (they execute whole plans and fetch scalars
    to the host) and aggregate/window functions (never scalar-evaluated).
    Host-side dictionary work (LIKE, IN, string functions) is fine — it
    runs at trace time over concrete dictionaries. Chains that still fail
    to trace (e.g. numeric->string casts, which format device values on
    host) are caught at build time and pinned to the eager path."""
    for x in E.walk(e):
        if isinstance(
            x, (E.SubqueryExpr, E.ScalarSubquery, E.Agg, E.WindowFn)
        ):
            return False
    return True


def _stage_fusible(n) -> bool:
    if isinstance(n, P.Filter):
        return _expr_fusible(n.predicate)
    if isinstance(n, P.Project):
        return bool(n.items) and all(_expr_fusible(e) for e, _ in n.items)
    return False


def _chain_worth_fusing(stages) -> bool:
    """A pure-rename/subset chain gains nothing from compilation (the eager
    path reuses the input column objects outright); fuse only when the
    chain filters or computes something."""
    for s in stages:
        if isinstance(s, P.Filter):
            return True
        if any(not isinstance(e, E.Col) for e, _ in s.items):
            return True
    return False


def _count_refs(node) -> dict:
    """Plan-node reference counts (subquery plans riding in expressions
    included). A shared wrapper must not be absorbed into a pipeline: the
    detached copy would defeat the executor's by-identity result reuse."""
    refs = {}
    seen = set()

    def visit(v):
        if isinstance(v, (P.PlanNode, E.Expr)):
            if isinstance(v, P.PlanNode):
                refs[id(v)] = refs.get(id(v), 0) + 1
            if id(v) in seen:
                return
            seen.add(id(v))
            for f in dataclasses.fields(v):
                visit(getattr(v, f.name))
        elif isinstance(v, (list, tuple)):
            for x in v:
                visit(x)

    visit(node)
    return refs


def mark_pipelines(node: P.PlanNode):
    """Rewrite every maximal linear Filter/Project chain (anywhere in the
    tree, subquery plans included) into one `plan.Pipeline` node.

    Returns (root, count): the root itself may head a chain, so callers
    must adopt the returned root; `count` is the number of pipelines
    created (plan-introspection aid for tests/tools)."""
    refs = _count_refs(node)
    made = 0
    seen = set()

    def absorb(n):
        """The Pipeline replacing chain head `n`, or `n` unchanged."""
        nonlocal made
        topdown = []
        cur = n
        while isinstance(cur, (P.Filter, P.Project)) and _stage_fusible(cur):
            # shared nodes keep their identity (the executor caches results
            # by id): a chain stops at the first node with a second parent
            if refs.get(id(cur), 1) > 1:
                break
            topdown.append(cur)
            cur = cur.child
        if not topdown or not _chain_worth_fusing(topdown):
            return n
        stages = []
        for s in reversed(topdown):  # execution (innermost-first) order
            if isinstance(s, P.Filter):
                stages.append(P.Filter(predicate=s.predicate, child=None))
            else:
                stages.append(P.Project(items=list(s.items), child=None))
        made += 1
        return P.Pipeline(
            stages=stages,
            child=cur,
            donate_ok=(
                refs.get(id(cur), 1) <= 1
                and not isinstance(cur, _NO_DONATE_CHILD)
            ),
        )

    def visit(v):
        if isinstance(v, (P.PlanNode, E.Expr)):
            if id(v) in seen:
                return
            seen.add(id(v))
            if isinstance(v, P.Sort):
                # single-consumer annotation for the Limit-over-Sort top-k
                # gather (exec._exec_limit): a shared Sort must execute in
                # full once, not top-k for one parent and again in full
                # for the other
                v._topk_safe = refs.get(id(v), 1) <= 1
            if isinstance(v, P.Pipeline):
                # stages are detached (child=None) fragments: never
                # re-absorb them; only the real child subtree recurses
                visit(v.child)
                return
            for f in dataclasses.fields(v):
                cv = getattr(v, f.name)
                if isinstance(cv, P.PlanNode):
                    nv = absorb(cv)
                    if nv is not cv:
                        # Expr dataclasses are frozen; the plan field of a
                        # ScalarSubquery is excluded from hash/compare, so
                        # in-place rewrite is safe
                        object.__setattr__(v, f.name, nv)
                        cv = nv
                elif isinstance(cv, list):
                    for i, x in enumerate(cv):
                        if isinstance(x, P.PlanNode):
                            nx = absorb(x)
                            if nx is not x:
                                cv[i] = nx
                visit(cv)
        elif isinstance(v, (list, tuple)):
            for x in v:
                visit(x)

    root = absorb(node)
    visit(root)
    return root, made


# ---------------------------------------------------------------------------
# fused evaluation
# ---------------------------------------------------------------------------


class _StatsMarker:
    """Build-time stand-in for an input column's ColStats: an output column
    whose stats object survived the chain untouched maps back to the input
    column index, so every CALL resolves stats from its own input table
    (bounds captured from a trace-time sample would go stale under
    executable reuse across datasets)."""

    __slots__ = ("idx",)

    def __init__(self, idx):
        self.idx = idx


class _InCol:
    """Input-column metadata a FusedPipeline retains (device buffers must
    not outlive the call — see FusedPipeline.__init__)."""

    __slots__ = ("dtype", "has_valid", "dictionary", "has_stats")

    def __init__(self, dtype, has_valid, dictionary, has_stats):
        self.dtype = dtype
        self.has_valid = has_valid
        self.dictionary = dictionary
        self.has_stats = has_stats


class FusedPipeline:
    """One compiled Filter/Project chain for one input signature.

    Built once per (stage fingerprint, input signature); jax adds one
    executable per input capacity bucket underneath the single traced
    callable. Construction traces the chain abstractly (jax.eval_shape) to
    capture output structure and the passthrough map; a chain that cannot
    trace raises, and the ExecutableCache pins its signature to the eager
    path."""

    def __init__(self, stages, sample: Table):
        self.stages = stages
        self.in_names = list(sample.columns)
        # metadata ONLY — never retain the sample's Column objects: an
        # entry lives for the session and a retained fact-scale .data
        # buffer would pin GBs of device memory past any OOM-recovery wipe
        self.in_meta = [
            _InCol(
                c.dtype,
                c.valid is not None,
                c.dictionary,
                c.stats is not None,
            )
            for c in sample.columns.values()
        ]
        # the dictionaries ARE retained deliberately: the cache key uses
        # id(dictionary), which stays truthful only while the object is
        # alive (a recycled address must not alias a new dict), and the
        # trace bakes their lookup tables in. Host-side, dimension-sized.
        self.has_filter = any(isinstance(s, P.Filter) for s in stages)
        # live handling: "count" (live=None input: the mask is built inside
        # the jit from a scalar row count — no mask buffer at the boundary),
        # "mask" (explicit mask input), "none" (pure projection over an
        # unmasked table: liveness never enters the jit)
        if self.has_filter:
            self.live_mode = "count" if sample.live is None else "mask"
        else:
            self.live_mode = "none" if sample.live is None else "mask_pass"
        self.out_meta = None
        self.passthrough = None
        specs = []
        if self.live_mode == "count":
            specs.append(jax.ShapeDtypeStruct((), jnp.int32))
        elif self.live_mode in ("mask", "mask_pass"):
            specs.append(jax.ShapeDtypeStruct((sample.cap,), jnp.bool_))
        for c in sample.columns.values():
            specs.append(jax.ShapeDtypeStruct(c.data.shape, c.data.dtype))
        for c in sample.columns.values():
            if c.valid is not None:
                specs.append(jax.ShapeDtypeStruct((sample.cap,), jnp.bool_))
        jax.eval_shape(self._run_full, *specs)
        # outputs that pass an input buffer through are reassembled from
        # the caller's own columns; pruning them from the jit lets jax drop
        # the then-unused inputs entirely (no copies through the
        # executable)
        self._kept = [
            i for i, src in enumerate(self.passthrough) if src is None
        ]
        self._jit = jax.jit(self._run_kept)
        self._jit_donate = None

    # -- traced body ------------------------------------------------------
    def _flat_inputs(self, flat):
        i = 0
        live = None
        if self.live_mode == "count":
            n = flat[0]
            i = 1
        elif self.live_mode in ("mask", "mask_pass"):
            live = flat[0]
            i = 1
        datas = flat[i:i + len(self.in_meta)]
        i += len(self.in_meta)
        cap = int(datas[0].shape[0]) if datas else (
            int(live.shape[0]) if live is not None else 0
        )
        if self.live_mode == "count":
            live = jnp.arange(cap, dtype=jnp.int32) < n
        cols = {}
        vi = i
        for ci, (name, c, d) in enumerate(
            zip(self.in_names, self.in_meta, datas)
        ):
            valid = None
            if c.has_valid:
                valid = flat[vi]
                vi += 1
            cols[name] = Column(
                d, c.dtype, valid, c.dictionary,
                _StatsMarker(ci) if c.has_stats else None,
            )
        nrows = jnp.sum(live, dtype=jnp.int32) if live is not None else 0
        return Table(cols, nrows, live=live)

    def _run_full(self, *flat):
        t = self._flat_inputs(flat)
        for s in self.stages:
            ev = Evaluator(t)
            if isinstance(s, P.Filter):
                pr = ev.eval(s.predicate)
                mask = pr.data.astype(bool)
                if pr.valid is not None:
                    mask = mask & pr.valid
                mask = mask & t.row_mask()
                t = Table(
                    dict(t.columns), jnp.sum(mask, dtype=jnp.int32),
                    live=mask,
                )
            else:
                cols = {name: ev.eval(e) for e, name in s.items}
                t = Table(cols, t.nrows_lazy, live=t.live)
        # flatten outputs + capture structure (side effect: runs at trace
        # time only, with identical values on every trace)
        flat_out = []
        if self.has_filter:
            flat_out.append(t.nrows_lazy)  # queued count (0-d device)
            flat_out.append(t.live)
        self.out_data_base = len(flat_out)
        for c in t.columns.values():
            flat_out.append(c.data)
        valid_slots = []
        for c in t.columns.values():
            if c.valid is not None:
                valid_slots.append(len(flat_out))
                flat_out.append(c.valid)
            else:
                valid_slots.append(None)
        self.out_valid_slots = valid_slots
        self.out_meta = [
            (name, c.dtype, c.dictionary, c.stats)
            for name, c in t.columns.items()
        ]
        self.passthrough = [
            next((j for j, a in enumerate(flat) if o is a), None)
            for o in flat_out
        ]
        return tuple(flat_out)

    def _run_kept(self, *flat):
        out = self._run_full(*flat)
        return tuple(out[i] for i in self._kept)

    # -- call -------------------------------------------------------------
    def _flat_args(self, table: Table):
        flat = []
        if self.live_mode == "count":
            # asarray, not int(): the count may be a still-queued 0-d
            # device scalar and must not force a sync here
            flat.append(jnp.asarray(table.nrows_lazy, dtype=jnp.int32))
        elif self.live_mode in ("mask", "mask_pass"):
            flat.append(table.row_mask())
        for c in table.columns.values():
            flat.append(c.data)
        for c in table.columns.values():
            if c.valid is not None:
                flat.append(c.valid)
        return flat

    def _donatable(self):
        """Flat arg indices safe to donate: the live-mask input, when the
        chain consumes it rather than passing it through."""
        if self.live_mode != "mask":
            return ()
        if any(src == 0 for src in self.passthrough):
            return ()
        return (0,)

    def call(self, table: Table, donate: bool) -> Table:
        flat = self._flat_args(table)
        if donate and self._donatable():
            if self._jit_donate is None:
                self._jit_donate = jax.jit(
                    self._run_kept, donate_argnums=self._donatable()
                )
            out = self._jit_donate(*flat)
        else:
            out = self._jit(*flat)
        # reassemble: computed slots from the executable, passthrough
        # slots straight from the caller's own buffers
        full = [None] * len(self.passthrough)
        for slot, v in zip(self._kept, out):
            full[slot] = v
        for slot, src in enumerate(self.passthrough):
            if src is not None:
                full[slot] = flat[src]
        if self.has_filter:
            nrows, live = full[0], full[1]
        else:
            nrows, live = table.nrows_lazy, table.live
        in_cols = list(table.columns.values())
        cols = {}
        for k, (name, dtype, dic, st) in enumerate(self.out_meta):
            data = full[self.out_data_base + k]
            vslot = self.out_valid_slots[k]
            valid = None if vslot is None else full[vslot]
            stats = (
                in_cols[st.idx].subset_stats()
                if isinstance(st, _StatsMarker)
                else None  # never trust stats minted at trace time
            )
            cols[name] = Column(data, dtype, valid, dic, stats)
        return Table(
            cols, nrows, live=live, unique_key=self._out_unique_key(table)
        )

    def _out_unique_key(self, table: Table):
        """Replay name flow host-side: filters preserve the input's unique
        key; projections keep it only when every key column survives as a
        plain rename (mirrors exec._project_table)."""
        uk = table.unique_key
        names = set(table.columns)
        for s in self.stages:
            if uk is None:
                return None
            if isinstance(s, P.Filter):
                continue
            renames = {}
            for e, name in s.items:
                if isinstance(e, E.Col):
                    key = f"{e.table}.{e.name}" if e.table else e.name
                    if key not in names and e.name in names:
                        key = e.name
                    renames.setdefault(key, name)
            uk = (
                frozenset(renames[k] for k in uk)
                if all(k in renames for k in uk)
                else None
            )
            names = {n for _, n in s.items}
        return uk


def input_signature(table: Table):
    """Hashable identity of an input table's device layout: liveness mode,
    column names, dtypes, validity presence, dictionary identity (codes are
    only meaningful relative to their dictionary, and trace-time lookup
    tables bake it in). Capacity is deliberately absent — jax keys
    executables per shape bucket underneath one traced callable, which is
    exactly the shape-bucketed reuse: a query re-run (same bucket) or a
    structurally identical query at another bucket share the trace."""
    sig = [table.live is not None]
    for name, c in table.columns.items():
        sig.append(
            (
                name,
                repr(c.dtype),
                c.valid is not None,
                id(c.dictionary) if c.dictionary is not None else None,
            )
        )
    return tuple(sig)


class ExecutableCache:
    """Session-level cache of FusedPipeline builds keyed by (pipeline
    structure fingerprint, input signature), with per-(key, bucket)
    hit/miss accounting — the bucket level is where XLA actually compiles.
    Entries pin their dictionaries (see input_signature); a failed build is
    pinned as None so the executor stops re-attempting the fuse. LRU by
    entry count: entries hold host-side trace machinery, not device
    buffers."""

    def __init__(self, max_entries: int = 512):
        self.max_entries = max_entries
        self.map = OrderedDict()  # (fp, sig) -> FusedPipeline | None
        self.buckets = set()  # (fp, sig, cap) already compiled
        self.hits = 0
        self.misses = 0

    def lookup(self, fp, sig, cap, build):
        """(FusedPipeline | None, hit: bool)."""
        key = (fp, sig)
        if key in self.map:
            entry = self.map[key]
            self.map.move_to_end(key)
        else:
            try:
                entry = build()
            except Exception:
                entry = None  # unfusible chain: pin to the eager path
            self.map[key] = entry
            while len(self.map) > self.max_entries:
                old_key, _ = self.map.popitem(last=False)
                self.buckets = {
                    b for b in self.buckets if b[:2] != old_key
                }
        if entry is None:
            return None, False
        bkey = (fp, sig, cap)
        hit = bkey in self.buckets
        if hit:
            self.hits += 1
        else:
            self.misses += 1
            self.buckets.add(bkey)
        return entry, hit

    def clear(self):
        self.map.clear()
        self.buckets.clear()
